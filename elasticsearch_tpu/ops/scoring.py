"""Batched posting-scatter scoring primitives (pure JAX).

These replace the Lucene hot loop the reference runs per shard
(search/query/QueryPhase.java:153 — BulkScorer iterating postings with
BM25 Similarity into TopScoreDocCollector). The TPU formulation is
BM25S-style eager scoring (PAPERS.md): per-posting BM25 impacts are
precomputed at index time, so a query is

    gather posting blocks -> weight -> scatter-add into dense per-doc scores

which is batched over queries ([B, ...]) and vectorized over the 128-lane
posting blocks. On a real TPU backend the executor dispatches these
clause kinds to the fused Pallas kernels in ops/pallas_scoring.py
(one-hot MXU scatter with sorted-range tile skip; tiled forward-index
compare+FMA); these jnp versions are the reference semantics, the CPU
path, and what the kernels are tested against in interpret mode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..index.segment import BLOCK, BM25_K1
from .topk import NEG_INF, running_topk_init, running_topk_merge


def batched_scatter_add(ids: jax.Array, vals: jax.Array, cap: int) -> jax.Array:
    """scores[b, ids[b, n]] += vals[b, n]; ids == cap (or any OOB) dropped.

    ids: int32 [B, N], vals: float32 [B, N] -> [B, cap] float32.
    """

    def one(i, v):
        return jnp.zeros((cap,), jnp.float32).at[i].add(v, mode="drop")

    return jax.vmap(one)(ids, vals)


def gather_term_blocks(block_docs: jax.Array, block_imps: jax.Array,
                       block_lo: jax.Array, nb_valid: jax.Array,
                       nb_pad: int, cap: int) -> tuple[jax.Array, jax.Array]:
    """Gather a term's posting blocks per batched query.

    block_docs/block_imps: [NB, 128] segment posting storage.
    block_lo: [B] first block of this term, nb_valid: [B] how many blocks.
    Returns (docs [B, nb_pad*128] padded with `cap`, imps [B, nb_pad*128]).
    """
    iota = jnp.arange(nb_pad, dtype=jnp.int32)
    idx = block_lo[:, None] + iota[None, :]                   # [B, nb_pad]
    ok = iota[None, :] < nb_valid[:, None]
    safe = jnp.where(ok, idx, 0)
    docs = block_docs[safe]                                   # [B, nb_pad, 128]
    imps = block_imps[safe]
    docs = jnp.where(ok[..., None], docs, cap)                # padded -> dropped
    b = block_lo.shape[0]
    return docs.reshape(b, nb_pad * BLOCK), imps.reshape(b, nb_pad * BLOCK)


def score_term(block_docs: jax.Array, block_imps: jax.Array,
               block_lo: jax.Array, nb_valid: jax.Array, weight: jax.Array,
               nb_pad: int, cap: int) -> jax.Array:
    """Score one text-term clause for a batch of queries -> [B, cap].

    weight multiplies the precomputed BM25 impact (query boost; the idf is
    already inside the impact). score > 0 wherever the term matched, so
    the same array doubles as the match mask (bind clamps weight > 0).
    """
    docs, imps = gather_term_blocks(block_docs, block_imps, block_lo, nb_valid,
                                    nb_pad, cap)
    return batched_scatter_add(docs, imps * weight[:, None], cap)


def gather_fused_blocks(block_docs: jax.Array, block_imps: jax.Array,
                        gather_idx: jax.Array, weights: jax.Array,
                        cap: int) -> tuple[jax.Array, jax.Array]:
    """Gather + weight the blocks of a fused disjunction group.

    gather_idx: [B, M] absolute block indices (-1 = padding);
    weights: [B, M] per-block clause weight.
    Returns (docs [B, M*128] padded with cap, vals [B, M*128]) — the
    single shared preamble for both the jnp and Pallas scatter backends.
    """
    ok = gather_idx >= 0
    safe = jnp.where(ok, gather_idx, 0)
    docs = block_docs[safe]                                   # [B, M, 128]
    imps = block_imps[safe]
    docs = jnp.where(ok[..., None], docs, cap)
    vals = imps * weights[..., None]
    b, m = gather_idx.shape
    return docs.reshape(b, m * BLOCK), vals.reshape(b, m * BLOCK)


def score_terms_fused(block_docs: jax.Array, block_imps: jax.Array,
                      gather_idx: jax.Array, weights: jax.Array,
                      cap: int) -> jax.Array:
    """Score MANY term clauses of one disjunction group in a single scatter.

    Used for `should`-group fusion (a match query's terms all land in one
    scatter) — the common fast path for the http_logs bench query.
    """
    docs, vals = gather_fused_blocks(block_docs, block_imps, gather_idx,
                                     weights, cap)
    return batched_scatter_add(docs, vals, cap)


# ---------------------------------------------------------------------------
# Fused block-max score + top-k (forward-index path)
#
# The unfused pipeline materializes a full [B, cap] score matrix and runs
# lax.top_k over it. The fused pipeline walks SCORE_TILE-doc tiles with a
# fori_loop carrying a running top-k, and uses the pack-time block-max
# summaries (index/segment.build_tile_max) to skip tiles that cannot
# change the result — the block-max WAND idea (arxiv 1910.11028) mapped
# onto dense tiles, generalized to whole bool plans (the BM-WAND family):
# a CLAUSE BUNDLE of must/should scoring clauses plus filter/must_not
# match-mask clauses is evaluated per tile, the tile bound is the sum of
# per-clause block-max bounds, and minimum-should-match-aware pruning
# drops a tile when fewer than msm should clauses can possibly match in
# it. Two prune levels per tile, both decided batch-wide
# (per-lane skipping saves nothing on SIMD hardware):
#
#   hard skip:  no query's bound is > 0 in this tile -> no doc can match;
#               the tile contributes nothing, not even to total hits.
#   threshold:  every query's bound is <= its running k-th best score ->
#               the tile is scored for EXACT hit counting, but the
#               per-tile top-k extraction + merge is skipped.
#
# Tie safety: a tile is threshold-pruned only when each doc's score is
# <= the query's current k-th best, which came from LOWER doc ids
# (tiles run in doc order) — and lax.top_k breaks ties toward the lower
# index, so a tied pruned doc would have lost anyway.
# ---------------------------------------------------------------------------


# relative slack applied to the tile bounds before THRESHOLD compares:
# the bound and the score loops accumulate in the same q order, but the
# compilers (XLA for the bounds, XLA or Mosaic for the scores) may
# contract one side's mul+add into an FMA and not the other's, letting
# a tile's best doc round a few ULPs ABOVE its bound. 32 eps covers any
# realistic query-term count; scores are nonnegative, so scaling the
# bound up only makes pruning more conservative. Hard-skip (ub > 0)
# needs no slack: every per-term product of the bound dominates the
# corresponding per-doc product under monotone f32 rounding, so ub == 0
# forces all doc scores to 0 regardless of contraction.
BOUND_SLACK = 1.0 + 32 * float(jnp.finfo(jnp.float32).eps)


def dense_tile_bounds(tile_max: jax.Array, qt: jax.Array, wq: jax.Array
                      ) -> jax.Array:
    """[T, J] block-max summary x [B, Q] query -> [B, J] score bounds
    (BOUND_SLACK-inflated, see above). Padded/absent terms (qt < 0)
    contribute 0, mirroring their zero-impact matches."""
    b, q_n = qt.shape
    n_tiles = tile_max.shape[1]
    safe = jnp.clip(qt, 0, max(tile_max.shape[0] - 1, 0))
    ub = jnp.zeros((b, n_tiles), jnp.float32)
    for q in range(q_n):
        tm = tile_max[safe[:, q]]                       # [B, J]
        w = jnp.where(qt[:, q] >= 0, wq[:, q], 0.0)
        ub = ub + tm * w[:, None]
    return ub * jnp.float32(BOUND_SLACK)


def _dense_tile_scores(t_tids: jax.Array, t_imps: jax.Array,
                       qt: jax.Array, wq: jax.Array) -> jax.Array:
    """One tile of the forward-index scoring loop: [tile, L] x [B, Q] ->
    [B, tile], with the same reduction order as the unfused jnp path so
    fused and unfused scores are bit-identical."""
    b = qt.shape[0]
    tile = t_tids.shape[0]
    score = jnp.zeros((b, tile), jnp.float32)
    for q in range(qt.shape[1]):
        tq = qt[:, q][:, None, None]                    # [B, 1, 1]
        contrib = jnp.sum(
            jnp.where(t_tids[None] == tq, t_imps[None], 0.0), axis=-1)
        score = score + contrib * wq[:, q][:, None]
    return score


# A clause bundle is a STATIC tuple of clause descriptors
#
#     (role, kind, field, wrapped)
#
# role ∈ {"must", "filter", "must_not", "should"}; kind is a scoring
# dense-text kind ("terms_dense" / "term_text") or a numeric range mask
# ("range_int" / "range_f32", filter/must_not roles only); `wrapped`
# marks a clause that binds as a single-should bool wrapper carrying its
# own dynamic (msm, boost). Clauses MUST be ordered (must, filter,
# must_not, should) with source order preserved inside each role — that
# is eval_node's accumulation order, and reproducing it keeps fused and
# unfused scores bit-identical.
#
# Per-clause dynamic inputs (parallel tuple `cl_inputs`):
#   dense: (qt [B, Q] int32, wq [B, Q] f32, msm_c [B] int32,
#           boost_c [B] f32)  — unwrapped clauses pass msm_c = 1,
#           boost_c = 1.0 (both exact no-ops in f32)
#   range: (lo [B], hi [B]) in the column's device dtype
#
# `text_cols[field]` carries fwd_tids/fwd_imps/tile_max; `num_cols
# [field]` carries values/exists plus the pack-time per-tile extrema
# tile_lo/tile_hi (index/segment.build_tile_minmax) that let range
# filters prune tiles on mask density.

# the ONE definition of which desc kinds are dense scoring clauses vs
# numeric range masks vs vector scoring clauses — the executor's
# admission classifier imports these, so the two layers cannot drift
DENSE_CLAUSE_KINDS = ("terms_dense", "term_text")
RANGE_CLAUSE_KINDS = ("range_int", "range_f32")
# vector similarity as a bundle scoring clause (must/should roles): the
# executor precomputes the whole-capacity similarity column INSIDE the
# fused program (one MXU matmul — search/executor._vec_clause_inputs)
# and the tile walk slices it, so a hybrid BM25+vector bool plan stays
# ONE device dispatch. Per-clause dynamic input:
#   (col [B, cap] f32  — transformed similarity, boost-folded, 0 where
#                        the doc has no vector,
#    exists [cap] bool — the clause's match mask,
#    ub [B, J] f32     — per-tile max of col, BOUND_SLACK-inflated:
#                        an EXACT per-query tile bound, the tile_max
#                        analog computed at query time)
VEC_CLAUSE_KINDS = ("knn_vec",)
_DENSE_KINDS = DENSE_CLAUSE_KINDS
_VEC_KINDS = VEC_CLAUSE_KINDS

# Positional scoring clauses evaluate adjacency over the positions
# column family (index/segment.pack_positions: fwd_pos [cap, L*P]
# int16 per-posting delta lists forward-aligned with the fwd_tids
# slots, plus the pack-time k1ln/lnorm norm columns). The clause
# STATICS ride inside the kind string itself, so clauses with
# different term counts get different trace signatures and are never
# batched together (no padding semantics to define):
#
#   "phrase_pos:{n}:{e|s}"  n-term match_phrase; 'e' = exact
#                           adjacency (slop == 0), 's' = the sloppy
#                           pointer sweep (slop stays DYNAMIC — one
#                           compile serves every slop value)
#   "span_pos:{n}:{o|u}"    span_near over n same-field span_term
#                           children, ordered / unordered
#   "bm25f:{nf}:{nt}"       multi-field multi_match as true BM25F:
#                           nf fields x nt terms, shared idf,
#                           per-field length norms + weights; the
#                           clause's `field` slot holds the TUPLE of
#                           field names
#
# Per-clause dynamic inputs (cl_inputs entry):
#   phrase/span: (qt [B, n] i32, wb [B, n] f32 bound weights
#                 f32(idf_sum / idf_i), idf_sum [B] f32, slop [B] i32,
#                 pboost [B] f32 clause boost, msm_c [B] i32,
#                 boost_c [B] f32 — wrapper dynamics as for dense)
#   bm25f:       (qt [B, nf, nt] i32, idf [B, nt] f32, wf [B, nf] f32,
#                 pboost [B] f32, msm_c [B] i32, boost_c [B] f32)
POSITIONAL_PREFIXES = ("phrase_pos", "span_pos", "bm25f")

# decoded-position pad sentinel: far above any real position
# (POS_MAX_ENC = 32767) yet small enough that sentinel +/- small-int
# arithmetic stays well inside int32
_POS_BIG = 1 << 30


def positional_prefix(kind: str) -> str | None:
    """The positional family of a clause kind, or None for the rest."""
    head = kind.split(":", 1)[0]
    return head if head in POSITIONAL_PREFIXES else None


def phrase_kind(n: int, sloppy: bool) -> str:
    return f"phrase_pos:{n}:{'s' if sloppy else 'e'}"


def span_kind(n: int, in_order: bool) -> str:
    return f"span_pos:{n}:{'o' if in_order else 'u'}"


def bm25f_kind(nf: int, nt: int) -> str:
    return f"bm25f:{nf}:{nt}"


def parse_positional_kind(kind: str) -> tuple[str, int, str]:
    """"head:a:b" -> (head, int(a), b)."""
    head, a, bv = kind.split(":")
    return head, int(a), bv


def clause_fields(field) -> tuple:
    """A clause's fields as a tuple (bm25f stores a field TUPLE in the
    `field` slot; every other kind a single str)."""
    return field if isinstance(field, tuple) else (field,)


def bundle_primary_field(clauses: tuple) -> str:
    """Field of the first dense or positional scoring clause (defines
    the tile grid — every field of a segment shares cap and tile
    size, so any of them pins the same grid)."""
    for _role, kind, field, _w in clauses:
        if kind in _DENSE_KINDS:
            return field
        if positional_prefix(kind):
            return clause_fields(field)[0]
    raise ValueError("bundle has no dense scoring clause")


def bundle_text_fields(clauses: tuple) -> tuple:
    """Fields whose forward text columns (fwd_tids/fwd_imps) the tile
    walk must slice — dense clause fields plus every field of every
    positional clause (the slot compare that locates a term's
    position window reads fwd_tids)."""
    return tuple(dict.fromkeys(
        f for _r, kd, fld, _w in clauses
        if kd in _DENSE_KINDS or positional_prefix(kd)
        for f in clause_fields(fld)))


def bundle_pos_fields(clauses: tuple) -> tuple:
    """Fields whose positions columns (fwd_pos/k1ln/lnorm) the tile
    walk must slice."""
    return tuple(dict.fromkeys(
        f for _r, kd, fld, _w in clauses if positional_prefix(kd)
        for f in clause_fields(fld)))


# ---------------------------------------------------------------------------
# Positional tile evaluation
#
# Device mirrors of search/phrase.py's host loops, restated as fixed-
# shape array programs over one [tile] doc slab. Every op is per-doc
# (elementwise over the doc axis, reductions only over position/term
# axes), so evaluating tile-by-tile is bit-identical to evaluating the
# whole capacity at once — eval_node's unfused reference calls the
# same helpers full-cap. All frequency computations are exact integer
# programs; the single f32 impact formula at the end is shared op for
# op with search/phrase.phrase_impacts, which keeps fused == unfused
# == host-oracle byte identity.
# ---------------------------------------------------------------------------


def _term_positions(t_tids: jax.Array, t_pos: jax.Array, tq: jax.Array
                    ) -> tuple[jax.Array, jax.Array]:
    """Decode one query term's positions for every doc in a tile.

    t_tids [tile, L] slot term ids; t_pos [tile, L*P] int16 delta
    lists (slot l owns columns [l*P, (l+1)*P)); tq [B] query term id.
    Returns (pos [B, tile, P] int32 ascending, pads -> _POS_BIG;
    tf [B, tile] int32 valid-position count). A doc's slots hold
    DISTINCT term ids, so at most one slot matches and a masked max
    over the slot axis selects it without any L-unrolled loop; tq < 0
    (inert padded batch rows) matches nothing — fwd_tids pads are -1,
    hence the explicit tq >= 0 guard."""
    tile, n_slots = t_tids.shape
    p_width = t_pos.shape[1] // n_slots
    pos3 = t_pos.reshape(tile, n_slots, p_width)
    hit = (t_tids[None] == tq[:, None, None]) \
        & (tq >= 0)[:, None, None]                       # [B, tile, L]
    enc = jnp.where(hit[..., None], pos3[None],
                    jnp.int16(-1)).max(axis=2)           # [B, tile, P]
    valid = enc >= 0
    pos = jnp.cumsum(jnp.where(valid, enc.astype(jnp.int32), 0), axis=-1)
    pos = jnp.where(valid, pos, _POS_BIG)
    return pos, valid.sum(axis=-1, dtype=jnp.int32)


def _phrase_freq_exact(pos: jax.Array, tf: jax.Array) -> jax.Array:
    """Exact-adjacency phrase frequency (host mirror: phrase_match's
    slop <= 0 branch — a start p survives iff term i occurs at p + i).

    pos [B, tile, n, P], tf [B, tile, n] -> freq [B, tile] i32.
    Pad starts (_POS_BIG) self-eliminate for n >= 2: _POS_BIG + i
    equals neither a real position nor _POS_BIG."""
    n = pos.shape[2]
    if n == 1:
        return tf[..., 0]
    starts = pos[:, :, 0, :]                             # [B, tile, P]
    alive = starts < _POS_BIG
    for i in range(1, n):
        member = jnp.any(
            pos[:, :, i, None, :] == (starts + i)[..., :, None], axis=-1)
        alive = alive & member
    return alive.sum(axis=-1, dtype=jnp.int32)


def _phrase_freq_sloppy(pos: jax.Array, tf: jax.Array, slop: jax.Array
                        ) -> jax.Array:
    """Sloppy phrase frequency — the _sloppy_match pointer sweep run
    for all docs in lockstep: n*P fixed iterations, each testing the
    current window (min/max of the n adjusted head positions, repeats
    must land on distinct raw tokens) and advancing the FIRST pointer
    holding the minimum (host `vals.index(lo)`; jnp.argmin breaks
    ties to the first index identically). Docs whose sweep finishes
    early go inactive (`ptr < tf` fails) and simply stop counting —
    the remaining iterations are no-ops for them, so the final count
    equals the host loop's."""
    b, tile, n, p_width = pos.shape
    adj = pos - jnp.arange(n, dtype=jnp.int32)[None, None, :, None]

    def body(_it, st):
        ptr, freq = st
        safe = jnp.clip(ptr, 0, p_width - 1)
        vals = jnp.take_along_axis(adj, safe[..., None], axis=-1)[..., 0]
        active = jnp.all(ptr < tf, axis=-1)              # [B, tile]
        lo = vals.min(axis=-1)
        hi = vals.max(axis=-1)
        raw = vals + jnp.arange(n, dtype=jnp.int32)[None, None, :]
        distinct = jnp.ones((b, tile), bool)
        for i in range(n):
            for j in range(i + 1, n):
                distinct = distinct & (raw[..., i] != raw[..., j])
        hit = active & ((hi - lo) <= slop[:, None]) & distinct
        freq = freq + hit.astype(jnp.int32)
        amin = jnp.argmin(vals, axis=-1)
        adv = jnp.arange(n, dtype=jnp.int32)[None, None, :] \
            == amin[..., None]
        ptr = ptr + jnp.where(active[..., None] & adv, 1, 0)
        return ptr, freq

    st0 = (jnp.zeros((b, tile, n), jnp.int32),
           jnp.zeros((b, tile), jnp.int32))
    _ptr, freq = jax.lax.fori_loop(0, n * p_width, body, st0)
    return freq


def _span_freq_ordered(pos: jax.Array, tf: jax.Array, slop: jax.Array
                       ) -> jax.Array:
    """Ordered span_near frequency over n width-1 children (host
    mirror: _near_ordered + the set dedupe of envelopes).

    The host recursion emits DISTINCT envelopes (first_start,
    prev_end); with width-1 children an envelope is a (p0, pl) pair
    with p0 in A_0, pl in A_{n-1}, and SOME ascending chain through
    A_1..A_{n-2}. A chain exists iff the GREEDY minimal chain fits
    under pl: x_1 = min{p in A_1 : p >= p0 + 1}, x_{i+1} likewise
    above x_i; pl must exceed x_{n-2}. The window test is the host's
    gap = (pl + 1 - p0) - n <= slop (every child has length 1, so
    len_sum == n). Pads: a _POS_BIG p0 makes every x and the pl > x
    test fail; a _POS_BIG pl fails the window test (slop is a real
    query int, far below the sentinel)."""
    n = pos.shape[2]
    if n == 1:
        return tf[..., 0]
    p0 = pos[:, :, 0, :]                                 # [B, tile, P]
    x = p0
    for i in range(1, n - 1):
        ai = pos[:, :, i, :]
        cand = jnp.where(ai[:, :, None, :] >= x[..., :, None] + 1,
                         ai[:, :, None, :], _POS_BIG)    # [B,tile,P0,P]
        x = cand.min(axis=-1)
    pl = pos[:, :, n - 1, :]
    ok = (pl[:, :, None, :] > x[..., :, None]) \
        & (((pl[:, :, None, :] + 1 - p0[..., :, None]) - n)
           <= slop[:, None, None, None])
    return ok.sum(axis=(-1, -2), dtype=jnp.int32)


def _span_freq_unordered(pos: jax.Array, tf: jax.Array, slop: jax.Array
                         ) -> jax.Array:
    """Unordered span_near frequency over n width-1 children (host
    mirror: _near_unordered + its set dedupe). Pointer sweep: window
    (min start, max start + 1) tested against (hi - lo) - n <= slop,
    then the first pointer holding the earliest start advances. The
    host dedupes via a set; here duplicates of an emitted (lo, hi)
    are provably ADJACENT among emissions (lo is non-decreasing over
    the sweep, and within equal lo the emitted hi is non-decreasing),
    so comparing against the last emitted pair counts exactly the
    distinct windows."""
    b, tile, n, p_width = pos.shape
    if n == 1:
        return tf[..., 0]

    def body(_it, st):
        ptr, freq, last_lo, last_hi = st
        safe = jnp.clip(ptr, 0, p_width - 1)
        starts = jnp.take_along_axis(pos, safe[..., None], axis=-1)[..., 0]
        active = jnp.all(ptr < tf, axis=-1)
        lo = starts.min(axis=-1)
        hi = starts.max(axis=-1) + 1
        win = active & (((hi - lo) - n) <= slop[:, None])
        new = win & ((lo != last_lo) | (hi != last_hi))
        freq = freq + new.astype(jnp.int32)
        last_lo = jnp.where(win, lo, last_lo)
        last_hi = jnp.where(win, hi, last_hi)
        amin = jnp.argmin(starts, axis=-1)
        adv = jnp.arange(n, dtype=jnp.int32)[None, None, :] \
            == amin[..., None]
        ptr = ptr + jnp.where(active[..., None] & adv, 1, 0)
        return ptr, freq, last_lo, last_hi

    st0 = (jnp.zeros((b, tile, n), jnp.int32),
           jnp.zeros((b, tile), jnp.int32),
           jnp.full((b, tile), -1, jnp.int32),
           jnp.full((b, tile), -1, jnp.int32))
    _ptr, freq, _ll, _lh = jax.lax.fori_loop(0, n * p_width, body, st0)
    return freq


def positional_tile_freqs(kind: str, qt: jax.Array, slop: jax.Array,
                          t_tids: jax.Array, t_pos: jax.Array
                          ) -> jax.Array:
    """Phrase/span occurrence counts for one doc tile -> [B, tile]
    i32. kind selects the algorithm (see POSITIONAL_PREFIXES)."""
    head, n, variant = parse_positional_kind(kind)
    per = [_term_positions(t_tids, t_pos, qt[:, i]) for i in range(n)]
    pos = jnp.stack([p for p, _t in per], axis=2)        # [B,tile,n,P]
    tf = jnp.stack([t for _p, t in per], axis=2)         # [B,tile,n]
    if head == "phrase_pos":
        if variant == "e":
            return _phrase_freq_exact(pos, tf)
        return _phrase_freq_sloppy(pos, tf, slop)
    if variant == "o":
        return _span_freq_ordered(pos, tf, slop)
    return _span_freq_unordered(pos, tf, slop)


def positional_impacts(freq: jax.Array, idf_sum: jax.Array,
                       k1ln: jax.Array) -> jax.Array:
    """Phrase frequency -> BM25 impact, op for op the f32 chain of
    search/phrase.phrase_impacts (the byte-identity oracle): freq == 0
    falls out as 0 / (0 + k1ln) = 0 with no masking (k1ln > 0 by
    construction). freq [B, tile] i32, idf_sum [B] f32, k1ln [tile]
    f32 (the pack-time k1 * lnorm column — packed as its own column
    precisely so no compiler can contract a tf + k1*lnorm mul-add
    into an FMA and break host/device identity)."""
    tf32 = freq.astype(jnp.float32)
    num = (idf_sum[:, None] * tf32) * jnp.float32(BM25_K1 + 1.0)
    return num / (tf32 + k1ln[None, :])


def bm25f_tile_scores(fields: tuple, qt: jax.Array, idf: jax.Array,
                      wf: jax.Array, text_tiles: dict, pos_tiles: dict
                      ) -> jax.Array:
    """BM25F scores for one doc tile -> [B, tile] f32, op for op the
    host oracle search/phrase.bm25f_scores (field-then-term f32
    accumulation). Per-field tf comes from the positions column's
    valid-count — identical to the host's pf.tfs because the pack
    stores every occurrence (pos_pack_width admits a field only when
    max tf <= POS_CAP)."""
    b = qt.shape[0]
    nf, nt = qt.shape[1], qt.shape[2]
    tile = pos_tiles[fields[0]][2].shape[0]
    k1_32 = jnp.float32(BM25_K1)
    total = jnp.zeros((b, tile), jnp.float32)
    for ti in range(nt):
        acc = jnp.zeros((b, tile), jnp.float32)
        for fi in range(nf):
            f = fields[fi]
            t_tids, _t_imps = text_tiles[f]
            t_pos, _k1ln, lnorm = pos_tiles[f]
            _pos, tf = _term_positions(t_tids, t_pos, qt[:, fi, ti])
            acc = acc + (wf[:, fi, None] * tf.astype(jnp.float32)) \
                / lnorm[None, :]
        total = total + (idf[:, ti, None] * acc) / (k1_32 + acc)
    return total


def positional_tile_scores(kind: str, field, inp: tuple,
                           text_tiles: dict, pos_tiles: dict
                           ) -> tuple[jax.Array, jax.Array]:
    """(s_leaf [B, tile] f32 with the clause boost applied, m_leaf
    [B, tile] bool) for one positional clause over one doc tile —
    the shared leaf evaluator of bundle_tile_eval, the Pallas kernel
    (interpret reference), and eval_node's unfused path."""
    if positional_prefix(kind) == "bm25f":
        qt, idf, wf, pboost, _msm_c, _boost_c = inp
        raw = bm25f_tile_scores(field, qt, idf, wf, text_tiles,
                                pos_tiles)
        return raw * pboost[:, None], raw > 0.0
    qt, _wb, idf_sum, slop, pboost, _msm_c, _boost_c = inp
    t_tids, _t_imps = text_tiles[field]
    t_pos, k1ln, _lnorm = pos_tiles[field]
    freq = positional_tile_freqs(kind, qt, slop, t_tids, t_pos)
    raw = positional_impacts(freq, idf_sum, k1ln)
    return raw * pboost[:, None], freq > 0


def bundle_tile_bounds(clauses: tuple, cl_inputs: tuple, text_cols: dict,
                       num_cols: dict, msm: jax.Array,
                       boost: jax.Array | None
                       ) -> tuple[jax.Array, jax.Array]:
    """Per-tile (can_match [B, J] bool, score bound [B, J] f32) for a
    clause bundle.

    can_match is msm-aware: a tile is matchable only when every
    must/filter clause can possibly match in it (dense: positive bound;
    range: [tile_lo, tile_hi] overlaps [lo, hi]) AND at least msm should
    clauses can. The bound sums the boost-weighted per-clause block-max
    bounds of the scoring clauses (must + should) — a monotone upper
    bound on any doc's post-boost score — and is BOUND_SLACK-inflated
    once more on top of the per-clause inflation to absorb the extra
    adds/muls of the multi-clause combine."""
    b = msm.shape[0]
    n_tiles = text_cols[bundle_primary_field(clauses)]["tile_max"].shape[1]
    bound = jnp.zeros((b, n_tiles), jnp.float32)
    possible = jnp.ones((b, n_tiles), bool)
    pos_cnt = jnp.zeros((b, n_tiles), jnp.int32)
    for (role, kind, field, _w), inp in zip(clauses, cl_inputs):
        head = positional_prefix(kind)
        if kind in _DENSE_KINDS:
            qt, wq, msm_c, boost_c = inp
            ub = dense_tile_bounds(text_cols[field]["tile_max"], qt, wq)
            p = ((ub > 0.0) | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
            if role in ("must", "should"):
                bound = bound + ub * boost_c[:, None]
            if role in ("must", "filter"):
                possible = possible & p
            elif role == "should":
                pos_cnt = pos_cnt + p.astype(jnp.int32)
        elif head in ("phrase_pos", "span_pos"):
            # position-BLIND bound (the tiered pager's host mirror
            # must stay exact without fetching a single tile): a tile
            # missing ANY required term can't match a phrase/span
            # (presence gate, exact: tile_max > 0 iff the term occurs
            # there); a present tile's phrase impact is bounded by
            # Sum_i (idf_sum/idf_i) * tile_max_i — phrase freq <= the
            # pointer sweep's iteration count <= Sum_i tf_i, and the
            # saturation tf/(tf + k1ln) is concave-subadditive, so
            # idf_sum*k1p1*satur(freq) <= Sum_i idf_sum*k1p1*
            # satur(tf_i) = Sum_i wb_i * impact_i. Ordered span freq
            # counts (start, end) PAIRS and can exceed Sum tf_i, so it
            # takes the flat satur < 1 bound idf_sum * (k1 + 1)
            # instead. BOUND_SLACK absorbs the f32 rounding of either
            # chain (real margins dwarf 32 eps: satur's distance from
            # 1 is >= ~1e-4 at POS_CAP'd tfs).
            qt, wb, idf_sum, _slop, pboost, msm_c, boost_c = inp
            tm = text_cols[field]["tile_max"]
            safe = jnp.clip(qt, 0, max(tm.shape[0] - 1, 0))
            pres = jnp.ones((b, n_tiles), bool)
            for i in range(qt.shape[1]):
                pres = pres & (tm[safe[:, i]] > 0.0) \
                    & (qt[:, i] >= 0)[:, None]
            if kind.endswith(":o"):
                ub = jnp.broadcast_to(
                    (idf_sum * jnp.float32(BM25_K1 + 1.0)
                     * jnp.float32(BOUND_SLACK))[:, None], (b, n_tiles))
            else:
                ub = dense_tile_bounds(tm, qt, wb)
            ub = jnp.where(pres, ub, 0.0) * pboost[:, None]
            p = (pres | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
            if role in ("must", "should"):
                bound = bound + ub * boost_c[:, None]
            if role in ("must", "filter"):
                possible = possible & p
            elif role == "should":
                pos_cnt = pos_cnt + p.astype(jnp.int32)
        elif head == "bm25f":
            # per-term any-field presence; a present term's saturated
            # contribution idf_t * acc / (k1 + acc) is < idf_t, so the
            # tile bound is the presence-gated idf sum
            qt, idf, _wf, pboost, msm_c, boost_c = inp
            nf, nt = qt.shape[1], qt.shape[2]
            ub = jnp.zeros((b, n_tiles), jnp.float32)
            p_any = jnp.zeros((b, n_tiles), bool)
            for t in range(nt):
                pres_t = jnp.zeros((b, n_tiles), bool)
                for fi in range(nf):
                    tm = text_cols[field[fi]]["tile_max"]
                    safe = jnp.clip(qt[:, fi, t], 0,
                                    max(tm.shape[0] - 1, 0))
                    pres_t = pres_t | ((tm[safe] > 0.0)
                                       & (qt[:, fi, t] >= 0)[:, None])
                ub = ub + jnp.where(pres_t, idf[:, t][:, None], 0.0)
                p_any = p_any | pres_t
            ub = ub * jnp.float32(BOUND_SLACK) * pboost[:, None]
            p = (p_any | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
            if role in ("must", "should"):
                bound = bound + ub * boost_c[:, None]
            if role in ("must", "filter"):
                possible = possible & p
            elif role == "should":
                pos_cnt = pos_cnt + p.astype(jnp.int32)
        elif kind in _VEC_KINDS:
            # vector clause: the executor supplies the EXACT per-tile
            # bound (max of the similarity column, slack-inflated);
            # can-match is "some doc in the tile carries a vector"
            _col, v_exists, ub = inp
            tile = v_exists.shape[0] // n_tiles
            p = jnp.broadcast_to(
                v_exists.reshape(n_tiles, tile).any(axis=1)[None, :],
                (b, n_tiles))
            bound = bound + ub
            if role == "must":
                possible = possible & p
            else:                           # should
                pos_cnt = pos_cnt + p.astype(jnp.int32)
        elif role != "must_not":            # range mask (no bound to
            lo, hi = inp                    # prune on for exclusions)
            tl = num_cols[field]["tile_lo"]
            th = num_cols[field]["tile_hi"]
            possible = possible & ((tl[None, :] <= hi[:, None])
                                   & (th[None, :] >= lo[:, None]))
    can_match = possible & (pos_cnt >= msm[:, None])
    if boost is not None:
        bound = bound * boost[:, None]
    # combine slack, sign-guarded: dense/range bounds are nonnegative
    # (identical behavior), but a vector clause's bound can be
    # negative (dot_product on non-unit vectors) — scaling a negative
    # total up would lower it below the true tile max
    return can_match, jnp.where(bound >= 0.0,
                                bound * jnp.float32(BOUND_SLACK),
                                bound / jnp.float32(BOUND_SLACK))


def bundle_tile_bounds_np(clauses: tuple, cl_inputs: tuple,
                          text_tile_max: dict, num_extrema: dict,
                          msm: np.ndarray, boost: np.ndarray | None
                          ) -> tuple[np.ndarray, np.ndarray]:
    """HOST mirror of bundle_tile_bounds — the tiered-residency pager's
    survivor oracle (index/tiering.py): it must decide, BEFORE any tile
    is fetched, exactly which tiles the device walk could possibly
    match in. Keep op-for-op in lockstep with bundle_tile_bounds above.

    Exactness of the can_match half (the only half correctness rides
    on): per-clause ub sums nonnegative f32 products, so `ub > 0` is
    order-independent and bit-agrees with any compilation of the device
    sum — a product is positive iff both factors are (identical IEEE
    semantics host and device, including underflow-to-zero), and
    nonnegative addends cannot cancel. Range-overlap and msm tests are
    exact integer/ordered comparisons on the same build_tile_minmax
    numbers the device reads. The bound half inherits the same
    BOUND_SLACK inflation and is advisory (fetch ordering), never a
    correctness input."""
    b = msm.shape[0]
    field0 = bundle_primary_field(clauses)
    n_tiles = text_tile_max[field0].shape[1]
    bound = np.zeros((b, n_tiles), np.float32)
    possible = np.ones((b, n_tiles), bool)
    pos_cnt = np.zeros((b, n_tiles), np.int32)
    for (role, kind, field, _w), inp in zip(clauses, cl_inputs):
        head = positional_prefix(kind)
        if kind in _VEC_KINDS:
            # the vector clause's bound is a DEVICE product (the
            # similarity column matmul) — there is nothing to mirror
            # host-side, so the tiered pager must decline knn bundles
            # (executor admission does; this is the backstop)
            raise ValueError("knn_vec bundles have no host bound mirror")
        if head in ("phrase_pos", "span_pos"):
            # position-BLIND by design (see bundle_tile_bounds): the
            # presence gate reads only tile_max, which the pager holds
            # resident — no position tile is touched before the
            # survivor decision, and the exactness argument is the
            # dense one (tile_max > 0 is order-independent in f32)
            qt, wb, idf_sum, _slop, pboost, msm_c, boost_c = (
                np.asarray(x) for x in inp)
            tm = text_tile_max[field]
            safe = np.clip(qt, 0, max(tm.shape[0] - 1, 0))
            pres = np.ones((b, n_tiles), bool)
            for i in range(qt.shape[1]):
                pres = pres & (tm[safe[:, i]] > 0.0) \
                    & (qt[:, i] >= 0)[:, None]
            if kind.endswith(":o"):
                ub = np.broadcast_to(
                    (idf_sum.astype(np.float32)
                     * np.float32(BM25_K1 + 1.0)
                     * np.float32(BOUND_SLACK))[:, None],
                    (b, n_tiles)).astype(np.float32)
            else:
                ub = np.zeros((b, n_tiles), np.float32)
                for i in range(qt.shape[1]):
                    w = np.where(qt[:, i] >= 0, wb[:, i],
                                 np.float32(0.0)).astype(np.float32)
                    ub = ub + tm[safe[:, i]] * w[:, None]
                ub = ub * np.float32(BOUND_SLACK)
            ub = np.where(pres, ub, np.float32(0.0)) \
                * pboost[:, None].astype(np.float32)
            p = (pres | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
            if role in ("must", "should"):
                bound = bound + ub * boost_c[:, None].astype(np.float32)
            if role in ("must", "filter"):
                possible = possible & p
            elif role == "should":
                pos_cnt = pos_cnt + p.astype(np.int32)
            continue
        if head == "bm25f":
            qt, idf, _wf, pboost, msm_c, boost_c = (
                np.asarray(x) for x in inp)
            nf, nt = qt.shape[1], qt.shape[2]
            ub = np.zeros((b, n_tiles), np.float32)
            p_any = np.zeros((b, n_tiles), bool)
            for t in range(nt):
                pres_t = np.zeros((b, n_tiles), bool)
                for fi in range(nf):
                    tm = text_tile_max[field[fi]]
                    safe = np.clip(qt[:, fi, t], 0,
                                   max(tm.shape[0] - 1, 0))
                    pres_t = pres_t | ((tm[safe] > 0.0)
                                       & (qt[:, fi, t] >= 0)[:, None])
                ub = ub + np.where(pres_t, idf[:, t][:, None],
                                   np.float32(0.0))
                p_any = p_any | pres_t
            ub = (ub * np.float32(BOUND_SLACK)
                  * pboost[:, None].astype(np.float32))
            p = (p_any | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
            if role in ("must", "should"):
                bound = bound + ub * boost_c[:, None].astype(np.float32)
            if role in ("must", "filter"):
                possible = possible & p
            elif role == "should":
                pos_cnt = pos_cnt + p.astype(np.int32)
            continue
        if kind in _DENSE_KINDS:
            qt, wq, msm_c, boost_c = (np.asarray(x) for x in inp)
            tm = text_tile_max[field]
            safe = np.clip(qt, 0, max(tm.shape[0] - 1, 0))
            ub = np.zeros((b, n_tiles), np.float32)
            for q in range(qt.shape[1]):
                w = np.where(qt[:, q] >= 0, wq[:, q],
                             np.float32(0.0)).astype(np.float32)
                ub = ub + tm[safe[:, q]] * w[:, None]
            ub = ub * np.float32(BOUND_SLACK)
            p = ((ub > 0.0) | (msm_c <= 0)[:, None]) \
                & (msm_c <= 1)[:, None]
            if role in ("must", "should"):
                bound = bound + ub * boost_c[:, None].astype(np.float32)
            if role in ("must", "filter"):
                possible = possible & p
            elif role == "should":
                pos_cnt = pos_cnt + p.astype(np.int32)
        elif role != "must_not":
            lo, hi = (np.asarray(x) for x in inp)
            tl, th = num_extrema[field]
            possible = possible & ((tl[None, :] <= hi[:, None])
                                   & (th[None, :] >= lo[:, None]))
    can_match = possible & (pos_cnt >= np.asarray(msm)[:, None])
    if boost is not None:
        bound = bound * np.asarray(boost)[:, None].astype(np.float32)
    # sign-guarded combine slack — kept op-for-op with the device
    # version above (a no-op for the nonnegative dense/range bounds
    # this mirror actually serves; knn bundles raise earlier)
    return can_match, np.where(bound >= 0.0,
                               bound * np.float32(BOUND_SLACK),
                               bound / np.float32(BOUND_SLACK)
                               ).astype(np.float32)


def bundle_tile_eval(clauses: tuple, cl_inputs: tuple, text_tiles: dict,
                     num_tiles: dict, msm: jax.Array,
                     boost: jax.Array | None, t_live: jax.Array,
                     vec_tiles: dict | None = None,
                     pos_tiles: dict | None = None
                     ) -> tuple[jax.Array, jax.Array]:
    """Evaluate a clause bundle over one doc tile -> (score [B, tile]
    post-boost, match [B, tile] incl. live). Accumulation mirrors
    eval_node's bool branch op for op (must scores, then should scores;
    where-masked adds; nested wrapper boost before the parent add; outer
    boost last) so scores stay bit-identical to the unfused path.
    `vec_tiles[ci]` = (col [B, tile], exists [tile]) — this tile's
    slice of clause ci's precomputed similarity column (same numbers
    eval_node's knn_vec leaf reads, so hybrid scores stay identical).
    `pos_tiles[field]` = (t_pos [tile, L*P], k1ln [tile], lnorm
    [tile]) — this tile's slice of the positions column family, for
    the positional clause kinds."""
    b = msm.shape[0]
    tile = t_live.shape[0]
    score = jnp.zeros((b, tile), jnp.float32)
    must_ok = jnp.ones((b, tile), bool)
    not_any = jnp.zeros((b, tile), bool)
    cnt = jnp.zeros((b, tile), jnp.int32)
    for ci, ((role, kind, field, _w), inp) in enumerate(
            zip(clauses, cl_inputs)):
        if kind in _DENSE_KINDS:
            qt, wq, msm_c, boost_c = inp
            t_tids, t_imps = text_tiles[field]
            s_leaf = _dense_tile_scores(t_tids, t_imps, qt, wq)
            m_leaf = s_leaf > 0.0
            # single-should wrapper semantics (exact: for unwrapped
            # clauses msm_c = 1 / boost_c = 1 reduce to m_leaf / s_leaf)
            m = (m_leaf | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
            s = jnp.where(m_leaf, s_leaf, 0.0) * boost_c[:, None]
        elif positional_prefix(kind):
            s_leaf, m_leaf = positional_tile_scores(
                kind, field, inp, text_tiles, pos_tiles)
            msm_c, boost_c = inp[-2], inp[-1]
            m = (m_leaf | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
            s = jnp.where(m_leaf, s_leaf, 0.0) * boost_c[:, None]
        elif kind in _VEC_KINDS:
            t_col, t_exists = vec_tiles[ci]
            m = jnp.broadcast_to(t_exists[None, :], (b, tile))
            s = t_col                        # boost already folded in
        else:
            lo, hi = inp
            t_vals, t_exists = num_tiles[field]
            m = ((t_vals[None, :] >= lo[:, None])
                 & (t_vals[None, :] <= hi[:, None]) & t_exists[None, :])
            s = None                         # mask-only roles
        if role == "must":
            score = score + jnp.where(m, s, 0.0)
            must_ok = must_ok & m
        elif role == "filter":
            must_ok = must_ok & m
        elif role == "must_not":
            not_any = not_any | m
        else:
            score = score + jnp.where(m, s, 0.0)
            cnt = cnt + m.astype(jnp.int32)
    match = must_ok & (~not_any) & (cnt >= msm[:, None]) & t_live[None, :]
    if boost is not None:
        score = score * boost[:, None]
    return score, match


def bundle_tile_match(clauses: tuple, cl_inputs: tuple, text_tiles: dict,
                      num_tiles: dict, msm: jax.Array, t_live: jax.Array,
                      vec_tiles: dict | None = None,
                      pos_tiles: dict | None = None) -> jax.Array:
    """Mask-only bundle_tile_eval: the match mask [B, tile] of one doc
    tile WITHOUT the weighted score accumulation — the k == 0
    (filtered / size-0 agg) pass, where the score matrix is never
    consumed.

    Exactness: a dense clause's unfused match is `score > 0`, where
    score sums (impact * weight) over the doc's matching term slots.
    Impacts of real postings are strictly positive (BM25 idf > 0,
    tf-norm > 0) and clause weights are clamped positive at bind time,
    so `score > 0` is EQUIVALENT to "some query term (qt >= 0) is
    present in a slot with positive impact" — which is what this
    membership test computes, minus the FMA work."""
    b = msm.shape[0]
    tile = t_live.shape[0]
    must_ok = jnp.ones((b, tile), bool)
    not_any = jnp.zeros((b, tile), bool)
    cnt = jnp.zeros((b, tile), jnp.int32)
    for ci, ((role, kind, field, _w), inp) in enumerate(
            zip(clauses, cl_inputs)):
        if kind in _VEC_KINDS:
            _t_col, t_exists = vec_tiles[ci]
            m = jnp.broadcast_to(t_exists[None, :], (b, tile))
            if role in ("must", "filter"):
                must_ok = must_ok & m
            elif role == "must_not":
                not_any = not_any | m
            else:
                cnt = cnt + m.astype(jnp.int32)
            continue
        head = positional_prefix(kind)
        if head == "bm25f":
            # bm25f match is `score > 0`, and a term's saturated
            # contribution is positive iff some field carries the term
            # with a positive tf (weights/idf are bind-clamped > 0) —
            # so the mask is the dense membership test OR-reduced over
            # (field, term), no position decode needed
            qt, _idf, _wf, _pboost, msm_c, _boost_c = inp
            nf, nt = qt.shape[1], qt.shape[2]
            m_leaf = jnp.zeros((b, tile), bool)
            for fi in range(nf):
                t_tids, t_imps = text_tiles[field[fi]]
                present = t_imps > 0.0
                for t in range(nt):
                    tq = qt[:, fi, t][:, None, None]
                    hit = jnp.any((t_tids[None] == tq) & present[None],
                                  axis=-1)
                    m_leaf = m_leaf | (hit
                                       & (qt[:, fi, t] >= 0)[:, None])
            m = (m_leaf | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
        elif head:
            # phrase/span match requires the occurrence count — there
            # is no cheaper exact test than running the adjacency
            qt, _wb, _idf_sum, slop, _pb, msm_c, _boost_c = inp
            t_tids, _t_imps = text_tiles[field]
            t_pos, _k1ln, _lnorm = pos_tiles[field]
            freq = positional_tile_freqs(kind, qt, slop, t_tids, t_pos)
            m_leaf = freq > 0
            m = (m_leaf | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
        elif kind in _DENSE_KINDS:
            qt, _wq, msm_c, _boost_c = inp
            t_tids, t_imps = text_tiles[field]
            present = t_imps > 0.0                   # [tile, L]
            m_leaf = jnp.zeros((b, tile), bool)
            for q in range(qt.shape[1]):
                tq = qt[:, q][:, None, None]         # [B, 1, 1]
                hit = jnp.any((t_tids[None] == tq) & present[None],
                              axis=-1)
                m_leaf = m_leaf | (hit & (qt[:, q] >= 0)[:, None])
            # single-should wrapper semantics (see bundle_tile_eval)
            m = (m_leaf | (msm_c <= 0)[:, None]) & (msm_c <= 1)[:, None]
        else:
            lo, hi = inp
            t_vals, t_exists = num_tiles[field]
            m = ((t_vals[None, :] >= lo[:, None])
                 & (t_vals[None, :] <= hi[:, None]) & t_exists[None, :])
        if role in ("must", "filter"):
            must_ok = must_ok & m
        elif role == "must_not":
            not_any = not_any | m
        else:
            cnt = cnt + m.astype(jnp.int32)
    return must_ok & (~not_any) & (cnt >= msm[:, None]) & t_live[None, :]


# ---------------------------------------------------------------------------
# Stepped tile loop (resident query loop, see search/resident.py)
#
# A `step` argument — (chunk_tiles, init_state, check) — reshapes the
# single fori_loop over tiles into an outer loop over CHUNKS of
# chunk_tiles tiles. `check(chunk_idx, state) -> (timed_out, state)`
# runs once per chunk (the executor wires an io_callback that polls the
# host clock against the dispatch deadline and meters injected
# straggler delay); once it reports timed_out the remaining chunks'
# tile work is skipped entirely, so a laggard step EXITS EARLY instead
# of burning the rest of its tile walk — the preemptive device-side
# timeout. With step=None the original single loop runs: the composed
# chunked loop visits tiles in the identical order, so un-timed results
# are bit-identical either way. The Pallas engine honors the SAME step
# contract (ops/pallas_scoring.fused_topk_bundle_pallas /
# match_mask_bundle_pallas): there the chunks are separate pallas_call
# invocations with the running threshold threaded through a [B, 1]
# in/out pair, and `check` runs between kernels — one contract, two
# engines, so the resident loop and the mesh swap engines freely.
# ---------------------------------------------------------------------------


def _stepped_tile_loop(n_tiles: int, body, st0, step):
    """fori(0, n_tiles, body, st0), optionally chunked with a per-chunk
    step check. Returns (state, timed_out bool scalar | None)."""
    if step is None:
        return jax.lax.fori_loop(0, n_tiles, body, st0), None
    chunk_tiles, ck0, check = step
    n_chunks = -(-n_tiles // chunk_tiles)

    def chunk_body(c, outer):
        st, ck, _t = outer
        timed, ck = check(c, ck)
        st = jax.lax.cond(
            timed, lambda s: s,
            lambda s: jax.lax.fori_loop(
                c * chunk_tiles,
                jnp.minimum((c + 1) * chunk_tiles, n_tiles), body, s),
            st)
        return st, ck, timed

    st, ck, timed = jax.lax.fori_loop(
        0, n_chunks, chunk_body, (st0, ck0, jnp.bool_(False)))
    # one FINAL check after the last chunk: a deadline expiring during
    # the last chunk's work (or the only chunk's, at n_chunks == 1)
    # must still report timed_out — the resident caller skips the
    # cooperative collect-boundary check on the strength of this
    # verdict, so the device must cover the whole walk, not all-but-
    # the-end of it
    final, _ck = check(n_chunks, ck)
    return st, timed | final


def match_mask_bundle_fused(text_cols: dict, num_cols: dict,
                            clauses: tuple, cl_inputs: tuple,
                            msm: jax.Array, boost: jax.Array | None,
                            live: jax.Array, emit_match: bool = True,
                            step=None):
    """Fused match-mask-only pass over a clause bundle — the k == 0
    engine (size-0 counts and filtered aggregation plans), which skips
    the score matrix AND the top-k selection entirely.

    Returns (total [B] int32, prune_stats int32 [3] = (hard_skipped,
    0, tiles_examined)) plus, when emit_match, the exact match mask
    [B, cap] bool for a downstream aggregation pass. Hard-skipping on
    the msm-aware can_match is exact: a skipped tile provably contains
    no matching doc, so its mask rows stay zero. A `step` (see
    _stepped_tile_loop) appends the timed_out scalar to the result."""
    field0 = bundle_primary_field(clauses)
    n_tiles = text_cols[field0]["tile_max"].shape[1]
    cap = live.shape[0]
    tile = cap // n_tiles
    b = msm.shape[0]
    can_match, _ub = bundle_tile_bounds(clauses, cl_inputs, text_cols,
                                        num_cols, msm, boost)
    text_fields = bundle_text_fields(clauses)
    pos_fields = bundle_pos_fields(clauses)
    num_fields = tuple(dict.fromkeys(
        f for _r, kd, f, _w in clauses if kd in RANGE_CLAUSE_KINDS))
    vec_idx = tuple(i for i, (_r, kd, _f, _w) in enumerate(clauses)
                    if kd in _VEC_KINDS)

    def body(j, st):
        lo = j * tile
        can_j = jax.lax.dynamic_slice_in_dim(can_match, j, 1, axis=1)[:, 0]

        def hard_skip(st):
            return (st[0], st[1] + jnp.array([1, 0, 1], jnp.int32)) + st[2:]

        def eval_tile(st):
            total, pruned = st[:2]
            text_tiles = {
                f: (jax.lax.dynamic_slice(
                        text_cols[f]["fwd_tids"], (lo, 0),
                        (tile, text_cols[f]["fwd_tids"].shape[1])),
                    jax.lax.dynamic_slice(
                        text_cols[f]["fwd_imps"], (lo, 0),
                        (tile, text_cols[f]["fwd_imps"].shape[1])))
                for f in text_fields}
            pos_tiles = {
                f: (jax.lax.dynamic_slice(
                        text_cols[f]["fwd_pos"], (lo, 0),
                        (tile, text_cols[f]["fwd_pos"].shape[1])),
                    jax.lax.dynamic_slice(text_cols[f]["k1ln"], (lo,),
                                          (tile,)),
                    jax.lax.dynamic_slice(text_cols[f]["lnorm"], (lo,),
                                          (tile,)))
                for f in pos_fields}
            num_tiles = {
                f: (jax.lax.dynamic_slice(num_cols[f]["values"], (lo,),
                                          (tile,)),
                    jax.lax.dynamic_slice(num_cols[f]["exists"], (lo,),
                                          (tile,)))
                for f in num_fields}
            vec_tiles = {
                i: (jax.lax.dynamic_slice(cl_inputs[i][0], (0, lo),
                                          (b, tile)),
                    jax.lax.dynamic_slice(cl_inputs[i][1], (lo,),
                                          (tile,)))
                for i in vec_idx}
            t_live = jax.lax.dynamic_slice(live, (lo,), (tile,))
            match = bundle_tile_match(clauses, cl_inputs, text_tiles,
                                      num_tiles, msm, t_live,
                                      vec_tiles=vec_tiles,
                                      pos_tiles=pos_tiles)
            total = total + match.sum(axis=-1, dtype=jnp.int32)
            pruned = pruned + jnp.array([0, 0, 1], jnp.int32)
            out = (total, pruned)
            if emit_match:
                out = out + (jax.lax.dynamic_update_slice(
                    st[2], match, (0, lo)),)
            return out

        return jax.lax.cond(jnp.any(can_j), eval_tile, hard_skip, st)

    st0 = (jnp.zeros((b,), jnp.int32), jnp.zeros((3,), jnp.int32))
    if emit_match:
        st0 = st0 + (jnp.zeros((b, cap), bool),)
    st, timed = _stepped_tile_loop(n_tiles, body, st0, step)
    out = st if emit_match else st[:2]
    return out if timed is None else out + (timed,)


def score_topk_bundle_fused(text_cols: dict, num_cols: dict, clauses: tuple,
                            cl_inputs: tuple, msm: jax.Array,
                            boost: jax.Array | None, live: jax.Array,
                            k: int, emit_match: bool = False,
                            step=None, init_topk=None, idx_offset: int = 0):
    """Fused block-max-WAND score + top-k over a bool clause bundle.

    Returns (top_scores [B, k], top_idx [B, k], total [B] int32,
    prune_stats int32 [3] = (hard_skipped, thresholded, tiles_examined))
    plus, when emit_match, the exact match mask [B, cap] bool (incl.
    live) for a downstream aggregation pass — hard-skipped tiles keep
    their zeros, which is exact because a hard skip means no doc there
    can match. Entries past a query's total are -inf with undefined
    indices — the top_k_hits contract.

    Selection happens on POST-boost scores computed in eval_node's exact
    op order, so doc ids and tie order are identical to the unfused
    full-matrix path for ANY positive boosts (the PR 1 pre-boost
    selection caveat is gone). Correct pruning relies on the
    forward-index invariant that a doc's slots hold DISTINCT term ids.
    A `step` (see _stepped_tile_loop) appends the timed_out scalar to
    the result tuple.

    `init_topk` seeds the running top-k state with an EARLIER walk's
    (top_s, top_i) and `idx_offset` shifts this walk's doc indices —
    together they chain base + delta packs (streaming write path) into
    ONE selection: the base walk's k-th best becomes the delta walk's
    opening threshold (its tiles prune against it, exactly as base
    tiles prune against each other), candidates merge through the same
    running_topk_merge (existing state concatenated first, so base docs
    win ties — the (segment order, doc id) tie rule), and the merged
    result equals a per-segment top-k union truncated host-side,
    byte-for-byte. Totals/prune stats cover ONLY this walk.
    """
    field0 = bundle_primary_field(clauses)
    n_tiles = text_cols[field0]["tile_max"].shape[1]
    cap = live.shape[0]
    tile = cap // n_tiles
    b = msm.shape[0]
    k = min(k, cap) if init_topk is None else init_topk[0].shape[1]
    ck = min(k, tile)
    can_match, ub = bundle_tile_bounds(clauses, cl_inputs, text_cols,
                                       num_cols, msm, boost)
    text_fields = bundle_text_fields(clauses)
    pos_fields = bundle_pos_fields(clauses)
    num_fields = tuple(dict.fromkeys(
        f for _r, kd, f, _w in clauses if kd in RANGE_CLAUSE_KINDS))
    vec_idx = tuple(i for i, (_r, kd, _f, _w) in enumerate(clauses)
                    if kd in _VEC_KINDS)

    def body(j, st):
        lo = j * tile
        can_j = jax.lax.dynamic_slice_in_dim(can_match, j, 1, axis=1)[:, 0]
        ub_j = jax.lax.dynamic_slice_in_dim(ub, j, 1, axis=1)[:, 0]

        def hard_skip(st):
            return st[:3] + (st[3] + jnp.array([1, 0, 1], jnp.int32),) \
                + st[4:]

        def score_tile(st):
            top_s, top_i, total, pruned = st[:4]
            text_tiles = {
                f: (jax.lax.dynamic_slice(
                        text_cols[f]["fwd_tids"], (lo, 0),
                        (tile, text_cols[f]["fwd_tids"].shape[1])),
                    jax.lax.dynamic_slice(
                        text_cols[f]["fwd_imps"], (lo, 0),
                        (tile, text_cols[f]["fwd_imps"].shape[1])))
                for f in text_fields}
            pos_tiles = {
                f: (jax.lax.dynamic_slice(
                        text_cols[f]["fwd_pos"], (lo, 0),
                        (tile, text_cols[f]["fwd_pos"].shape[1])),
                    jax.lax.dynamic_slice(text_cols[f]["k1ln"], (lo,),
                                          (tile,)),
                    jax.lax.dynamic_slice(text_cols[f]["lnorm"], (lo,),
                                          (tile,)))
                for f in pos_fields}
            num_tiles = {
                f: (jax.lax.dynamic_slice(num_cols[f]["values"], (lo,),
                                          (tile,)),
                    jax.lax.dynamic_slice(num_cols[f]["exists"], (lo,),
                                          (tile,)))
                for f in num_fields}
            vec_tiles = {
                i: (jax.lax.dynamic_slice(cl_inputs[i][0], (0, lo),
                                          (b, tile)),
                    jax.lax.dynamic_slice(cl_inputs[i][1], (lo,),
                                          (tile,)))
                for i in vec_idx}
            t_live = jax.lax.dynamic_slice(live, (lo,), (tile,))
            score, match = bundle_tile_eval(clauses, cl_inputs, text_tiles,
                                            num_tiles, msm, boost, t_live,
                                            vec_tiles=vec_tiles,
                                            pos_tiles=pos_tiles)
            total = total + match.sum(axis=-1, dtype=jnp.int32)
            can_top = can_j & (ub_j > top_s[:, -1])

            def merge(args):
                ts, ti = args
                cand = jnp.where(match, score, NEG_INF)
                c_s, c_loc = jax.lax.top_k(cand, ck)
                return running_topk_merge(ts, ti, c_s,
                                          c_loc + lo + idx_offset)

            any_top = jnp.any(can_top)
            top_s, top_i = jax.lax.cond(any_top, merge, lambda a: a,
                                        (top_s, top_i))
            pruned = pruned + jnp.where(
                any_top, jnp.array([0, 0, 1], jnp.int32),
                jnp.array([0, 1, 1], jnp.int32))
            out = (top_s, top_i, total, pruned)
            if emit_match:
                out = out + (jax.lax.dynamic_update_slice(
                    st[4], match, (0, lo)),)
            return out

        return jax.lax.cond(jnp.any(can_j), score_tile, hard_skip, st)

    top_s0, top_i0 = (running_topk_init(b, k) if init_topk is None
                      else init_topk)
    st0 = (top_s0, top_i0, jnp.zeros((b,), jnp.int32),
           jnp.zeros((3,), jnp.int32))
    if emit_match:
        st0 = st0 + (jnp.zeros((b, cap), bool),)
    st, timed = _stepped_tile_loop(n_tiles, body, st0, step)
    out = st if emit_match else st[:4]
    return out if timed is None else out + (timed,)


def score_topk_dense_fused(fwd_tids: jax.Array, fwd_imps: jax.Array,
                           tile_max: jax.Array, qt: jax.Array,
                           wq: jax.Array, live: jax.Array, k: int,
                           msm: jax.Array | None = None,
                           boost: jax.Array | None = None
                           ) -> tuple[jax.Array, jax.Array, jax.Array,
                                      jax.Array]:
    """Single-dense-clause entry (PR 1 signature), now a thin wrapper
    over the bundle engine: one should clause whose enclosing bool node
    contributes the dynamic msm/boost. Unlike PR 1, boost is applied
    BEFORE selection in eval_node's exact op order, so doc ids and ties
    match the unfused path for any boost > 0."""
    b = qt.shape[0]
    if msm is None:
        msm = jnp.ones((b,), jnp.int32)
    clauses = (("should", "terms_dense", "f", False),)
    cl_inputs = ((qt, wq, jnp.ones((b,), jnp.int32),
                  jnp.ones((b,), jnp.float32)),)
    text_cols = {"f": {"fwd_tids": fwd_tids, "fwd_imps": fwd_imps,
                       "tile_max": tile_max}}
    return score_topk_bundle_fused(text_cols, {}, clauses, cl_inputs,
                                   msm, boost, live, k)
