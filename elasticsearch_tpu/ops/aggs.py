"""Aggregation device kernels: masked scatter-add bucketing + metrics.

Reference analog: the per-doc LeafBucketCollector loops of the
aggregations framework — e.g. terms via global ordinals
(search/aggregations/bucket/terms/GlobalOrdinalsStringTermsAggregator.java:101-116
— `collect` scatter-adds into BigArrays buckets) and
bucket/histogram/HistogramAggregator.java. Here a whole segment is
bucketed in one batched scatter-add; the per-shard/segment partial
arrays are reduced by addition (the InternalAggregation.reduce analog).

All kernels take a match mask [B, cap] from the query (queries batched)
and return per-bucket arrays [B, n_buckets]; `n_buckets` indexes a
shard-global ordinal space (for terms) or a histogram extent (for
date_histogram/histogram) so partials align across segments.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

F32_INF = jnp.float32(jnp.inf)


def _vscatter(bucket_ids: jax.Array, weights: jax.Array, n_buckets: int) -> jax.Array:
    """weights [B, cap] scattered by bucket_ids [cap] -> [B, n_buckets].
    OOB bucket ids (missing values etc.) are dropped."""

    def one(w):
        return jnp.zeros((n_buckets,), jnp.float32).at[bucket_ids].add(w, mode="drop")

    return jax.vmap(one)(weights)


def bucket_counts(bucket_ids: jax.Array, mask: jax.Array, n_buckets: int) -> jax.Array:
    return _vscatter(bucket_ids, mask.astype(jnp.float32), n_buckets)


# -- sorted (scatter-free) group-by ----------------------------------------
# XLA lowers scatter-add on TPU to a serialized loop, which dominates
# high-cardinality aggregations. Group keys (keyword ordinals, numeric
# values) are STATIC per segment, so a one-time sort permutation turns
# every masked group-by into permute -> cumsum -> boundary gather —
# all dense, parallel VPU work. This is the TPU-first analog of
# GlobalOrdinalsStringTermsAggregator's collect loop.


def _view_block_k(n: int) -> int | None:
    """Block width for the two-level reduce: capacities are BLOCK- or
    pow2-padded, so 512 (or 128 for small segments) always divides."""
    for k in (512, 128):
        if n % k == 0 and n >= k:
            return k
    return None


def view_group_reduce(w: jax.Array, bounds: jax.Array,
                      int_weights: bool = False) -> jax.Array:
    """Per-range sums of SORTED-SPACE weights — the hot aggregation
    kernel at HBM-resident corpus scale.

    `w` [B, Np] holds each query's weights already in the layout's sort
    order (mask evaluated on sorted column projections — no per-query
    permutation gather, which costs ~17ms per 20M-row query on TPU vs
    ~0.5ms for this path). `bounds` [G+1] (or [B, G+1]) are positions
    into [0, Np]; range g spans [bounds[g], bounds[g+1]).

    Two-level decomposition instead of a flat [B, Np] cumsum:
      block sums [B, Np/K] -> short cumsum -> boundary base + an
      intra-block prefix fix at each bound.
    This is both ~2x less HBM traffic than the flat cumsum and the
    precision fix for large corpora: counts accumulate in int32 (a flat
    f32 cumsum goes inexact past 2^24 docs), and float sums only see
    rounding within one K-sized block plus a short cumsum whose hi-lo
    errors cancel locally.

    Ref analog: the per-doc collect loops of
    bucket/terms/GlobalOrdinalsStringTermsAggregator.java:101-116 and
    bucket/histogram/HistogramAggregator.java, restructured as dense
    segmented reduction.
    """
    B, Np = w.shape
    K = _view_block_k(Np)
    acc = jnp.int32 if int_weights else jnp.float32
    if K is None:  # tiny/odd capacity: flat cumsum is fine
        cs0 = jnp.pad(jnp.cumsum(w.astype(acc), axis=-1), ((0, 0), (1, 0)))
        if bounds.ndim == 1:
            hi = jnp.take(cs0, bounds[1:], axis=-1)
            lo = jnp.take(cs0, bounds[:-1], axis=-1)
        else:
            hi = jnp.take_along_axis(cs0, bounds[:, 1:], axis=-1)
            lo = jnp.take_along_axis(cs0, bounds[:, :-1], axis=-1)
        return hi - lo
    NB = Np // K
    blocks = w.reshape(B, NB, K)
    bs = blocks.sum(-1, dtype=acc)
    cs0 = jnp.pad(jnp.cumsum(bs, axis=-1), ((0, 0), (1, 0)))
    blk = bounds // K
    off = bounds % K
    lane = jnp.arange(K, dtype=bounds.dtype)
    # bounds == Np land on blk == NB: cs0[NB] is valid; the row gather
    # clamps but off == 0 zeroes the intra term, so the clamp is inert
    if bounds.ndim == 1:
        base = jnp.take(cs0, blk, axis=-1)                # [B, G+1]
        rows = jnp.take(blocks, blk, axis=1)              # [B, G+1, K]
        intra = jnp.where(lane[None, None, :] < off[None, :, None],
                          rows, 0).sum(-1, dtype=acc)
    else:
        base = jnp.take_along_axis(cs0, blk, axis=-1)
        rows = jnp.take_along_axis(blocks, blk[:, :, None], axis=1)
        intra = jnp.where(lane[None, None, :] < off[:, :, None],
                          rows, 0).sum(-1, dtype=acc)
    pref = base + intra
    return pref[:, 1:] - pref[:, :-1]


def sorted_group_reduce(perm: jax.Array, starts: jax.Array,
                        weighted: jax.Array) -> jax.Array:
    """Sum `weighted` [B, cap] per group. `perm` [cap] sorts docs by
    group key; group g spans sorted positions [starts[g], starts[g+1])
    (starts [G+1]; rows before starts[0] are the missing-key run)."""
    pm = jnp.take(weighted, perm, axis=-1)            # [B, cap]
    cs = jnp.cumsum(pm, axis=-1)
    cs0 = jnp.pad(cs, ((0, 0), (1, 0)))
    hi = jnp.take(cs0, starts[1:], axis=-1)
    lo = jnp.take(cs0, starts[:-1], axis=-1)
    return hi - lo                                     # [B, G]


def sorted_hist_reduce(sorted_vals: jax.Array, perm: jax.Array,
                       weighted: jax.Array,
                       edges: jax.Array) -> jax.Array:
    """Histogram over value-sorted docs: bucket b sums `weighted` where
    edges[b] <= value < edges[b+1]. Boundary positions come from a
    log-depth searchsorted instead of a scatter; runtime edges are fine
    because only the PERMUTATION is static."""
    pm = jnp.take(weighted, perm, axis=-1)
    cs = jnp.cumsum(pm, axis=-1)
    cs0 = jnp.pad(cs, ((0, 0), (1, 0)))
    pos = jnp.searchsorted(sorted_vals, edges, side="left")
    hi = jnp.take(cs0, pos[1:], axis=-1)
    lo = jnp.take(cs0, pos[:-1], axis=-1)
    return hi - lo


def bucket_sums(bucket_ids: jax.Array, mask: jax.Array, values: jax.Array,
                n_buckets: int) -> jax.Array:
    return _vscatter(bucket_ids, jnp.where(mask, values.astype(jnp.float32), 0.0),
                     n_buckets)


def bucket_min(bucket_ids: jax.Array, mask: jax.Array, values: jax.Array,
               n_buckets: int) -> jax.Array:
    def one(m):
        v = jnp.where(m, values.astype(jnp.float32), F32_INF)
        return jnp.full((n_buckets,), F32_INF).at[bucket_ids].min(v, mode="drop")

    return jax.vmap(one)(mask)


def bucket_max(bucket_ids: jax.Array, mask: jax.Array, values: jax.Array,
               n_buckets: int) -> jax.Array:
    def one(m):
        v = jnp.where(m, values.astype(jnp.float32), -F32_INF)
        return jnp.full((n_buckets,), -F32_INF).at[bucket_ids].max(v, mode="drop")

    return jax.vmap(one)(mask)


def bucket_sum_sq(bucket_ids: jax.Array, mask: jax.Array, values: jax.Array,
                  n_buckets: int) -> jax.Array:
    v = values.astype(jnp.float32)
    return _vscatter(bucket_ids, jnp.where(mask, v * v, 0.0), n_buckets)


def keyword_bucket_ids(ords: jax.Array, seg2global: jax.Array, n_global: int
                       ) -> jax.Array:
    """Segment-local keyword ordinals -> shard-global bucket ids.

    ords [cap] int32 (-1 missing); seg2global [card_seg] int32. Missing
    docs map to n_global which every scatter drops. Ref: global ordinals
    mapping, index/fielddata/ordinals/GlobalOrdinalsBuilder.java.
    """
    g = seg2global[jnp.clip(ords, 0, None)]
    return jnp.where(ords >= 0, g, n_global).astype(jnp.int32)


def fixed_histogram_bucket_ids(values: jax.Array, exists: jax.Array,
                               origin, interval, n_buckets: int) -> jax.Array:
    """Fixed-interval (date_)histogram bucket ids.

    values: int32/float32 [cap] (dates are epoch seconds). For int32
    columns the arithmetic stays in int32 — f32 would lose exactness for
    values past 2^24 (epoch seconds!) and smear bucket boundaries. The
    caller passes origin <= data min so (v - origin) cannot overflow.
    """
    if values.dtype == jnp.int32:
        d = values - jnp.asarray(origin, jnp.int32)
        bid = jnp.where(d >= 0, d // jnp.asarray(interval, jnp.int32), -1)
    else:
        v = values.astype(jnp.float32)
        bid = jnp.floor((v - origin) / interval).astype(jnp.int32)
    ok = exists & (bid >= 0) & (bid < n_buckets)
    return jnp.where(ok, bid, n_buckets).astype(jnp.int32)


def edges_bucket_ids(values: jax.Array, exists: jax.Array, edges: jax.Array,
                     n_buckets: int) -> jax.Array:
    """Calendar-interval date_histogram / range agg: bucket by sorted edges.

    edges [n_buckets+1] in the COLUMN's dtype (int32 for dates — exact);
    bucket i covers [edges[i], edges[i+1]).
    """
    bid = jnp.searchsorted(edges.astype(values.dtype), values, side="right") - 1
    bid = bid.astype(jnp.int32)
    ok = exists & (bid >= 0) & (bid < n_buckets)
    return jnp.where(ok, bid, n_buckets).astype(jnp.int32)


# -- top-level (bucket-less) metrics ----------------------------------------


def masked_stats(values: jax.Array, exists: jax.Array, mask: jax.Array) -> dict:
    """count/sum/min/max/sum_sq of a numeric column under a match mask.

    Ref: search/aggregations/metrics/stats/StatsAggregator.java collect loop.
    Returns dict of [B] arrays; reduced across segments by the host.
    """
    m = mask & exists[None, :]
    v = values.astype(jnp.float32)[None, :]
    zero = jnp.zeros_like(v)
    return {
        "count": m.sum(axis=-1, dtype=jnp.float32),
        "sum": jnp.where(m, v, zero).sum(axis=-1),
        "sum_sq": jnp.where(m, v * v, zero).sum(axis=-1),
        "min": jnp.where(m, v, F32_INF).min(axis=-1),
        "max": jnp.where(m, v, -F32_INF).max(axis=-1),
    }
