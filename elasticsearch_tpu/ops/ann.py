"""IVF probe: cluster bounds + threshold-pruned exact scoring.

Query-time half of the coarse-quantized vector path (index/ann.py
builds the clusters at pack time). The shape is the block-max WAND
walk transplanted onto clusters:

  * one small centroid matmul scores every cluster's UPPER BOUND on
    the transformed similarity (`cluster_bounds` — the tile_max
    analog, derived from centroid + radius geometry, inflated by
    ANN_BOUND_SLACK so bf16 member scoring can never beat it);
  * the nprobe candidate clusters are picked and ORDERED by centroid
    similarity (the classic IVF coarse rank — the radius bound
    saturates at the transform ceiling for every cluster whose ball
    covers a near match, so ordering by it would tie-break
    arbitrarily), then probed with a RUNNING top-k threshold carried
    across clusters — same bound-vs-threshold contract as
    `bundle_tile_bounds`: a cluster whose radius bound cannot beat
    the running k-th best is skipped without touching its members
    (`clusters_pruned`);
  * survivor clusters score their members EXACTLY on the MXU (the
    same transforms as ops/knn.knn_score_column), so recall loss
    comes only from the declared nprobe coarse stage, never from
    scoring.

`cluster_bounds_np` is the HOST mirror (kept op-for-op in lockstep
with the device version, the `bundle_tile_bounds_np` convention): a
tiered / oversubscribed pack can rank and filter cluster FETCHES
before any device I/O happens, the way PR 11's pager I/O-filters
tiles.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index.ann import ANN_BOUND_SLACK
from .topk import NEG_INF, running_topk_init, running_topk_merge


def _slacked(t):
    """Bound inflation that is conservative on BOTH signs: nonnegative
    bounds scale up, negative ones shrink toward zero (multiplying a
    negative bound up would LOWER it below a member's true score)."""
    return jnp.where(t >= 0.0, t * ANN_BOUND_SLACK, t / ANN_BOUND_SLACK)


def cluster_bounds(centroids: jax.Array, radii: jax.Array,
                   query: jax.Array, *, similarity: str) -> jax.Array:
    """[C, D] centroids x [B, D] queries -> [B, C] f32 upper bounds on
    the TRANSFORMED similarity of any cluster member.

    Geometry (working space per index/ann._working_space):
      cosine      cos(q, x) = q_hat . x_hat <= q_hat . c + r
      dot_product q . x = q . c + q . (x - c) <= q . c + ||q|| r
      l2_norm     d(q, x) >= max(0, d(q, c) - r)
    each pushed through its monotone score transform."""
    q = query.astype(jnp.float32)
    c = centroids.astype(jnp.float32)
    qc = jnp.dot(q, c.T, preferred_element_type=jnp.float32)   # [B, C]
    if similarity == "cosine":
        qn = jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                         1e-12)
        cosb = jnp.minimum(qc / qn + radii[None, :], 1.0)
        return _slacked((1.0 + cosb) / 2.0)
    if similarity == "dot_product":
        qn = jnp.linalg.norm(q, axis=1, keepdims=True)
        dotb = qc + qn * radii[None, :]
        return _slacked((1.0 + dotb) / 2.0)
    # l2_norm
    qn2 = jnp.sum(q * q, axis=1, keepdims=True)
    c2 = jnp.sum(c * c, axis=1)[None, :]
    d = jnp.sqrt(jnp.maximum(qn2 - 2.0 * qc + c2, 0.0))
    dmin = jnp.maximum(d - radii[None, :], 0.0)
    return _slacked(1.0 / (1.0 + dmin * dmin))


def cluster_bounds_np(centroids: np.ndarray, radii: np.ndarray,
                      query: np.ndarray, *, similarity: str) -> np.ndarray:
    """HOST mirror of cluster_bounds — keep op-for-op in lockstep (the
    bundle_tile_bounds_np convention). Used by the shard searcher to
    pick + order cluster fetches for tiered/oversubscribed packs
    BEFORE any device I/O; the device probe then consumes the
    host-picked ids, so host and device agree on the survivor set by
    construction. f32 throughout: the products are the same IEEE ops
    the device version lowers to."""
    slack = np.float32(ANN_BOUND_SLACK)

    def slacked(t):
        return np.where(t >= 0.0, t * slack, t / slack).astype(np.float32)

    q = np.asarray(query, dtype=np.float32)
    c = np.asarray(centroids, dtype=np.float32)
    r = np.asarray(radii, dtype=np.float32)
    qc = (q @ c.T).astype(np.float32)
    if similarity == "cosine":
        qn = np.maximum(np.linalg.norm(q, axis=1, keepdims=True),
                        np.float32(1e-12)).astype(np.float32)
        cosb = np.minimum(qc / qn + r[None, :], np.float32(1.0))
        return slacked((1.0 + cosb) / np.float32(2.0))
    if similarity == "dot_product":
        qn = np.linalg.norm(q, axis=1, keepdims=True).astype(np.float32)
        dotb = qc + qn * r[None, :]
        return slacked((1.0 + dotb) / np.float32(2.0))
    qn2 = np.sum(q * q, axis=1, keepdims=True, dtype=np.float32)
    c2 = np.sum(c * c, axis=1, dtype=np.float32)[None, :]
    d = np.sqrt(np.maximum(qn2 - 2.0 * qc + c2, np.float32(0.0)))
    dmin = np.maximum(d - r[None, :], np.float32(0.0))
    return slacked(1.0 / (np.float32(1.0) + dmin * dmin))


def _member_scores(v: jax.Array, nrm: jax.Array, query: jax.Array,
                   similarity: str) -> jax.Array:
    """Exact transformed similarity of gathered members: [B, M, D]
    member vectors x [B, D] queries -> [B, M] f32. Delegates to the ONE
    transform definition (ops/knn.knn_score_column) vmapped over the
    per-row cluster gathers, so a transform edit there cannot silently
    diverge IVF member scores from the exact scan's."""
    from .knn import knn_score_column

    ones = jnp.ones(v.shape[1], bool)   # validity masked by the caller

    def one_row(vv, nn, qq):
        return knn_score_column(vv, nn, ones, qq[None],
                                similarity=similarity)[0]

    return jax.vmap(one_row)(v, nrm, query)


@partial(jax.jit, static_argnames=("similarity", "k", "nprobe"))
def ivf_topk(vectors: jax.Array, norms: jax.Array, exists: jax.Array,
             live: jax.Array, members: jax.Array,
             centroids: jax.Array, radii: jax.Array, query: jax.Array,
             *, similarity: str, k: int, nprobe: int,
             probe: tuple[jax.Array, jax.Array] | None = None
             ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """IVF probed top-k over one segment's vectors.

    -> (scores [B, k], idx [B, k] global doc ordinals, stats int32 [3]
    = (clusters_probed, clusters_pruned, clusters_scored), counted in
    per-(query, cluster) units). Entries past a query's hit count are
    -inf with undefined indices — the top_k_hits contract.

    `probe`: optional host-picked (bounds [B, nprobe], ids [B, nprobe])
    from cluster_bounds_np, centroid-rank-ordered per row — the tiered
    pack's I/O filter; when absent the centroid matmul + top_k run
    in-program (ONE dispatch covers coarse stage and probe)."""
    n_clusters = centroids.shape[0]
    nprobe = min(nprobe, n_clusters)
    b = query.shape[0]
    k = min(k, vectors.shape[0])
    ccap = members.shape[1]
    if probe is None:
        bounds = cluster_bounds(centroids, radii, query,
                                similarity=similarity)       # [B, C]
        # rank by the radius-free centroid score: the radius bound
        # CEILS at the transform maximum for every cluster whose ball
        # covers a near-perfect match, so it cannot order candidates
        rank = cluster_bounds(centroids, jnp.zeros_like(radii), query,
                              similarity=similarity)
        _pr, pidx = jax.lax.top_k(rank, nprobe)
        pb = jnp.take_along_axis(bounds, pidx, axis=1)
    else:
        pb, pidx = probe

    def body(j, st):
        top_s, top_i, stats = st
        cid = jnp.clip(pidx[:, j], 0, n_clusters - 1)        # [B]
        # the radius bound vs the running k-th best (the
        # bundle_tile_bounds contract); probe order is centroid-rank
        # descending, so near clusters fill the threshold early and
        # far clusters skip
        need = pb[:, j] > top_s[:, -1]                       # [B]

        def scan(st):
            top_s, top_i, stats = st
            mem = members[cid]                               # [B, ccap]
            valid = mem >= 0
            safe = jnp.where(valid, mem, 0)
            v = vectors[safe]                                # [B,ccap,D]
            s = _member_scores(v, norms[safe], query, similarity)
            ok = valid & exists[safe] & live[safe] & need[:, None]
            s = jnp.where(ok, s, NEG_INF)
            c_s, c_loc = jax.lax.top_k(s, min(k, ccap))
            c_idx = jnp.take_along_axis(safe, c_loc, axis=1)
            top_s, top_i = running_topk_merge(top_s, top_i, c_s, c_idx)
            return top_s, top_i, stats + jnp.array(
                [0, 0, 1], jnp.int32) * need.sum(dtype=jnp.int32)

        # batch-wide skip (per-lane skipping saves nothing on SIMD
        # hardware): members gather + scoring run iff ANY row still
        # needs this probe slot; pruned rows mask their lanes out
        top_s, top_i, stats = jax.lax.cond(
            jnp.any(need), scan, lambda s: s, (top_s, top_i, stats))
        stats = stats + jnp.array([1, 0, 0], jnp.int32) * jnp.int32(b) \
            + jnp.array([0, 1, 0], jnp.int32) * (
                (~need).sum(dtype=jnp.int32))
        return top_s, top_i, stats

    top_s, top_i = running_topk_init(b, k)
    top_s, top_i, stats = jax.lax.fori_loop(
        0, nprobe, body, (top_s, top_i, jnp.zeros((3,), jnp.int32)))
    return top_s, top_i, stats
