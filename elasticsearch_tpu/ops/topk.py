"""Top-k selection over dense score/sort-key arrays.

Replaces Lucene's TopScoreDocCollector / TopFieldCollector heaps
(the collector inside search/query/QueryPhase.java:153) with
jax.lax.top_k over the dense per-doc arrays the scoring ops produce.

Tie-breaking: lax.top_k prefers the lower index on equal keys, and our
doc ids are positional, so ties resolve to the lower doc id — the same
order Lucene produces per shard and what SearchPhaseController.sortDocs
(search/controller/SearchPhaseController.java:233) assumes when merging.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)


def top_k_hits(scores: jax.Array, valid: jax.Array, k: int
               ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(scores [B,cap], valid [B,cap]) -> (top_scores [B,k], top_idx [B,k],
    total_hits [B]). Invalid docs get -inf and can be recognized by the
    caller via total_hits / -inf scores."""
    masked = jnp.where(valid, scores, NEG_INF)
    top_scores, top_idx = jax.lax.top_k(masked, k)
    total = valid.sum(axis=-1, dtype=jnp.int32)
    return top_scores, top_idx, total


def top_k_by_field(sort_key: jax.Array, valid: jax.Array, k: int,
                   descending: bool = True
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Field sort: sort_key [B, cap] (already broadcast per batch) -> top-k.

    Ascending sort negates the key (exact for int32 keys well inside f32
    range; callers promote to f32 beforehand).
    """
    key = sort_key if descending else -sort_key
    masked = jnp.where(valid, key.astype(jnp.float32), NEG_INF)
    top_key, top_idx = jax.lax.top_k(masked, k)
    total = valid.sum(axis=-1, dtype=jnp.int32)
    out_key = top_key if descending else -top_key
    return out_key, top_idx, total
