"""Top-k selection over dense score/sort-key arrays.

Replaces Lucene's TopScoreDocCollector / TopFieldCollector heaps
(the collector inside search/query/QueryPhase.java:153) with
jax.lax.top_k over the dense per-doc arrays the scoring ops produce.

Tie-breaking: lax.top_k prefers the lower index on equal keys, and our
doc ids are positional, so ties resolve to the lower doc id — the same
order Lucene produces per shard and what SearchPhaseController.sortDocs
(search/controller/SearchPhaseController.java:233) assumes when merging.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)


def running_topk_init(b: int, k: int) -> tuple[jax.Array, jax.Array]:
    """Empty running top-k state: (-inf scores, index 0 placeholders).
    Entries beyond a query's total hit count stay -inf with undefined
    indices — the same contract top_k_hits callers already honor."""
    return (jnp.full((b, k), NEG_INF, jnp.float32),
            jnp.zeros((b, k), jnp.int32))


def running_topk_merge(top_s: jax.Array, top_i: jax.Array,
                       cand_s: jax.Array, cand_i: jax.Array
                       ) -> tuple[jax.Array, jax.Array]:
    """Fold a tile's candidates [B, ck] into the running top-k [B, k].

    The existing state is concatenated FIRST: lax.top_k prefers the
    lower position on equal keys, so docs already in the state (earlier
    tiles -> lower doc ids) win ties against new candidates, and within
    each side the established ascending-doc-id tie order is preserved —
    exactly the order one lax.top_k over the full score array produces.
    """
    k = top_s.shape[1]
    all_s = jnp.concatenate([top_s, cand_s], axis=1)
    all_i = jnp.concatenate([top_i, cand_i], axis=1)
    m_s, m_pos = jax.lax.top_k(all_s, k)
    return m_s, jnp.take_along_axis(all_i, m_pos, axis=1)


def top_k_hits(scores: jax.Array, valid: jax.Array, k: int
               ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(scores [B,cap], valid [B,cap]) -> (top_scores [B,k], top_idx [B,k],
    total_hits [B]). Invalid docs get -inf and can be recognized by the
    caller via total_hits / -inf scores."""
    masked = jnp.where(valid, scores, NEG_INF)
    top_scores, top_idx = jax.lax.top_k(masked, k)
    total = valid.sum(axis=-1, dtype=jnp.int32)
    return top_scores, top_idx, total


def top_k_by_field(sort_key: jax.Array, valid: jax.Array, missing: jax.Array,
                   k: int, descending: bool = True
                   ) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Field sort -> (top_key [B,k], top_idx, total [B], top_missing [B,k]).

    sort_key: [cap] or [B, cap]; missing: [cap] bool (docs without the
    field — they sort LAST among matching docs but still above
    non-matching docs, which Lucene guarantees and a shared -inf would
    break). int32 keys stay int32 end-to-end: casting epoch-second dates
    to f32 would collapse ~2-minute windows (ulp(1.7e9)=128).
    """
    is_int = sort_key.dtype == jnp.int32
    if sort_key.ndim == 1:
        sort_key = sort_key[None, :]
    if is_int:
        i32 = jnp.iinfo(jnp.int32)
        if descending:
            key = jnp.where(missing[None, :], i32.min + 1, sort_key)
            masked = jnp.where(valid, key, i32.min)
        else:
            # ascending via negation; saturate i32.min so it cannot wrap
            neg = jnp.where(sort_key == i32.min, i32.max, -sort_key)
            key = jnp.where(missing[None, :], i32.min + 1, neg)
            masked = jnp.where(valid, key, i32.min)
    else:
        f32 = jnp.finfo(jnp.float32)
        key = sort_key if descending else -sort_key
        key = jnp.where(missing[None, :], f32.min, key)
        masked = jnp.where(valid, key, NEG_INF)
    top_key, top_idx = jax.lax.top_k(jnp.broadcast_to(masked, valid.shape), k)
    total = valid.sum(axis=-1, dtype=jnp.int32)
    top_missing = jnp.take_along_axis(
        jnp.broadcast_to(missing[None, :], valid.shape), top_idx, axis=1)
    out_key = jnp.take_along_axis(
        jnp.broadcast_to(sort_key, valid.shape), top_idx, axis=1)
    return out_key, top_idx, total, top_missing
