"""Geo primitives: haversine on device, geohash + parsing on host.

Reference analog: common/geo/ (GeoPoint, GeoUtils, GeoHashUtils,
GeoDistance) and the geo query parsers under index/query/. Distance
math runs on the TPU VPU against the lat/lon doc-value columns — a
[B, cap] elementwise trig pipeline XLA fuses into one pass; ES computes
per-doc distances in a scalar loop per collector
(GeoDistanceRangeFilter).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..utils.errors import QueryParsingError

# ref: org.elasticsearch.common.unit.DistanceUnit (meters per unit)
EARTH_RADIUS_M = 6371008.7714  # GeoUtils.EARTH_MEAN_RADIUS
_UNITS_M = {
    "mm": 0.001, "millimeters": 0.001,
    "cm": 0.01, "centimeters": 0.01,
    "m": 1.0, "meters": 1.0,
    "km": 1000.0, "kilometers": 1000.0,
    "in": 0.0254, "inch": 0.0254,
    "yd": 0.9144, "yards": 0.9144,
    "ft": 0.3048, "feet": 0.3048,
    "mi": 1609.344, "miles": 1609.344,
    "nmi": 1852.0, "nauticalmiles": 1852.0, "NM": 1852.0,
}
_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_BASE32_IDX = {c: i for i, c in enumerate(_BASE32)}


def parse_distance(value, unit: str = "m") -> float:
    """"12km" / 12.5 / "1nmi" -> meters (default unit applies to bare
    numbers). Ref: DistanceUnit.Distance.parseDistance."""
    if isinstance(value, (int, float)):
        return float(value) * _UNITS_M.get(unit, 1.0)
    s = str(value).strip()
    for u in sorted(_UNITS_M, key=len, reverse=True):
        if s.endswith(u):
            try:
                return float(s[: -len(u)]) * _UNITS_M[u]
            except ValueError:
                break
    try:
        return float(s) * _UNITS_M.get(unit, 1.0)
    except ValueError:
        raise QueryParsingError(f"failed to parse distance [{value}]")


def distance_unit_meters(unit: str) -> float:
    m = _UNITS_M.get(unit)
    if m is None:
        raise QueryParsingError(f"unknown distance unit [{unit}]")
    return m


def parse_geo_point(value) -> tuple[float, float]:
    """Any accepted geo_point representation -> (lat, lon).

    Forms (ref: common/geo/GeoUtils.parseGeoPoint): {"lat":..,"lon":..},
    [lon, lat] (GeoJSON order!), "lat,lon" string, geohash string.
    """
    if isinstance(value, dict):
        try:
            return float(value["lat"]), float(value["lon"])
        except (KeyError, TypeError, ValueError):
            raise QueryParsingError(f"failed to parse geo_point {value!r}")
    if isinstance(value, (list, tuple)):
        if len(value) != 2:
            raise QueryParsingError(
                f"geo_point array must be [lon, lat], got {value!r}")
        try:
            return float(value[1]), float(value[0])
        except (TypeError, ValueError):
            raise QueryParsingError(f"failed to parse geo_point {value!r}")
    s = str(value).strip()
    if "," in s:
        parts = s.split(",")
        try:
            return float(parts[0]), float(parts[1])
        except (ValueError, IndexError):
            raise QueryParsingError(f"failed to parse geo_point [{s}]")
    return geohash_decode(s)


# -- geohash ----------------------------------------------------------------


def geohash_decode(geohash: str) -> tuple[float, float]:
    """Geohash -> cell-center (lat, lon). Ref: GeoHashUtils.decode."""
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    is_lon = True
    for c in geohash:
        idx = _BASE32_IDX.get(c)
        if idx is None:
            raise QueryParsingError(f"invalid geohash [{geohash}]")
        for bit in (16, 8, 4, 2, 1):
            if is_lon:
                mid = (lon_lo + lon_hi) / 2
                if idx & bit:
                    lon_lo = mid
                else:
                    lon_hi = mid
            else:
                mid = (lat_lo + lat_hi) / 2
                if idx & bit:
                    lat_lo = mid
                else:
                    lat_hi = mid
            is_lon = not is_lon
    return (lat_lo + lat_hi) / 2, (lon_lo + lon_hi) / 2


def geohash_cells(lat: np.ndarray, lon: np.ndarray, precision: int
                  ) -> np.ndarray:
    """Vectorized geohash cell ids (uint64) at `precision` chars.

    Bit-interleaved lon/lat quantization — the integer form of
    GeoHashUtils.encode; cells convert to strings via cells_to_geohash.
    """
    nbits = 5 * precision
    lon_bits = (nbits + 1) // 2
    lat_bits = nbits // 2
    lon_q = np.clip(((lon + 180.0) / 360.0) * (1 << lon_bits), 0,
                    (1 << lon_bits) - 1).astype(np.uint64)
    lat_q = np.clip(((lat + 90.0) / 180.0) * (1 << lat_bits), 0,
                    (1 << lat_bits) - 1).astype(np.uint64)
    cell = np.zeros_like(lon_q)
    for i in range(lon_bits):
        bit = (lon_q >> np.uint64(lon_bits - 1 - i)) & np.uint64(1)
        cell |= bit << np.uint64(nbits - 1 - 2 * i)
    for i in range(lat_bits):
        bit = (lat_q >> np.uint64(lat_bits - 1 - i)) & np.uint64(1)
        cell |= bit << np.uint64(nbits - 2 - 2 * i)
    return cell


def geohash_encode(lat: float, lon: float, precision: int = 12) -> str:
    """(lat, lon) -> geohash string. Ref: GeoHashUtils.encode."""
    cell = geohash_cells(np.asarray([lat]), np.asarray([lon]), precision)
    return cell_to_geohash(int(cell[0]), precision)


def cell_to_geohash(cell: int, precision: int) -> str:
    chars = []
    for i in range(precision):
        shift = 5 * (precision - 1 - i)
        chars.append(_BASE32[(cell >> shift) & 0x1F])
    return "".join(chars)


# -- device distance --------------------------------------------------------


def haversine_m(lat_col, lon_col, qlat, qlon, xp=jnp):
    """Great-circle distance in meters between each doc point and the
    query point. All angles degrees; fuses into one VPU pass."""
    rad = math.pi / 180.0
    phi1 = lat_col * rad
    phi2 = qlat * rad
    dphi = (qlat - lat_col) * rad
    dlam = (qlon - lon_col) * rad
    a = xp.sin(dphi / 2.0) ** 2 + \
        xp.cos(phi1) * xp.cos(phi2) * xp.sin(dlam / 2.0) ** 2
    return 2.0 * EARTH_RADIUS_M * xp.arcsin(xp.sqrt(xp.clip(a, 0.0, 1.0)))
