"""Pallas TPU kernels for the BM25 scoring hot loop.

The reference's per-shard hot loop (search/query/QueryPhase.java:153 —
BulkScorer iterating postings, BM25 Similarity, TopScoreDocCollector)
maps to two dense-tensor formulations here, each with a fused kernel:

* `score_terms_dense_pallas` — the forward-index path (`terms_dense` /
  `term_text` in the executor): score[b, d] = sum over the doc's
  (term, impact) slots of impact * weight where the slot's term id is
  one of the query's. One pass over the [cap, L] forward index per doc
  tile, all B queries and Q terms consumed from VMEM — the [B, cap, L]
  broadcast intermediate the jnp version materializes never exists.

* `scatter_add_pallas` — the posting-scatter path (`term_text_sc` /
  `terms_fused`): scores[b, docs[b, n]] += vals[b, n]. TPUs have no
  vector scatter, so each 128-posting chunk becomes a one-hot compare
  against a 128-doc tile contracted on the MXU; because postings are
  doc-sorted within a term, a prefetched per-chunk [min, max] doc range
  skips every (tile, chunk) pair that cannot intersect, making the work
  near-linear in postings instead of postings x doc-tiles.

The jnp implementations in ops/scoring.py remain the reference
semantics (and the CPU path); tests run these kernels in interpret mode
against them, and bench.py A/Bs them on the real chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..index.segment import BLOCK

LANES = 128          # TPU lane width = posting block width
_DOC_TILE = 512      # docs scored per dense-kernel grid step
_BATCH_TILE = 64     # queries scored per dense-kernel grid step — the
                     # kernel's [b_tile, doc_tile, L] compare/accumulate
                     # working set must stay well inside scoped VMEM
                     # (64*512*8*4B = 1MB per term step)


# ---------------------------------------------------------------------------
# forward-index (dense) scoring kernel
# ---------------------------------------------------------------------------


def _dense_kernel(qt_ref, wq_ref, tids_ref, imps_ref, out_ref):
    """One (batch tile, doc tile): out[b, t] = sum_q wq[b,q] * sum_l
    (tids[t, l] == qt[b, q]) * imps[t, l]. Both the term count Q and
    the forward-slot count L are small static ints, so they unroll;
    every live buffer stays 2-D [b_tile, doc_tile] — a 3-D [.., .., L]
    intermediate would be lane-padded L->128 by the TPU tiling and blow
    the scoped-VMEM budget 16x."""
    tids = tids_ref[...]                       # [L, TILE] int32
    imps = imps_ref[...]                       # [L, TILE] f32
    qt = qt_ref[...]                           # [Bt, Q] int32
    wq = wq_ref[...]                           # [Bt, Q] f32
    b_n, q_n = qt.shape
    n_slots, tile = tids.shape
    acc = jnp.zeros((b_n, tile), jnp.float32)
    for q in range(q_n):
        tq = qt[:, q]                          # [Bt]
        hit = jnp.zeros((b_n, tile), jnp.float32)
        for l in range(n_slots):
            # row slices of the slot-major layout are contiguous lane
            # vectors (a [TILE, L] column slice would stride the padded
            # minor dim and spill registers catastrophically)
            eq = tids[l][None, :] == tq[:, None]      # [Bt, TILE]
            hit = hit + jnp.where(eq, imps[l][None, :], 0.0)
        acc = acc + hit * wq[:, q][:, None]
    out_ref[...] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def score_terms_dense_pallas(fwd_tids: jax.Array, fwd_imps: jax.Array,
                             qt: jax.Array, wq: jax.Array,
                             interpret: bool = False) -> jax.Array:
    """[cap, L] forward index x [B, Q] query terms -> [B, cap] scores.

    Query term ids use -1 for padding (matches only zero-impact slots,
    exactly like the jnp path, since tids padding is also -1 with 0
    impact — weights for padded terms must be 0, which bind guarantees).
    """
    cap, lanes = fwd_tids.shape
    b = qt.shape[0]
    tile = min(_DOC_TILE, cap)
    btile = min(_BATCH_TILE, b)
    pad_b = (-b) % btile
    if pad_b:
        # pad the query axis up to the tile (padded rows score against
        # weight 0 and are sliced off)
        qt = jnp.pad(qt, ((0, pad_b), (0, 0)), constant_values=-1)
        wq = jnp.pad(wq, ((0, pad_b), (0, 0)))
    bp = b + pad_b
    # slot-major layout: kernel blocks slice slot ROWS (contiguous lane
    # vectors); XLA hoists + caches this transpose across calls
    tids_t = fwd_tids.T                        # [L, cap]
    imps_t = fwd_imps.T
    grid = (bp // btile, cap // tile)
    out = pl.pallas_call(
        _dense_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((btile, qt.shape[1]), lambda bi, i: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, wq.shape[1]), lambda bi, i: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((lanes, tile), lambda bi, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((lanes, tile), lambda bi, i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((btile, tile), lambda bi, i: (bi, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, cap), jnp.float32),
        interpret=interpret,
    )(qt, wq, tids_t, imps_t)
    return out[:b] if pad_b else out


# ---------------------------------------------------------------------------
# posting-scatter kernel (one-hot MXU scatter with sorted-range skip)
# ---------------------------------------------------------------------------


_BROWS = 8  # batch rows per scatter block (TPU sublane granularity)


def _scatter_kernel(cmin_ref, cmax_ref, docs_ref, vals_ref, out_ref):
    b = pl.program_id(0)
    t = pl.program_id(1)
    c = pl.program_id(2)

    @pl.when(c == 0)
    def _zero():
        out_ref[...] = jnp.zeros_like(out_ref)

    tile_lo = t * LANES
    # whole-block skip: does ANY of the 8 rows' chunk range touch this
    # doc tile? (rows are independent queries; posting chunks are
    # doc-sorted so the [min, max] test prunes most (tile, chunk) pairs)
    hit = jnp.zeros((), jnp.bool_)
    for r in range(_BROWS):
        row = b * _BROWS + r
        hit = hit | ((cmax_ref[row, c] >= tile_lo)
                     & (cmin_ref[row, c] < tile_lo + LANES))

    @pl.when(hit)
    def _accumulate():
        docs = docs_ref[...]                   # [8, 128] int32
        vals = vals_ref[...]                   # [8, 128] f32
        local = docs - tile_lo
        iota = jax.lax.broadcasted_iota(jnp.int32, (_BROWS, LANES, LANES),
                                        2)
        onehot = (local[:, :, None] == iota).astype(jnp.float32)
        # contribution[r, j] = sum_i vals[r, i] * onehot[r, i, j]
        # (batched MXU contract over the 8 rows)
        contrib = jax.lax.dot_general(
            vals[:, None, :], onehot,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)   # [8, 1, 128]
        out_ref[...] += contrib[:, 0, :]


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def scatter_add_pallas(docs: jax.Array, vals: jax.Array, cap: int,
                       interpret: bool = False) -> jax.Array:
    """scores[b, docs[b, n]] += vals[b, n]; docs >= cap (padding) drop.

    docs: int32 [B, N] sorted non-decreasing per (query, term) run —
    segment posting blocks are doc-sorted, which is what makes the
    per-chunk [min, max] tile skip effective. Correctness does NOT
    depend on sortedness, only performance.
    """
    b, n = docs.shape
    n_pad = -(-n // LANES) * LANES
    cap_pad = -(-cap // LANES) * LANES
    b_pad = -(-b // _BROWS) * _BROWS
    if n_pad != n:
        docs = jnp.pad(docs, ((0, 0), (0, n_pad - n)),
                       constant_values=cap_pad)
        vals = jnp.pad(vals, ((0, 0), (0, n_pad - n)))
    if b_pad != b:
        docs = jnp.pad(docs, ((0, b_pad - b), (0, 0)),
                       constant_values=cap_pad)
        vals = jnp.pad(vals, ((0, b_pad - b), (0, 0)))
    # OOB padding (== cap) must never land in a tile: clamp into a
    # sentinel range past cap_pad so the range skip drops those chunks
    docs = jnp.where(docs >= cap, cap_pad + LANES, docs)
    chunks = docs.reshape(b_pad, n_pad // LANES, LANES)
    cmin = chunks.min(axis=-1).astype(jnp.int32)     # [B, C]
    cmax = chunks.max(axis=-1).astype(jnp.int32)
    # padded chunk rows (all sentinel) have cmin > cap_pad -> skipped
    grid = (b_pad // _BROWS, cap_pad // LANES, n_pad // LANES)
    out = pl.pallas_call(
        _scatter_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((_BROWS, LANES),
                             lambda b_, t, c, *_: (b_, c)),
                pl.BlockSpec((_BROWS, LANES),
                             lambda b_, t, c, *_: (b_, c)),
            ],
            out_specs=pl.BlockSpec((_BROWS, LANES),
                                   lambda b_, t, c, *_: (b_, t)),
        ),
        out_shape=jax.ShapeDtypeStruct((b_pad, cap_pad), jnp.float32),
        interpret=interpret,
    )(cmin, cmax, docs.reshape(b_pad, n_pad), vals.reshape(b_pad, n_pad))
    return out[:b, :cap]


# ---------------------------------------------------------------------------
# fused block-max score + top-k kernel (forward-index path, bool bundles)
# ---------------------------------------------------------------------------
#
# One kernel walks (batch tile, doc tile) grid cells. The doc-tile axis
# is the INNER grid dimension, which TPU executes sequentially, so a
# VMEM scratch row carries each query's running top-k threshold across
# the tiles of its batch tile ("running per-query threshold in on-chip
# memory"). Per tile the kernel evaluates the WHOLE clause bundle (see
# ops/scoring.py: must/should scoring clauses + filter/must_not masks,
# single-should wrappers with per-clause msm/boost) and emits the
# tile-local top-k candidates (ck = min(k, tile) values + doc ids), the
# exact match count, and a prune flag; a single cheap lax.top_k over the
# [B, n_tiles * ck] candidate strip — ~k/tile the size of the [B, cap]
# matrix the unfused path materializes — merges them. Candidate order
# (tile-ascending, within-tile ties doc-ascending) makes the merge
# reproduce the global lax.top_k tie-breaking exactly.
#
# The per-tile can_match/bound vectors are precomputed OUTSIDE the
# kernel (ops/scoring.bundle_tile_bounds — [B, J] is tiny), so the
# kernel itself only consumes one column per tile. Pallas eligibility is
# bundles whose clauses all score ONE text field with no numeric-range
# masks; everything else runs the XLA engine.
#
# The in-kernel threshold is the max over processed tiles of the tile's
# k-th best score — a lower bound on the global k-th best backed by k
# lower-doc-id candidates, so `bound <= thr` tiles can skip extraction
# without changing the result (ties lose to the earlier docs anyway).
# It is only maintained when ck == k; a narrower tile cannot witness k
# candidates and the threshold stays -inf (no threshold pruning).


def _bundle_topk_kernel(qt_ref, wq_ref, msmc_ref, boostc_ref, msm_ref,
                        boost_ref, canm_ref, ub_ref, tids_ref, imps_ref,
                        live_ref, cs_ref, ci_ref, cnt_ref, flag_ref,
                        thr_ref, *, roles: tuple, qm: int, ck: int,
                        update_thr: bool):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _reset():
        thr_ref[...] = jnp.full_like(thr_ref, -jnp.inf)

    ub = ub_ref[...]                           # [bt, 1] f32 tile bound
    can_hit = canm_ref[...] > 0                # [bt, 1] msm-aware prune
    thr = thr_ref[:, 0:1]                      # [bt, 1]
    any_hit = jnp.any(can_hit)

    @pl.when(jnp.logical_not(any_hit))
    def _hard_skip():
        # no query can match in this tile: nothing to score OR count
        cs_ref[...] = jnp.full_like(cs_ref, -jnp.inf)
        ci_ref[...] = jnp.zeros_like(ci_ref)
        cnt_ref[...] = jnp.zeros_like(cnt_ref)
        flag_ref[...] = jnp.full_like(flag_ref, 2)

    @pl.when(any_hit)
    def _score():
        tids = tids_ref[...]                   # [L, tile] slot-major
        imps = imps_ref[...]
        qt = qt_ref[...]                       # [bt, C*qm]
        wq = wq_ref[...]
        msmc = msmc_ref[...]                   # [bt, C] i32
        boostc = boostc_ref[...]               # [bt, C] f32
        b_n = qt.shape[0]
        n_slots, tile = tids.shape
        acc = jnp.zeros((b_n, tile), jnp.float32)
        must_ok = jnp.ones((b_n, tile), bool)
        not_any = jnp.zeros((b_n, tile), bool)
        scnt = jnp.zeros((b_n, tile), jnp.int32)
        # static clause unroll in eval_node order (must, filter,
        # must_not, should — the caller guarantees the ordering)
        for c, role in enumerate(roles):
            s_leaf = jnp.zeros((b_n, tile), jnp.float32)
            for q in range(qm):
                tq = qt[:, c * qm + q]
                hit = jnp.zeros((b_n, tile), jnp.float32)
                for l in range(n_slots):
                    eq = tids[l][None, :] == tq[:, None]
                    hit = hit + jnp.where(eq, imps[l][None, :], 0.0)
                s_leaf = s_leaf + hit * wq[:, c * qm + q][:, None]
            m_leaf = s_leaf > 0.0
            msm_c = msmc[:, c:c + 1]
            m = (m_leaf | (msm_c <= 0)) & (msm_c <= 1)
            s = jnp.where(m_leaf, s_leaf, 0.0) * boostc[:, c:c + 1]
            if role in ("must", "should"):
                acc = acc + jnp.where(m, s, 0.0)
            if role == "must" or role == "filter":
                must_ok = must_ok & m
            elif role == "must_not":
                not_any = not_any | m
            elif role == "should":
                scnt = scnt + m.astype(jnp.int32)
        live = live_ref[...] > 0               # [1, tile]
        match = (must_ok & jnp.logical_not(not_any)
                 & (scnt >= msm_ref[...]) & live)
        acc = acc * boost_ref[...]             # post-accum outer boost
        cnt_ref[...] = jnp.sum(match, axis=1, keepdims=True
                               ).astype(jnp.int32)
        can_top = can_hit & (ub > thr)
        any_top = jnp.any(can_top)

        @pl.when(jnp.logical_not(any_top))
        def _thresholded():
            # exact counting happened above; candidates cannot improve
            # any query's top-k, skip the extraction
            cs_ref[...] = jnp.full_like(cs_ref, -jnp.inf)
            ci_ref[...] = jnp.zeros_like(ci_ref)
            flag_ref[...] = jnp.ones_like(flag_ref)

        @pl.when(any_top)
        def _select():
            # ck passes of (max, lowest-argmax, mask): ties come out in
            # ascending doc order, matching lax.top_k's tie rule
            cand = jnp.where(match, acc, -jnp.inf)
            idx = jax.lax.broadcasted_iota(jnp.int32, (b_n, tile), 1)
            vs = []
            ps = []
            for _s in range(ck):
                m = jnp.max(cand, axis=1, keepdims=True)           # [bt,1]
                pos = jnp.min(jnp.where(cand == m, idx, tile),
                              axis=1, keepdims=True)
                vs.append(m)
                ps.append(pos)
                cand = jnp.where(idx == pos, -jnp.inf, cand)
            v = jnp.concatenate(vs, axis=1)                        # [bt,ck]
            p = jnp.concatenate(ps, axis=1)
            cs_ref[...] = v
            ci_ref[...] = jnp.where(v > -jnp.inf, p + j * tile, 0)
            flag_ref[...] = jnp.zeros_like(flag_ref)
            if update_thr:
                thr_ref[:, 0:1] = jnp.maximum(thr, v[:, ck - 1:ck])


@functools.partial(jax.jit, static_argnames=("roles", "k", "interpret"))
def fused_topk_bundle_pallas(fwd_tids: jax.Array, fwd_imps: jax.Array,
                             can_match: jax.Array, ub: jax.Array,
                             qt_all: jax.Array, wq_all: jax.Array,
                             msmc: jax.Array, boostc: jax.Array,
                             msm: jax.Array, boost: jax.Array,
                             live: jax.Array, roles: tuple, k: int,
                             interpret: bool = False
                             ) -> tuple[jax.Array, jax.Array, jax.Array,
                                        jax.Array]:
    """Pallas counterpart of ops.scoring.score_topk_bundle_fused for
    SINGLE-text-field bundles (every clause scores the same forward
    index; no numeric-range masks — the XLA engine covers the rest).

    roles: static per-clause role tuple in eval_node order. qt_all /
    wq_all: [B, C*qm] clause-stacked query terms, each clause padded to
    qm = max clause width (tid -1 / weight 0 padding adds exact 0.0).
    msmc/boostc: [B, C] per-clause wrapper params (1 / 1.0 for bare
    clauses). can_match/ub: [B, J] from bundle_tile_bounds — shared with
    the XLA engine so both backends prune identically. Returns
    (top_s [B,k], top_i [B,k], total [B], prune_stats f32 [3] =
    (hard, thresholded, examined) in doc-tile units: per-(batch-tile,
    doc-tile) decisions are averaged over batch tiles so examined ==
    n_tiles, matching the XLA backend's batch-wide counters)."""
    cap, slots = fwd_tids.shape
    b = qt_all.shape[0]
    n_tiles = can_match.shape[1]
    tile = cap // n_tiles
    k = min(k, cap)
    ck = min(k, tile)
    n_clauses = len(roles)
    qm = qt_all.shape[1] // n_clauses
    btile = min(_BATCH_TILE, b)
    pad_b = (-b) % btile
    if pad_b:
        # padded rows are inert: can_match=0 keeps them out of every
        # batch-wide prune vote and msm=2 with no should votes matches
        # nothing, so their exact counts are 0
        qt_all = jnp.pad(qt_all, ((0, pad_b), (0, 0)), constant_values=-1)
        wq_all = jnp.pad(wq_all, ((0, pad_b), (0, 0)))
        msmc = jnp.pad(msmc, ((0, pad_b), (0, 0)), constant_values=1)
        boostc = jnp.pad(boostc, ((0, pad_b), (0, 0)), constant_values=1.0)
        msm = jnp.pad(msm, (0, pad_b), constant_values=2)
        boost = jnp.pad(boost, (0, pad_b), constant_values=1.0)
        can_match = jnp.pad(can_match, ((0, pad_b), (0, 0)))
        ub = jnp.pad(ub, ((0, pad_b), (0, 0)))
    bp = b + pad_b
    grid = (bp // btile, n_tiles)
    kern = functools.partial(_bundle_topk_kernel, roles=roles, qm=qm,
                             ck=ck, update_thr=(ck == k))
    qw = qt_all.shape[1]
    cs, ci, cnt, flags = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((btile, qw), lambda bi, j: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, qw), lambda bi, j: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, n_clauses), lambda bi, j: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, n_clauses), lambda bi, j: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, 1), lambda bi, j: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, 1), lambda bi, j: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, 1), lambda bi, j: (bi, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, 1), lambda bi, j: (bi, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((slots, tile), lambda bi, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((slots, tile), lambda bi, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda bi, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((btile, ck), lambda bi, j: (bi, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, ck), lambda bi, j: (bi, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, 1), lambda bi, j: (bi, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, 1), lambda bi, j: (bi, j),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, n_tiles * ck), jnp.float32),
            jax.ShapeDtypeStruct((bp, n_tiles * ck), jnp.int32),
            jax.ShapeDtypeStruct((bp, n_tiles), jnp.int32),
            jax.ShapeDtypeStruct((bp, n_tiles), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((btile, LANES), jnp.float32)],
        interpret=interpret,
    )(qt_all, wq_all, msmc, boostc, msm[:, None].astype(jnp.int32),
      boost[:, None].astype(jnp.float32),
      can_match.astype(jnp.int32), ub,
      fwd_tids.T, fwd_imps.T, live.astype(jnp.int32)[None, :])
    # tile-major candidate strip: global top_k tie-breaks by flat index,
    # i.e. (tile asc, within-tile rank) — lower doc ids win ties, the
    # same order one lax.top_k over the full score matrix produces
    top_s, pos = jax.lax.top_k(cs[:b], k)
    top_i = jnp.take_along_axis(ci[:b], pos, axis=1)
    total = cnt[:b].sum(axis=1)
    # prune decisions happen per (batch-tile, doc-tile) grid cell here
    # but per doc-tile in the XLA backend; normalize by the batch-tile
    # count so both report in doc-tile units (examined == n_tiles) and
    # prune rates stay comparable when the autotuner mixes backends
    reps = flags[::btile]                       # one row per batch tile
    n_btiles = bp // btile
    pruned = (jnp.stack([(reps == 2).sum(), (reps == 1).sum(),
                         jnp.int32(reps.size)]).astype(jnp.float32)
              / n_btiles)
    return top_s, top_i, total, pruned


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def fused_topk_dense_pallas(fwd_tids: jax.Array, fwd_imps: jax.Array,
                            tile_max: jax.Array, qt: jax.Array,
                            wq: jax.Array, live: jax.Array, k: int,
                            msm: jax.Array | None = None,
                            boost: jax.Array | None = None,
                            interpret: bool = False
                            ) -> tuple[jax.Array, jax.Array, jax.Array,
                                       jax.Array]:
    """Single-dense-clause entry (PR 1 signature): a thin wrapper over
    the bundle kernel — one should clause, the enclosing bool node's
    dynamic msm/boost as the outer params. Like the XLA wrapper, boost
    now applies BEFORE selection in eval_node's exact op order, so doc
    ids and ties match the unfused path for any boost > 0."""
    from .scoring import bundle_tile_bounds
    b = qt.shape[0]
    if msm is None:
        msm = jnp.ones((b,), jnp.int32)
    if boost is None:
        boost = jnp.ones((b,), jnp.float32)
    ones_i = jnp.ones((b, 1), jnp.int32)
    ones_f = jnp.ones((b, 1), jnp.float32)
    clauses = (("should", "terms_dense", "f", False),)
    cl_inputs = ((qt, wq, ones_i[:, 0], ones_f[:, 0]),)
    can_match, ub = bundle_tile_bounds(
        clauses, cl_inputs, {"f": {"tile_max": tile_max}}, {}, msm, boost)
    return fused_topk_bundle_pallas(
        fwd_tids, fwd_imps, can_match, ub, qt, wq, ones_i, ones_f,
        msm, boost, live, ("should",), k, interpret=interpret)


# ---------------------------------------------------------------------------
# drop-in counterparts for ops/scoring.py entry points
# ---------------------------------------------------------------------------


def score_term_pallas(block_docs: jax.Array, block_imps: jax.Array,
                      block_lo: jax.Array, nb_valid: jax.Array,
                      weight: jax.Array, nb_pad: int, cap: int,
                      interpret: bool = False) -> jax.Array:
    """Pallas-backed ops.scoring.score_term: XLA block gather (regular,
    already efficient) + fused one-hot scatter."""
    from .scoring import gather_term_blocks
    docs, imps = gather_term_blocks(block_docs, block_imps, block_lo,
                                    nb_valid, nb_pad, cap)
    return scatter_add_pallas(docs, imps * weight[:, None], cap,
                              interpret=interpret)


def score_terms_fused_pallas(block_docs: jax.Array, block_imps: jax.Array,
                             gather_idx: jax.Array, weights: jax.Array,
                             cap: int, interpret: bool = False) -> jax.Array:
    """Pallas-backed ops.scoring.score_terms_fused."""
    from .scoring import gather_fused_blocks
    docs, vals = gather_fused_blocks(block_docs, block_imps, gather_idx,
                                     weights, cap)
    return scatter_add_pallas(docs, vals, cap, interpret=interpret)


# ---------------------------------------------------------------------------
# dispatch: use the kernels on real TPU, jnp elsewhere
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def pallas_enabled() -> bool:
    """Kernels engage on an actual TPU backend unless ES_TPU_PALLAS=0;
    ES_TPU_PALLAS=1 forces them even off-TPU (in interpret mode — far
    slower than the XLA fallback, for validation only)."""
    import os
    flag = os.environ.get("ES_TPU_PALLAS", "auto").lower()
    if flag in ("0", "false", "off"):
        return False
    if flag in ("1", "true", "on"):
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def resident_step_ok() -> bool:
    """May a resident stepped entry (search/resident.py) run through a
    Pallas kernel? No: the per-chunk device-side deadline check is an
    XLA host callback threaded through the chunked tile loop
    (ops/scoring._stepped_tile_loop), and a Mosaic kernel body cannot
    host such a callback mid-grid — so resident entries always pin the
    XLA bundle engine, and pallas-tuned plans simply take the cold
    (autotuned) dispatch when residency would lose the kernel. Exists
    as a named predicate so the executor's admission reads as policy,
    not accident."""
    return False


@functools.lru_cache(maxsize=1)
def interpret_mode() -> bool:
    """Forced-on kernels off-TPU must run the Pallas interpreter —
    Mosaic lowering only exists for TPU backends."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True
