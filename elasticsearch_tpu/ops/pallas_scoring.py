"""Pallas TPU kernels for the BM25 scoring hot loop.

The reference's per-shard hot loop (search/query/QueryPhase.java:153 —
BulkScorer iterating postings, BM25 Similarity, TopScoreDocCollector)
maps to two dense-tensor formulations here, each with a fused kernel:

* `score_terms_dense_pallas` — the forward-index path (`terms_dense` /
  `term_text` in the executor): score[b, d] = sum over the doc's
  (term, impact) slots of impact * weight where the slot's term id is
  one of the query's. One pass over the [cap, L] forward index per doc
  tile, all B queries and Q terms consumed from VMEM — the [B, cap, L]
  broadcast intermediate the jnp version materializes never exists.

* `scatter_add_pallas` — the posting-scatter path (`term_text_sc` /
  `terms_fused`): scores[b, docs[b, n]] += vals[b, n]. TPUs have no
  vector scatter, so each 128-posting chunk becomes a one-hot compare
  against a 128-doc tile contracted on the MXU; because postings are
  doc-sorted within a term, a prefetched per-chunk [min, max] doc range
  skips every (tile, chunk) pair that cannot intersect, making the work
  near-linear in postings instead of postings x doc-tiles.

* `fused_topk_bundle_pallas` / `match_mask_bundle_pallas` — the fused
  block-max-WAND bundle engine (see ops/scoring.py for the reference
  semantics): one kernel family covering the FULL bundle admission
  matrix — multi-text-field clause bundles, numeric range masks in
  VMEM, emit-match, the mask-only k == 0 grid — with an in-VMEM
  running top-k threshold, plus a stepped chunked form that carries
  the threshold across pallas_call boundaries so the resident loop
  and the mesh can host per-chunk deadline checks between kernels.

The jnp implementations in ops/scoring.py remain the reference
semantics (and the CPU path); tests run these kernels in interpret mode
against them, and bench.py A/Bs them on the real chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..index.segment import BLOCK

LANES = 128          # TPU lane width = posting block width
_DOC_TILE = 512      # docs scored per dense-kernel grid step
_BATCH_TILE = 64     # queries scored per dense-kernel grid step — the
                     # kernel's [b_tile, doc_tile, L] compare/accumulate
                     # working set must stay well inside scoped VMEM
                     # (64*512*8*4B = 1MB per term step)


# ---------------------------------------------------------------------------
# forward-index (dense) scoring kernel
# ---------------------------------------------------------------------------


def _dense_kernel(qt_ref, wq_ref, tids_ref, imps_ref, out_ref):
    """One (batch tile, doc tile): out[b, t] = sum_q wq[b,q] * sum_l
    (tids[t, l] == qt[b, q]) * imps[t, l]. Both the term count Q and
    the forward-slot count L are small static ints, so they unroll;
    every live buffer stays 2-D [b_tile, doc_tile] — a 3-D [.., .., L]
    intermediate would be lane-padded L->128 by the TPU tiling and blow
    the scoped-VMEM budget 16x."""
    tids = tids_ref[...]                       # [L, TILE] int32
    imps = imps_ref[...]                       # [L, TILE] f32
    qt = qt_ref[...]                           # [Bt, Q] int32
    wq = wq_ref[...]                           # [Bt, Q] f32
    b_n, q_n = qt.shape
    n_slots, tile = tids.shape
    acc = jnp.zeros((b_n, tile), jnp.float32)
    for q in range(q_n):
        tq = qt[:, q]                          # [Bt]
        hit = jnp.zeros((b_n, tile), jnp.float32)
        for l in range(n_slots):
            # row slices of the slot-major layout are contiguous lane
            # vectors (a [TILE, L] column slice would stride the padded
            # minor dim and spill registers catastrophically)
            eq = tids[l][None, :] == tq[:, None]      # [Bt, TILE]
            hit = hit + jnp.where(eq, imps[l][None, :], 0.0)
        acc = acc + hit * wq[:, q][:, None]
    out_ref[...] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def score_terms_dense_pallas(fwd_tids: jax.Array, fwd_imps: jax.Array,
                             qt: jax.Array, wq: jax.Array,
                             interpret: bool = False) -> jax.Array:
    """[cap, L] forward index x [B, Q] query terms -> [B, cap] scores.

    Query term ids use -1 for padding (matches only zero-impact slots,
    exactly like the jnp path, since tids padding is also -1 with 0
    impact — weights for padded terms must be 0, which bind guarantees).
    """
    cap, lanes = fwd_tids.shape
    b = qt.shape[0]
    tile = min(_DOC_TILE, cap)
    btile = min(_BATCH_TILE, b)
    pad_b = (-b) % btile
    if pad_b:
        # pad the query axis up to the tile (padded rows score against
        # weight 0 and are sliced off)
        qt = jnp.pad(qt, ((0, pad_b), (0, 0)), constant_values=-1)
        wq = jnp.pad(wq, ((0, pad_b), (0, 0)))
    bp = b + pad_b
    # slot-major layout: kernel blocks slice slot ROWS (contiguous lane
    # vectors); XLA hoists + caches this transpose across calls
    tids_t = fwd_tids.T                        # [L, cap]
    imps_t = fwd_imps.T
    grid = (bp // btile, cap // tile)
    out = pl.pallas_call(
        _dense_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((btile, qt.shape[1]), lambda bi, i: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, wq.shape[1]), lambda bi, i: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((lanes, tile), lambda bi, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((lanes, tile), lambda bi, i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((btile, tile), lambda bi, i: (bi, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, cap), jnp.float32),
        interpret=interpret,
    )(qt, wq, tids_t, imps_t)
    return out[:b] if pad_b else out


# ---------------------------------------------------------------------------
# posting-scatter kernel (one-hot MXU scatter with sorted-range skip)
# ---------------------------------------------------------------------------


_BROWS = 8  # batch rows per scatter block (TPU sublane granularity)


def _scatter_kernel(cmin_ref, cmax_ref, docs_ref, vals_ref, out_ref):
    b = pl.program_id(0)
    t = pl.program_id(1)
    c = pl.program_id(2)

    @pl.when(c == 0)
    def _zero():
        out_ref[...] = jnp.zeros_like(out_ref)

    tile_lo = t * LANES
    # whole-block skip: does ANY of the 8 rows' chunk range touch this
    # doc tile? (rows are independent queries; posting chunks are
    # doc-sorted so the [min, max] test prunes most (tile, chunk) pairs)
    hit = jnp.zeros((), jnp.bool_)
    for r in range(_BROWS):
        row = b * _BROWS + r
        hit = hit | ((cmax_ref[row, c] >= tile_lo)
                     & (cmin_ref[row, c] < tile_lo + LANES))

    @pl.when(hit)
    def _accumulate():
        docs = docs_ref[...]                   # [8, 128] int32
        vals = vals_ref[...]                   # [8, 128] f32
        local = docs - tile_lo
        iota = jax.lax.broadcasted_iota(jnp.int32, (_BROWS, LANES, LANES),
                                        2)
        onehot = (local[:, :, None] == iota).astype(jnp.float32)
        # contribution[r, j] = sum_i vals[r, i] * onehot[r, i, j]
        # (batched MXU contract over the 8 rows)
        contrib = jax.lax.dot_general(
            vals[:, None, :], onehot,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)   # [8, 1, 128]
        out_ref[...] += contrib[:, 0, :]


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def scatter_add_pallas(docs: jax.Array, vals: jax.Array, cap: int,
                       interpret: bool = False) -> jax.Array:
    """scores[b, docs[b, n]] += vals[b, n]; docs >= cap (padding) drop.

    docs: int32 [B, N] sorted non-decreasing per (query, term) run —
    segment posting blocks are doc-sorted, which is what makes the
    per-chunk [min, max] tile skip effective. Correctness does NOT
    depend on sortedness, only performance.
    """
    b, n = docs.shape
    n_pad = -(-n // LANES) * LANES
    cap_pad = -(-cap // LANES) * LANES
    b_pad = -(-b // _BROWS) * _BROWS
    if n_pad != n:
        docs = jnp.pad(docs, ((0, 0), (0, n_pad - n)),
                       constant_values=cap_pad)
        vals = jnp.pad(vals, ((0, 0), (0, n_pad - n)))
    if b_pad != b:
        docs = jnp.pad(docs, ((0, b_pad - b), (0, 0)),
                       constant_values=cap_pad)
        vals = jnp.pad(vals, ((0, b_pad - b), (0, 0)))
    # OOB padding (== cap) must never land in a tile: clamp into a
    # sentinel range past cap_pad so the range skip drops those chunks
    docs = jnp.where(docs >= cap, cap_pad + LANES, docs)
    chunks = docs.reshape(b_pad, n_pad // LANES, LANES)
    cmin = chunks.min(axis=-1).astype(jnp.int32)     # [B, C]
    cmax = chunks.max(axis=-1).astype(jnp.int32)
    # padded chunk rows (all sentinel) have cmin > cap_pad -> skipped
    grid = (b_pad // _BROWS, cap_pad // LANES, n_pad // LANES)
    out = pl.pallas_call(
        _scatter_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((_BROWS, LANES),
                             lambda b_, t, c, *_: (b_, c)),
                pl.BlockSpec((_BROWS, LANES),
                             lambda b_, t, c, *_: (b_, c)),
            ],
            out_specs=pl.BlockSpec((_BROWS, LANES),
                                   lambda b_, t, c, *_: (b_, t)),
        ),
        out_shape=jax.ShapeDtypeStruct((b_pad, cap_pad), jnp.float32),
        interpret=interpret,
    )(cmin, cmax, docs.reshape(b_pad, n_pad), vals.reshape(b_pad, n_pad))
    return out[:b, :cap]


# ---------------------------------------------------------------------------
# fused block-max score + top-k kernel (forward-index path, bool bundles)
# ---------------------------------------------------------------------------
#
# One kernel walks (batch tile, doc tile) grid cells. The doc-tile axis
# is the INNER grid dimension, which TPU executes sequentially, so a
# VMEM scratch row carries each query's running top-k threshold across
# the tiles of its batch tile ("running per-query threshold in on-chip
# memory"). Per tile the kernel evaluates the WHOLE clause bundle (see
# ops/scoring.py: must/should scoring clauses over ANY mix of text
# fields + dense or numeric-range filter/must_not masks, single-should
# wrappers with per-clause msm/boost) and emits the tile-local top-k
# candidates (ck = min(k, tile) values + doc ids), the exact match
# count, a prune flag, and — in emit-match mode — the exact per-tile
# match mask (so k>0+aggs plans stay fused on Pallas; a downstream
# aggregation pass consumes the mask). A single cheap lax.top_k over
# the [B, n_tiles * ck] candidate strip — ~k/tile the size of the
# [B, cap] matrix the unfused path materializes — merges them.
# Candidate order (tile-ascending, within-tile ties doc-ascending)
# makes the merge reproduce the global lax.top_k tie-breaking exactly.
# A ck == 0 build of the same kernel is the mask-only k == 0 grid:
# no candidates, no threshold, just exact counts + mask.
#
# The per-tile can_match/bound vectors are precomputed OUTSIDE the
# kernel (ops/scoring.bundle_tile_bounds — [B, J] is tiny, and SHARED
# with the XLA engine so both backends prune identically); range masks
# are then re-evaluated per doc inside the kernel from the numeric
# columns in VMEM, exactly like ops/scoring.bundle_tile_eval.
#
# The in-kernel threshold is the max over processed tiles of the tile's
# k-th best score — a lower bound on the global k-th best backed by k
# lower-doc-id candidates, so `bound <= thr` tiles can skip extraction
# without changing the result (ties lose to the earlier docs anyway).
# It is only maintained when ck == k; a narrower tile cannot witness k
# candidates and the threshold stays -inf (no threshold pruning). The
# STEPPED form (step != None) partitions the doc-tile grid into chunks
# of pallas_call invocations and threads the threshold through a
# [B, 1] in/out pair, so pruning state survives the chunk boundary —
# a chunked walk is bit-identical to the single-call walk.

# per-tile selection unrolls (max, lowest-argmax, mask) passes up to
# this ck; beyond it a lax.fori_loop runs the same passes with a
# carried candidate buffer — the multi-pass form that lifts the old
# hard ck cap without minting pathological unrolled programs
_CK_UNROLL = 128


def _meta_for(clauses: tuple) -> tuple[tuple, tuple, tuple]:
    """Static kernel layout of a clause bundle: (text_fields,
    num_fields, pos_fields) in first-occurrence order. Dense clauses
    index text_fields (slot-major forward blocks); positional clauses
    index pos_fields (doc-major tids + positions + per-doc norms);
    everything else is a range clause and indexes num_fields (and its
    own (lo, hi) input pair). Positional kinds MUST be carved out here:
    their `field` slot can be a tuple (bm25f) and they carry no (lo,
    hi) pair, so lumping them with ranges would desync the ref walk."""
    from .scoring import (DENSE_CLAUSE_KINDS, bundle_pos_fields,
                          positional_prefix)
    text_fields = tuple(dict.fromkeys(
        f for _r, kd, f, _w in clauses if kd in DENSE_CLAUSE_KINDS))
    num_fields = tuple(dict.fromkeys(
        f for _r, kd, f, _w in clauses
        if kd not in DENSE_CLAUSE_KINDS and not positional_prefix(kd)))
    return text_fields, num_fields, bundle_pos_fields(clauses)


def _pos_param_arrays(clauses: tuple, cl_inputs: tuple
                      ) -> tuple[list, tuple]:
    """Flatten positional clause params into kernel-ready [B, x]
    columns, in clause order. Returns (arrays, pad_values) — the pad
    value feeds _pad_bundle_rows (qt pads -1 so inert batch rows decode
    zero positions and zero frequency; everything else pads 0).

    Per phrase/span clause (7 arrays): qt [B, n] i32, wb [B, n] f32,
    idf_sum / slop / pboost / msm_c / boost_c as [B, 1] columns.
    Per bm25f clause (6 arrays): qt [B, nf*nt] i32 (the [B, nf, nt]
    cube flattened — the kernel re-folds it from the static kind),
    idf [B, nt] f32, wf [B, nf] f32, pboost / msm_c / boost_c
    [B, 1]."""
    from .scoring import positional_prefix
    flat: list = []
    pads: list = []

    def _put(a, pad=0):
        flat.append(a)
        pads.append(pad)

    for (_r, kind, _f, _w), inp in zip(clauses, cl_inputs):
        head = positional_prefix(kind)
        if head is None:
            continue
        if head == "bm25f":
            qt, idf, wf, pb, mc, bc = inp
            b = qt.shape[0]
            _put(jnp.asarray(qt).reshape(b, -1), -1)
            _put(jnp.asarray(idf))
            _put(jnp.asarray(wf))
        else:
            qt, wb, idf_sum, slop, pb, mc, bc = inp
            _put(jnp.asarray(qt), -1)
            _put(jnp.asarray(wb))
            _put(jnp.asarray(idf_sum)[:, None])
            _put(jnp.asarray(slop)[:, None].astype(jnp.int32))
        _put(jnp.asarray(pb)[:, None].astype(jnp.float32))
        _put(jnp.asarray(mc)[:, None].astype(jnp.int32))
        _put(jnp.asarray(bc)[:, None].astype(jnp.float32))
    return flat, tuple(pads)


def _make_bundle_kernel(clauses: tuple, *, qm: int, ck: int,
                        update_thr: bool, emit_match: bool, tile: int,
                        t0: int):
    """Build the fused-bundle kernel for one (clauses, shape) pair.

    Ref layout (inputs): qt, wq [bt, Cd*qm]; msmc, boostc [bt, Cd];
    msm, boost, canm, ub [bt, 1]; (thr_in [bt, 1] when ck > 0); one
    (lo, hi) [bt, 1] pair per range clause; the flat positional param
    columns (_pos_param_arrays order) per positional clause; one
    (tids, imps) [L_f, tile] pair per text field; one (tids [tile, L_f]
    doc-major, pos [tile, L_f*P], k1ln [1, tile], lnorm [1, tile])
    quad per positional field; one (vals, exists) [1, tile] pair per
    numeric field; live [1, tile]. Outputs: (cs, ci [bt, ck], when
    ck > 0); cnt, flag [bt, 1]; (thr_out [bt, 1] when ck > 0); (match
    [bt, tile] i32 when emit_match). Scratch: thr [bt, LANES] when
    ck > 0. `t0` is the chunk's first tile (static): candidate doc ids
    are global, so chunked and single-call walks emit identical ids."""
    from .scoring import (DENSE_CLAUSE_KINDS, positional_prefix,
                          positional_tile_scores)
    text_fields, num_fields, pos_fields = _meta_for(clauses)
    n_range = len([1 for _r, kd, _f, _w in clauses
                   if kd not in DENSE_CLAUSE_KINDS
                   and not positional_prefix(kd)])
    pos_widths = [(6 if positional_prefix(kd) == "bm25f" else 7)
                  for _r, kd, _f, _w in clauses if positional_prefix(kd)]

    def kernel(*refs):
        it = iter(refs)
        qt_ref, wq_ref, msmc_ref, boostc_ref = (next(it) for _ in range(4))
        msm_ref, boost_ref, canm_ref, ub_ref = (next(it) for _ in range(4))
        thr_in_ref = next(it) if ck > 0 else None
        range_refs = [(next(it), next(it)) for _ in range(n_range)]
        pos_param_refs = [tuple(next(it) for _ in range(w))
                          for w in pos_widths]
        text_refs = {f: (next(it), next(it)) for f in text_fields}
        pos_refs = {f: tuple(next(it) for _ in range(4))
                    for f in pos_fields}
        num_refs = {f: (next(it), next(it)) for f in num_fields}
        live_ref = next(it)
        cs_ref = ci_ref = thr_out_ref = thr_scr = None
        if ck > 0:
            cs_ref, ci_ref = next(it), next(it)
        cnt_ref, flag_ref = next(it), next(it)
        if ck > 0:
            thr_out_ref = next(it)
        match_ref = next(it) if emit_match else None
        if ck > 0:
            thr_scr = next(it)

        j = pl.program_id(1)
        if ck > 0:
            @pl.when(j == 0)
            def _seed_thr():
                # chunked walks seed from the previous chunk's final
                # threshold; the first chunk (and the un-stepped single
                # call) seeds -inf from the caller
                thr_scr[...] = jnp.broadcast_to(thr_in_ref[...],
                                                thr_scr.shape)

        ub = ub_ref[...]                       # [bt, 1] f32 tile bound
        can_hit = canm_ref[...] > 0            # [bt, 1] msm-aware prune
        thr = thr_scr[:, 0:1] if ck > 0 else None
        any_hit = jnp.any(can_hit)

        @pl.when(jnp.logical_not(any_hit))
        def _hard_skip():
            # no query can match in this tile: nothing to score OR
            # count, and the mask rows provably stay zero
            if ck > 0:
                cs_ref[...] = jnp.full_like(cs_ref, -jnp.inf)
                ci_ref[...] = jnp.zeros_like(ci_ref)
            cnt_ref[...] = jnp.zeros_like(cnt_ref)
            flag_ref[...] = jnp.full_like(flag_ref, 2)
            if emit_match:
                match_ref[...] = jnp.zeros_like(match_ref)

        @pl.when(any_hit)
        def _score():
            qt = qt_ref[...]                   # [bt, Cd*qm]
            wq = wq_ref[...]
            msmc = msmc_ref[...]               # [bt, Cd] i32
            boostc = boostc_ref[...]           # [bt, Cd] f32
            b_n = qt.shape[0]
            acc = jnp.zeros((b_n, tile), jnp.float32)
            must_ok = jnp.ones((b_n, tile), bool)
            not_any = jnp.zeros((b_n, tile), bool)
            scnt = jnp.zeros((b_n, tile), jnp.int32)
            # positional columns for this doc tile, in the exact shapes
            # positional_tile_scores (the shared leaf evaluator — also
            # what bundle_tile_eval runs on the XLA engine) consumes:
            # text view (t_tids [tile, L], imps unused), pos view
            # (t_pos [tile, L*P], k1ln [tile], lnorm [tile])
            ptext = {f: (pos_refs[f][0][...], None) for f in pos_fields}
            ptiles = {f: (pos_refs[f][1][...], pos_refs[f][2][...][0],
                          pos_refs[f][3][...][0]) for f in pos_fields}
            # static clause unroll in eval_node order (must, filter,
            # must_not, should — the caller guarantees the ordering);
            # per-clause ops mirror ops/scoring.bundle_tile_eval so
            # fused-pallas scores stay identical to fused-xla
            dc = ri = pc = 0
            for role, kind, field, _w in clauses:
                if kind in DENSE_CLAUSE_KINDS:
                    tids_ref, imps_ref = text_refs[field]
                    tids = tids_ref[...]       # [L_f, tile] slot-major
                    imps = imps_ref[...]
                    n_slots = tids.shape[0]
                    s_leaf = jnp.zeros((b_n, tile), jnp.float32)
                    for q in range(qm):
                        tq = qt[:, dc * qm + q]
                        hit = jnp.zeros((b_n, tile), jnp.float32)
                        for l in range(n_slots):
                            eq = tids[l][None, :] == tq[:, None]
                            hit = hit + jnp.where(eq, imps[l][None, :],
                                                  0.0)
                        s_leaf = s_leaf + hit * wq[:, dc * qm + q][:, None]
                    m_leaf = s_leaf > 0.0
                    msm_c = msmc[:, dc:dc + 1]
                    m = (m_leaf | (msm_c <= 0)) & (msm_c <= 1)
                    s = jnp.where(m_leaf, s_leaf, 0.0) \
                        * boostc[:, dc:dc + 1]
                    dc += 1
                elif positional_prefix(kind):
                    # phrase / span / bm25f leaf: delegate to the SHARED
                    # evaluator (ops/scoring.positional_tile_scores) so
                    # the in-kernel f32 chain is op for op the XLA
                    # engine's — padded batch rows carry qt = -1 and
                    # decode zero frequency, exactly like dense pads
                    prefs = pos_param_refs[pc]
                    pc += 1
                    if positional_prefix(kind) == "bm25f":
                        nf = len(field)
                        qt_p = prefs[0][...]
                        inp = (qt_p.reshape(b_n, nf,
                                            qt_p.shape[1] // nf),
                               prefs[1][...], prefs[2][...],
                               prefs[3][...][:, 0], None, None)
                        msm_p, boost_p = prefs[4][...], prefs[5][...]
                    else:
                        inp = (prefs[0][...], prefs[1][...],
                               prefs[2][...][:, 0], prefs[3][...][:, 0],
                               prefs[4][...][:, 0], None, None)
                        msm_p, boost_p = prefs[5][...], prefs[6][...]
                    s_leaf, m_leaf = positional_tile_scores(
                        kind, field, inp, ptext, ptiles)
                    m = (m_leaf | (msm_p <= 0)) & (msm_p <= 1)
                    s = jnp.where(m_leaf, s_leaf, 0.0) * boost_p
                else:
                    # numeric range mask, evaluated per doc in VMEM —
                    # the same compare bundle_tile_eval runs, in the
                    # column's device dtype
                    lo_ref, hi_ref = range_refs[ri]
                    vals_ref, ex_ref = num_refs[field]
                    ri += 1
                    vals = vals_ref[...]       # [1, tile]
                    m = ((vals >= lo_ref[...]) & (vals <= hi_ref[...])
                         & (ex_ref[...] > 0))
                    s = None
                if role == "must":
                    acc = acc + jnp.where(m, s, 0.0)
                    must_ok = must_ok & m
                elif role == "filter":
                    must_ok = must_ok & m
                elif role == "must_not":
                    not_any = not_any | m
                else:
                    if s is not None:
                        acc = acc + jnp.where(m, s, 0.0)
                    scnt = scnt + m.astype(jnp.int32)
            live = live_ref[...] > 0           # [1, tile]
            match = (must_ok & jnp.logical_not(not_any)
                     & (scnt >= msm_ref[...]) & live)
            acc = acc * boost_ref[...]         # post-accum outer boost
            cnt_ref[...] = jnp.sum(match, axis=1, keepdims=True
                                   ).astype(jnp.int32)
            if emit_match:
                # exact mask regardless of threshold pruning below —
                # the aggregation pass consumes every tile's mask
                match_ref[...] = match.astype(jnp.int32)
            if ck == 0:
                # mask-only grid: counting + mask IS the result
                flag_ref[...] = jnp.zeros_like(flag_ref)
                return
            can_top = can_hit & (ub > thr)
            any_top = jnp.any(can_top)

            @pl.when(jnp.logical_not(any_top))
            def _thresholded():
                # exact counting happened above; candidates cannot
                # improve any query's top-k, skip the extraction
                cs_ref[...] = jnp.full_like(cs_ref, -jnp.inf)
                ci_ref[...] = jnp.zeros_like(ci_ref)
                flag_ref[...] = jnp.ones_like(flag_ref)

            @pl.when(any_top)
            def _select():
                # ck passes of (max, lowest-argmax, mask): ties come
                # out in ascending doc order, matching lax.top_k's tie
                # rule. Unrolled while small; a fori_loop with a
                # carried candidate buffer past _CK_UNROLL (identical
                # passes, bounded program size).
                cand = jnp.where(match, acc, -jnp.inf)
                idx = jax.lax.broadcasted_iota(jnp.int32, (b_n, tile), 1)
                if ck <= _CK_UNROLL:
                    vs = []
                    ps = []
                    for _s in range(ck):
                        mx = jnp.max(cand, axis=1, keepdims=True)
                        pos = jnp.min(jnp.where(cand == mx, idx, tile),
                                      axis=1, keepdims=True)
                        vs.append(mx)
                        ps.append(pos)
                        cand = jnp.where(idx == pos, -jnp.inf, cand)
                    v = jnp.concatenate(vs, axis=1)            # [bt,ck]
                    p = jnp.concatenate(ps, axis=1)
                else:
                    def sel_body(s, carry):
                        cand, v, p = carry
                        mx = jnp.max(cand, axis=1, keepdims=True)
                        pos = jnp.min(jnp.where(cand == mx, idx, tile),
                                      axis=1, keepdims=True)
                        v = jax.lax.dynamic_update_slice(v, mx, (0, s))
                        p = jax.lax.dynamic_update_slice(p, pos, (0, s))
                        cand = jnp.where(idx == pos, -jnp.inf, cand)
                        return cand, v, p
                    _, v, p = jax.lax.fori_loop(
                        0, ck, sel_body,
                        (cand, jnp.full((b_n, ck), -jnp.inf, jnp.float32),
                         jnp.zeros((b_n, ck), jnp.int32)))
                cs_ref[...] = v
                ci_ref[...] = jnp.where(v > -jnp.inf,
                                        p + (j + t0) * tile, 0)
                flag_ref[...] = jnp.zeros_like(flag_ref)
                if update_thr:
                    thr_scr[:, 0:1] = jnp.maximum(thr, v[:, ck - 1:ck])

        if ck > 0:
            # written every grid step (last j wins — the inner grid is
            # sequential): the chunk's final per-query threshold, fed
            # to the next chunk's thr_in
            thr_out_ref[...] = thr_scr[:, 0:1]

    return kernel


def _pad_bundle_rows(arrs: dict, pad_b: int) -> dict:
    """Pad the batch axis with INERT rows: can_match=0 keeps them out of
    every batch-wide prune vote, and msm=2 with zero should votes
    matches nothing, so their exact counts (and mask rows) are 0."""
    out = dict(arrs)
    out["qt"] = jnp.pad(arrs["qt"], ((0, pad_b), (0, 0)),
                        constant_values=-1)
    out["wq"] = jnp.pad(arrs["wq"], ((0, pad_b), (0, 0)))
    out["msmc"] = jnp.pad(arrs["msmc"], ((0, pad_b), (0, 0)),
                          constant_values=1)
    out["boostc"] = jnp.pad(arrs["boostc"], ((0, pad_b), (0, 0)),
                            constant_values=1.0)
    out["msm"] = jnp.pad(arrs["msm"], ((0, pad_b), (0, 0)),
                         constant_values=2)
    out["boost"] = jnp.pad(arrs["boost"], ((0, pad_b), (0, 0)),
                           constant_values=1.0)
    out["can"] = jnp.pad(arrs["can"], ((0, pad_b), (0, 0)))
    out["ub"] = jnp.pad(arrs["ub"], ((0, pad_b), (0, 0)))
    out["ranges"] = tuple(
        (jnp.pad(lo, ((0, pad_b), (0, 0))),
         jnp.pad(hi, ((0, pad_b), (0, 0))))
        for lo, hi in arrs["ranges"])
    out["pos"] = tuple(
        jnp.pad(a, ((0, pad_b), (0, 0)), constant_values=c)
        for a, c in zip(arrs["pos"], arrs["pos_pad"]))
    return out


def _bundle_chunk_call(clauses: tuple, arrs: dict, text_cols: dict,
                       num_cols: dict, live: jax.Array, *, qm: int,
                       ck: int, update_thr: bool, emit_match: bool,
                       tile: int, t0: int, nt: int, btile: int, bp: int,
                       interpret: bool, thr=None):
    """One pallas_call over the doc-tile span [t0, t0 + nt): the whole
    grid when step is None, one chunk of the stepped walk otherwise.
    Returns (cs, ci,)? cnt, flags (, match)? (, thr_out)? — candidate
    strips and counters covering this span only."""
    text_fields, num_fields, pos_fields = _meta_for(clauses)
    kern = _make_bundle_kernel(clauses, qm=qm, ck=ck,
                               update_thr=update_thr,
                               emit_match=emit_match, tile=tile, t0=t0)
    qw = arrs["qt"].shape[1]
    n_dense = arrs["msmc"].shape[1]

    def _bcast(bi, j):
        return (bi, 0)

    def _per_tile(bi, j, t0=t0):
        return (bi, j + t0)

    def _col(bi, j, t0=t0):
        return (0, j + t0)

    def _out(bi, j):
        return (bi, j)

    in_specs = [
        pl.BlockSpec((btile, max(qw, 1)), _bcast, memory_space=pltpu.VMEM),
        pl.BlockSpec((btile, max(qw, 1)), _bcast, memory_space=pltpu.VMEM),
        pl.BlockSpec((btile, max(n_dense, 1)), _bcast,
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((btile, max(n_dense, 1)), _bcast,
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((btile, 1), _bcast, memory_space=pltpu.VMEM),
        pl.BlockSpec((btile, 1), _bcast, memory_space=pltpu.VMEM),
        pl.BlockSpec((btile, 1), _per_tile, memory_space=pltpu.VMEM),
        pl.BlockSpec((btile, 1), _per_tile, memory_space=pltpu.VMEM),
    ]
    inputs = [arrs["qt"], arrs["wq"], arrs["msmc"], arrs["boostc"],
              arrs["msm"], arrs["boost"], arrs["can"], arrs["ub"]]
    if ck > 0:
        in_specs.append(pl.BlockSpec((btile, 1), _bcast,
                                     memory_space=pltpu.VMEM))
        inputs.append(thr)
    for lo, hi in arrs["ranges"]:
        in_specs.extend([
            pl.BlockSpec((btile, 1), _bcast, memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, 1), _bcast, memory_space=pltpu.VMEM)])
        inputs.extend([lo, hi])
    for a in arrs["pos"]:
        in_specs.append(pl.BlockSpec((btile, a.shape[1]), _bcast,
                                     memory_space=pltpu.VMEM))
        inputs.append(a)
    for f in text_fields:
        slots = text_cols[f]["fwd_tids"].shape[1]
        in_specs.extend([
            pl.BlockSpec((slots, tile), _col, memory_space=pltpu.VMEM),
            pl.BlockSpec((slots, tile), _col, memory_space=pltpu.VMEM)])
        inputs.extend([text_cols[f]["fwd_tids"].T,
                       text_cols[f]["fwd_imps"].T])
    for f in pos_fields:
        # doc-major blocks: positional decoding reads whole doc rows
        # (tids to locate the term's slot window, pos for the deltas),
        # so each grid step slices a [tile, ...] row band instead of
        # the dense path's slot-major columns
        def _row(bi, j, t0=t0):
            return (j + t0, 0)
        slots = text_cols[f]["fwd_tids"].shape[1]
        pw = text_cols[f]["fwd_pos"].shape[1]
        in_specs.extend([
            pl.BlockSpec((tile, slots), _row, memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, pw), _row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), _col, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), _col, memory_space=pltpu.VMEM)])
        inputs.extend([text_cols[f]["fwd_tids"],
                       text_cols[f]["fwd_pos"],
                       text_cols[f]["k1ln"][None, :],
                       text_cols[f]["lnorm"][None, :]])
    for f in num_fields:
        in_specs.extend([
            pl.BlockSpec((1, tile), _col, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), _col, memory_space=pltpu.VMEM)])
        inputs.extend([num_cols[f]["values"][None, :],
                       num_cols[f]["exists"].astype(jnp.int32)[None, :]])
    in_specs.append(pl.BlockSpec((1, tile), _col,
                                 memory_space=pltpu.VMEM))
    inputs.append(live.astype(jnp.int32)[None, :])

    out_specs = []
    out_shape = []
    if ck > 0:
        out_specs.extend([
            pl.BlockSpec((btile, ck), _out, memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, ck), _out, memory_space=pltpu.VMEM)])
        out_shape.extend([
            jax.ShapeDtypeStruct((bp, nt * ck), jnp.float32),
            jax.ShapeDtypeStruct((bp, nt * ck), jnp.int32)])
    out_specs.extend([
        pl.BlockSpec((btile, 1), _out, memory_space=pltpu.VMEM),
        pl.BlockSpec((btile, 1), _out, memory_space=pltpu.VMEM)])
    out_shape.extend([
        jax.ShapeDtypeStruct((bp, nt), jnp.int32),
        jax.ShapeDtypeStruct((bp, nt), jnp.int32)])
    if ck > 0:
        out_specs.append(pl.BlockSpec((btile, 1), _bcast,
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((bp, 1), jnp.float32))
    if emit_match:
        out_specs.append(pl.BlockSpec((btile, tile), _out,
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((bp, nt * tile), jnp.int32))
    scratch = [pltpu.VMEM((btile, LANES), jnp.float32)] if ck > 0 else []
    return pl.pallas_call(
        kern,
        grid=(bp // btile, nt),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*inputs)


def _stack_bundle_inputs(clauses: tuple, cl_inputs: tuple):
    """Clause-stacked kernel inputs: every dense clause padded to
    qm = max clause width (tid -1 / weight 0 padding contributes an
    exact 0.0); range clauses contribute their (lo, hi) pairs as
    [B, 1] columns. Positional clauses ride their own flat param
    columns (_pos_param_arrays) and contribute nothing here; a bundle
    with NO dense clause (pure phrase / span / bm25f) gets one inert
    dummy column (qt = -1, weight 0) so the fixed leading refs keep
    their shapes."""
    from .scoring import DENSE_CLAUSE_KINDS, positional_prefix
    dense = [(inp if kind in DENSE_CLAUSE_KINDS else None)
             for (r, kind, f, w), inp in zip(clauses, cl_inputs)]
    qm = max((inp[0].shape[1] for inp in dense if inp is not None),
             default=1)
    qts, wqs, msmcs, boostcs, ranges = [], [], [], [], []
    for (r, kind, f, w), inp in zip(clauses, cl_inputs):
        if kind in DENSE_CLAUSE_KINDS:
            qt, wq, msm_c, boost_c = inp
            pad = qm - qt.shape[1]
            if pad:
                qt = jnp.pad(qt, ((0, 0), (0, pad)), constant_values=-1)
                wq = jnp.pad(wq, ((0, 0), (0, pad)))
            qts.append(qt)
            wqs.append(wq)
            msmcs.append(msm_c)
            boostcs.append(boost_c)
        elif positional_prefix(kind):
            continue
        else:
            lo, hi = inp
            ranges.append((lo[:, None], hi[:, None]))
    if not qts:
        b = cl_inputs[0][0].shape[0]
        return (qm, jnp.full((b, qm), -1, jnp.int32),
                jnp.zeros((b, qm), jnp.float32),
                jnp.ones((b, 1), jnp.int32),
                jnp.ones((b, 1), jnp.float32), tuple(ranges))
    return (qm, jnp.concatenate(qts, axis=1), jnp.concatenate(wqs, axis=1),
            jnp.stack(msmcs, axis=1),
            jnp.stack(boostcs, axis=1).astype(jnp.float32), tuple(ranges))


def _bundle_pallas_walk(text_cols: dict, num_cols: dict, clauses: tuple,
                        cl_inputs: tuple, msm: jax.Array,
                        boost: jax.Array | None, live: jax.Array, *,
                        ck: int, update_thr: bool, emit_match: bool,
                        step, interpret: bool, thr_init=None):
    """ONE driver for both public entries (k>0 candidates and the
    ck == 0 mask-only grid): bounds, clause stacking, inert-row
    padding, and the walk — a single pallas_call over the whole grid,
    or the STEPPED chunk loop (one pallas_call per chunk, running
    threshold carried through a [B, 1] in/out pair, candidates /
    counts / prune flags concatenated across chunks, `check` hosted
    between kernel invocations with a FINAL check after the last chunk
    — the ops/scoring._stepped_tile_loop contract). Returns
    (cs, ci, cnt, flags, match, timed, b, btile, bp); cs/ci are None
    when ck == 0, match when not emit_match, timed when step is None."""
    from .scoring import bundle_tile_bounds, bundle_primary_field
    cap = live.shape[0]
    field0 = bundle_primary_field(clauses)
    n_tiles = text_cols[field0]["tile_max"].shape[1]
    tile = cap // n_tiles
    b = msm.shape[0]
    can_match, ub = bundle_tile_bounds(clauses, cl_inputs, text_cols,
                                       num_cols, msm, boost)
    boost_arr = boost if boost is not None \
        else jnp.ones((b,), jnp.float32)
    qm, qt_all, wq_all, msmc, boostc, ranges = _stack_bundle_inputs(
        clauses, cl_inputs)
    pos_flat, pos_pads = _pos_param_arrays(clauses, cl_inputs)
    # positional decoding materializes [bt, tile, ..] position cubes in
    # the kernel; shrink the batch tile so the working set stays inside
    # scoped VMEM (the admission gate bounds L*P separately)
    btile = min(8 if pos_flat else _BATCH_TILE, b)
    pad_b = (-b) % btile
    arrs = {"qt": qt_all, "wq": wq_all, "msmc": msmc, "boostc": boostc,
            "msm": msm[:, None].astype(jnp.int32),
            "boost": boost_arr[:, None].astype(jnp.float32),
            "can": can_match.astype(jnp.int32), "ub": ub,
            "ranges": ranges, "pos": tuple(pos_flat),
            "pos_pad": pos_pads}
    if pad_b:
        arrs = _pad_bundle_rows(arrs, pad_b)
    bp = b + pad_b
    chunk = functools.partial(
        _bundle_chunk_call, clauses, arrs, text_cols, num_cols, live,
        qm=qm, ck=ck, update_thr=update_thr, emit_match=emit_match,
        tile=tile, btile=btile, bp=bp, interpret=interpret)
    # fixed slots in a chunk call's output list: candidates only exist
    # for ck > 0, the threshold rides behind the counters
    n_cand = 2 if ck > 0 else 0
    thr0 = (jnp.full((bp, 1), -jnp.inf, jnp.float32) if ck > 0 else None)
    if ck > 0 and thr_init is not None:
        # delta-walk threshold seed (streaming write path): the base
        # walk's k-th best opens this walk's threshold, so delta tiles
        # prune against the base exactly as base tiles prune against
        # each other; a tied delta doc loses the merge anyway (base
        # candidates concatenate first), so seeding stays exact
        thr0 = thr0.at[: thr_init.shape[0]].set(thr_init)

    def _unpack(out):
        cs = out[0] if ck > 0 else None
        ci = out[1] if ck > 0 else None
        cnt, flags = out[n_cand], out[n_cand + 1]
        thr = out[n_cand + 2] if ck > 0 else None
        match = out[-1] if emit_match else None
        return cs, ci, cnt, flags, thr, match

    if step is None:
        out = chunk(t0=0, nt=n_tiles, thr=thr0) if ck > 0 \
            else chunk(t0=0, nt=n_tiles)
        cs, ci, cnt, flags, _thr, match = _unpack(list(out))
        return cs, ci, cnt, flags, match, None, b, btile, bp

    chunk_tiles, ck0, check = step
    n_chunks = -(-n_tiles // chunk_tiles)
    parts: list[list] = [[], [], [], [], []]       # cs ci cnt flags match
    thr = thr0
    st = ck0
    timed = jnp.bool_(False)
    for c in range(n_chunks):
        t0 = c * chunk_tiles
        nt = min(chunk_tiles, n_tiles - t0)
        timed, st = check(c, st)

        def _run(thr, t0=t0, nt=nt):
            return tuple(chunk(t0=t0, nt=nt, thr=thr)) if ck > 0 \
                else tuple(chunk(t0=t0, nt=nt))

        def _skip(thr, nt=nt):
            # a preempted chunk's tiles report as thresholded; the
            # caller discards the whole result on timed_out anyway
            out = ()
            if ck > 0:
                out = (jnp.full((bp, nt * ck), -jnp.inf, jnp.float32),
                       jnp.zeros((bp, nt * ck), jnp.int32))
            out = out + (jnp.zeros((bp, nt), jnp.int32),
                         jnp.ones((bp, nt), jnp.int32))
            if ck > 0:
                out = out + (thr,)
            if emit_match:
                out = out + (jnp.zeros((bp, nt * tile), jnp.int32),)
            return out

        out = jax.lax.cond(timed, _skip, _run, thr)
        cs_c, ci_c, cnt_c, flags_c, thr, match_c = _unpack(list(out))
        for dst, val in zip(parts, (cs_c, ci_c, cnt_c, flags_c,
                                    match_c)):
            if val is not None:
                dst.append(val)
    # one FINAL check after the last chunk (the same contract as
    # ops/scoring._stepped_tile_loop): a deadline expiring during the
    # last chunk's kernel must still report timed_out
    final, _st = check(n_chunks, st)
    timed = timed | final
    cat = [jnp.concatenate(p, axis=1) if p else None for p in parts]
    return cat[0], cat[1], cat[2], cat[3], cat[4], timed, b, btile, bp


def fused_topk_bundle_pallas(text_cols: dict, num_cols: dict,
                             clauses: tuple, cl_inputs: tuple,
                             msm: jax.Array, boost: jax.Array | None,
                             live: jax.Array, k: int,
                             emit_match: bool = False, step=None,
                             interpret: bool = False,
                             init_topk=None, idx_offset: int = 0):
    """Pallas counterpart of ops.scoring.score_topk_bundle_fused — the
    SAME calling convention, covering the full bundle admission matrix:
    multi-text-field bundles (one forward-index block pair per field),
    dense + numeric-range filter/must_not masks (evaluated per tile in
    VMEM from the same columns the XLA engine reads), and emit-match
    mode (exact [B, cap] match mask for a downstream aggregation pass).

    can_match/ub come from bundle_tile_bounds — shared with the XLA
    engine so both backends prune identically. Returns (top_s [B,k],
    top_i [B,k], total [B], prune_stats f32 [3] = (hard, thresholded,
    examined) in doc-tile units: per-(batch-tile, doc-tile) decisions
    are averaged over batch tiles so examined == n_tiles, matching the
    XLA backend's batch-wide counters), plus the match mask [B, cap]
    bool when emit_match, plus the timed_out scalar when a `step` (see
    ops/scoring._stepped_tile_loop) is given — the stepped form runs
    one pallas_call per chunk with the running threshold, candidates,
    and prune counters carried across chunk boundaries, hosting the
    per-chunk deadline callback BETWEEN kernel invocations."""
    from .scoring import bundle_primary_field, running_topk_merge
    cap = live.shape[0]
    k = min(k, cap) if init_topk is None else init_topk[0].shape[1]
    k_sel = min(k, cap)
    n_tiles = text_cols[bundle_primary_field(clauses)]["tile_max"].shape[1]
    ck = min(k_sel, cap // n_tiles)
    cs, ci, cnt, flags, match, timed, b, btile, bp = _bundle_pallas_walk(
        text_cols, num_cols, clauses, cl_inputs, msm, boost, live,
        ck=ck, update_thr=(ck == k_sel), emit_match=emit_match, step=step,
        interpret=interpret,
        thr_init=(None if init_topk is None
                  else init_topk[0][:, -1:]))
    # tile-major candidate strip: global top_k tie-breaks by flat index,
    # i.e. (tile asc, within-tile rank) — lower doc ids win ties, the
    # same order one lax.top_k over the full score matrix produces
    top_s, pos = jax.lax.top_k(cs[:b], min(k_sel, cs.shape[1]))
    top_i = jnp.take_along_axis(ci[:b], pos, axis=1) + idx_offset
    if init_topk is not None:
        # chain onto the earlier (base) walk's selection: existing
        # state first, so base docs win ties — the same merge rule the
        # XLA engine's carried running top-k applies
        top_s, top_i = running_topk_merge(init_topk[0], init_topk[1],
                                          top_s, top_i)
    total = cnt[:b].sum(axis=1)
    pruned = _normalize_prune(flags, btile, bp)
    out = (top_s, top_i, total, pruned)
    if emit_match:
        out = out + ((match[:b] != 0),)
    return out if timed is None else out + (timed,)


def _normalize_prune(flags: jax.Array, btile: int, bp: int) -> jax.Array:
    """Prune decisions happen per (batch-tile, doc-tile) grid cell here
    but per doc-tile in the XLA backend; normalize by the batch-tile
    count so both report in doc-tile units (examined == n_tiles) and
    prune rates stay comparable when the autotuner mixes backends."""
    reps = flags[::btile]                       # one row per batch tile
    n_btiles = bp // btile
    return (jnp.stack([(reps == 2).sum(), (reps == 1).sum(),
                       jnp.int32(reps.size)]).astype(jnp.float32)
            / n_btiles)


def match_mask_bundle_pallas(text_cols: dict, num_cols: dict,
                             clauses: tuple, cl_inputs: tuple,
                             msm: jax.Array, boost: jax.Array | None,
                             live: jax.Array, emit_match: bool = True,
                             step=None, interpret: bool = False):
    """Pallas counterpart of ops.scoring.match_mask_bundle_fused — the
    mask-only k == 0 grid: a ck == 0 build of the bundle kernel that
    emits exact counts (and, when emit_match, the exact match mask) with
    msm-aware hard-skips and NO candidate selection or threshold state.
    Match semantics are exact per ops/scoring.bundle_tile_match: a dense
    clause's match is `score > 0`, which the kernel evaluates with the
    same compare/accumulate ops, so totals and masks are bit-identical
    to the XLA engine. Returns (total [B], prune_stats f32 [3])
    (+ match [B, cap] bool)(+ timed_out when stepped)."""
    _cs, _ci, cnt, flags, match, timed, b, btile, bp = \
        _bundle_pallas_walk(
            text_cols, num_cols, clauses, cl_inputs, msm, boost, live,
            ck=0, update_thr=False, emit_match=emit_match, step=step,
            interpret=interpret)
    total = cnt[:b].sum(axis=1)
    pruned = _normalize_prune(flags, btile, bp)
    out = (total, pruned)
    if emit_match:
        out = out + ((match[:b] != 0),)
    return out if timed is None else out + (timed,)


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def fused_topk_dense_pallas(fwd_tids: jax.Array, fwd_imps: jax.Array,
                            tile_max: jax.Array, qt: jax.Array,
                            wq: jax.Array, live: jax.Array, k: int,
                            msm: jax.Array | None = None,
                            boost: jax.Array | None = None,
                            interpret: bool = False
                            ) -> tuple[jax.Array, jax.Array, jax.Array,
                                       jax.Array]:
    """Single-dense-clause entry (PR 1 signature): a thin wrapper over
    the bundle kernel — one should clause, the enclosing bool node's
    dynamic msm/boost as the outer params. Like the XLA wrapper, boost
    now applies BEFORE selection in eval_node's exact op order, so doc
    ids and ties match the unfused path for any boost > 0."""
    b = qt.shape[0]
    if msm is None:
        msm = jnp.ones((b,), jnp.int32)
    clauses = (("should", "terms_dense", "f", False),)
    cl_inputs = ((qt, wq, jnp.ones((b,), jnp.int32),
                  jnp.ones((b,), jnp.float32)),)
    text_cols = {"f": {"fwd_tids": fwd_tids, "fwd_imps": fwd_imps,
                       "tile_max": tile_max}}
    return fused_topk_bundle_pallas(text_cols, {}, clauses, cl_inputs,
                                    msm, boost, live, k,
                                    interpret=interpret)


# ---------------------------------------------------------------------------
# drop-in counterparts for ops/scoring.py entry points
# ---------------------------------------------------------------------------


def score_term_pallas(block_docs: jax.Array, block_imps: jax.Array,
                      block_lo: jax.Array, nb_valid: jax.Array,
                      weight: jax.Array, nb_pad: int, cap: int,
                      interpret: bool = False) -> jax.Array:
    """Pallas-backed ops.scoring.score_term: XLA block gather (regular,
    already efficient) + fused one-hot scatter."""
    from .scoring import gather_term_blocks
    docs, imps = gather_term_blocks(block_docs, block_imps, block_lo,
                                    nb_valid, nb_pad, cap)
    return scatter_add_pallas(docs, imps * weight[:, None], cap,
                              interpret=interpret)


def score_terms_fused_pallas(block_docs: jax.Array, block_imps: jax.Array,
                             gather_idx: jax.Array, weights: jax.Array,
                             cap: int, interpret: bool = False) -> jax.Array:
    """Pallas-backed ops.scoring.score_terms_fused."""
    from .scoring import gather_fused_blocks
    docs, vals = gather_fused_blocks(block_docs, block_imps, gather_idx,
                                     weights, cap)
    return scatter_add_pallas(docs, vals, cap, interpret=interpret)


# ---------------------------------------------------------------------------
# dispatch: use the kernels on real TPU, jnp elsewhere
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def pallas_enabled() -> bool:
    """Kernels engage on an actual TPU backend unless ES_TPU_PALLAS=0;
    ES_TPU_PALLAS=1 forces them even off-TPU (in interpret mode — far
    slower than the XLA fallback, for validation only)."""
    import os
    flag = os.environ.get("ES_TPU_PALLAS", "auto").lower()
    if flag in ("0", "false", "off"):
        return False
    if flag in ("1", "true", "on"):
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def resident_step_ok() -> bool:
    """May a resident stepped entry (search/resident.py) run through a
    Pallas kernel? Yes, whenever the kernels are enabled at all: the
    stepped form of fused_topk_bundle_pallas / match_mask_bundle_pallas
    partitions the doc-tile grid into chunks of pallas_call invocations
    and hosts the per-chunk deadline callback BETWEEN kernel chunks at
    the jit level (a Mosaic kernel body still cannot host a callback
    mid-grid — the chunk boundary is the preemption point), with the
    running threshold and prune counters carried across the boundary.
    Exists as a named predicate so the executor's admission reads as
    policy, not accident."""
    return pallas_enabled()


@functools.lru_cache(maxsize=1)
def interpret_mode() -> bool:
    """Forced-on kernels off-TPU must run the Pallas interpreter —
    Mosaic lowering only exists for TPU backends."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True
