"""Pallas TPU kernels for the BM25 scoring hot loop.

The reference's per-shard hot loop (search/query/QueryPhase.java:153 —
BulkScorer iterating postings, BM25 Similarity, TopScoreDocCollector)
maps to two dense-tensor formulations here, each with a fused kernel:

* `score_terms_dense_pallas` — the forward-index path (`terms_dense` /
  `term_text` in the executor): score[b, d] = sum over the doc's
  (term, impact) slots of impact * weight where the slot's term id is
  one of the query's. One pass over the [cap, L] forward index per doc
  tile, all B queries and Q terms consumed from VMEM — the [B, cap, L]
  broadcast intermediate the jnp version materializes never exists.

* `scatter_add_pallas` — the posting-scatter path (`term_text_sc` /
  `terms_fused`): scores[b, docs[b, n]] += vals[b, n]. TPUs have no
  vector scatter, so each 128-posting chunk becomes a one-hot compare
  against a 128-doc tile contracted on the MXU; because postings are
  doc-sorted within a term, a prefetched per-chunk [min, max] doc range
  skips every (tile, chunk) pair that cannot intersect, making the work
  near-linear in postings instead of postings x doc-tiles.

The jnp implementations in ops/scoring.py remain the reference
semantics (and the CPU path); tests run these kernels in interpret mode
against them, and bench.py A/Bs them on the real chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..index.segment import BLOCK

LANES = 128          # TPU lane width = posting block width
_DOC_TILE = 512      # docs scored per dense-kernel grid step
_BATCH_TILE = 64     # queries scored per dense-kernel grid step — the
                     # kernel's [b_tile, doc_tile, L] compare/accumulate
                     # working set must stay well inside scoped VMEM
                     # (64*512*8*4B = 1MB per term step)


# ---------------------------------------------------------------------------
# forward-index (dense) scoring kernel
# ---------------------------------------------------------------------------


def _dense_kernel(qt_ref, wq_ref, tids_ref, imps_ref, out_ref):
    """One (batch tile, doc tile): out[b, t] = sum_q wq[b,q] * sum_l
    (tids[t, l] == qt[b, q]) * imps[t, l]. Both the term count Q and
    the forward-slot count L are small static ints, so they unroll;
    every live buffer stays 2-D [b_tile, doc_tile] — a 3-D [.., .., L]
    intermediate would be lane-padded L->128 by the TPU tiling and blow
    the scoped-VMEM budget 16x."""
    tids = tids_ref[...]                       # [L, TILE] int32
    imps = imps_ref[...]                       # [L, TILE] f32
    qt = qt_ref[...]                           # [Bt, Q] int32
    wq = wq_ref[...]                           # [Bt, Q] f32
    b_n, q_n = qt.shape
    n_slots, tile = tids.shape
    acc = jnp.zeros((b_n, tile), jnp.float32)
    for q in range(q_n):
        tq = qt[:, q]                          # [Bt]
        hit = jnp.zeros((b_n, tile), jnp.float32)
        for l in range(n_slots):
            # row slices of the slot-major layout are contiguous lane
            # vectors (a [TILE, L] column slice would stride the padded
            # minor dim and spill registers catastrophically)
            eq = tids[l][None, :] == tq[:, None]      # [Bt, TILE]
            hit = hit + jnp.where(eq, imps[l][None, :], 0.0)
        acc = acc + hit * wq[:, q][:, None]
    out_ref[...] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def score_terms_dense_pallas(fwd_tids: jax.Array, fwd_imps: jax.Array,
                             qt: jax.Array, wq: jax.Array,
                             interpret: bool = False) -> jax.Array:
    """[cap, L] forward index x [B, Q] query terms -> [B, cap] scores.

    Query term ids use -1 for padding (matches only zero-impact slots,
    exactly like the jnp path, since tids padding is also -1 with 0
    impact — weights for padded terms must be 0, which bind guarantees).
    """
    cap, lanes = fwd_tids.shape
    b = qt.shape[0]
    tile = min(_DOC_TILE, cap)
    btile = min(_BATCH_TILE, b)
    pad_b = (-b) % btile
    if pad_b:
        # pad the query axis up to the tile (padded rows score against
        # weight 0 and are sliced off)
        qt = jnp.pad(qt, ((0, pad_b), (0, 0)), constant_values=-1)
        wq = jnp.pad(wq, ((0, pad_b), (0, 0)))
    bp = b + pad_b
    # slot-major layout: kernel blocks slice slot ROWS (contiguous lane
    # vectors); XLA hoists + caches this transpose across calls
    tids_t = fwd_tids.T                        # [L, cap]
    imps_t = fwd_imps.T
    grid = (bp // btile, cap // tile)
    out = pl.pallas_call(
        _dense_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((btile, qt.shape[1]), lambda bi, i: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((btile, wq.shape[1]), lambda bi, i: (bi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((lanes, tile), lambda bi, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((lanes, tile), lambda bi, i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((btile, tile), lambda bi, i: (bi, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, cap), jnp.float32),
        interpret=interpret,
    )(qt, wq, tids_t, imps_t)
    return out[:b] if pad_b else out


# ---------------------------------------------------------------------------
# posting-scatter kernel (one-hot MXU scatter with sorted-range skip)
# ---------------------------------------------------------------------------


_BROWS = 8  # batch rows per scatter block (TPU sublane granularity)


def _scatter_kernel(cmin_ref, cmax_ref, docs_ref, vals_ref, out_ref):
    b = pl.program_id(0)
    t = pl.program_id(1)
    c = pl.program_id(2)

    @pl.when(c == 0)
    def _zero():
        out_ref[...] = jnp.zeros_like(out_ref)

    tile_lo = t * LANES
    # whole-block skip: does ANY of the 8 rows' chunk range touch this
    # doc tile? (rows are independent queries; posting chunks are
    # doc-sorted so the [min, max] test prunes most (tile, chunk) pairs)
    hit = jnp.zeros((), jnp.bool_)
    for r in range(_BROWS):
        row = b * _BROWS + r
        hit = hit | ((cmax_ref[row, c] >= tile_lo)
                     & (cmin_ref[row, c] < tile_lo + LANES))

    @pl.when(hit)
    def _accumulate():
        docs = docs_ref[...]                   # [8, 128] int32
        vals = vals_ref[...]                   # [8, 128] f32
        local = docs - tile_lo
        iota = jax.lax.broadcasted_iota(jnp.int32, (_BROWS, LANES, LANES),
                                        2)
        onehot = (local[:, :, None] == iota).astype(jnp.float32)
        # contribution[r, j] = sum_i vals[r, i] * onehot[r, i, j]
        # (batched MXU contract over the 8 rows)
        contrib = jax.lax.dot_general(
            vals[:, None, :], onehot,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)   # [8, 1, 128]
        out_ref[...] += contrib[:, 0, :]


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def scatter_add_pallas(docs: jax.Array, vals: jax.Array, cap: int,
                       interpret: bool = False) -> jax.Array:
    """scores[b, docs[b, n]] += vals[b, n]; docs >= cap (padding) drop.

    docs: int32 [B, N] sorted non-decreasing per (query, term) run —
    segment posting blocks are doc-sorted, which is what makes the
    per-chunk [min, max] tile skip effective. Correctness does NOT
    depend on sortedness, only performance.
    """
    b, n = docs.shape
    n_pad = -(-n // LANES) * LANES
    cap_pad = -(-cap // LANES) * LANES
    b_pad = -(-b // _BROWS) * _BROWS
    if n_pad != n:
        docs = jnp.pad(docs, ((0, 0), (0, n_pad - n)),
                       constant_values=cap_pad)
        vals = jnp.pad(vals, ((0, 0), (0, n_pad - n)))
    if b_pad != b:
        docs = jnp.pad(docs, ((0, b_pad - b), (0, 0)),
                       constant_values=cap_pad)
        vals = jnp.pad(vals, ((0, b_pad - b), (0, 0)))
    # OOB padding (== cap) must never land in a tile: clamp into a
    # sentinel range past cap_pad so the range skip drops those chunks
    docs = jnp.where(docs >= cap, cap_pad + LANES, docs)
    chunks = docs.reshape(b_pad, n_pad // LANES, LANES)
    cmin = chunks.min(axis=-1).astype(jnp.int32)     # [B, C]
    cmax = chunks.max(axis=-1).astype(jnp.int32)
    # padded chunk rows (all sentinel) have cmin > cap_pad -> skipped
    grid = (b_pad // _BROWS, cap_pad // LANES, n_pad // LANES)
    out = pl.pallas_call(
        _scatter_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((_BROWS, LANES),
                             lambda b_, t, c, *_: (b_, c)),
                pl.BlockSpec((_BROWS, LANES),
                             lambda b_, t, c, *_: (b_, c)),
            ],
            out_specs=pl.BlockSpec((_BROWS, LANES),
                                   lambda b_, t, c, *_: (b_, t)),
        ),
        out_shape=jax.ShapeDtypeStruct((b_pad, cap_pad), jnp.float32),
        interpret=interpret,
    )(cmin, cmax, docs.reshape(b_pad, n_pad), vals.reshape(b_pad, n_pad))
    return out[:b, :cap]


# ---------------------------------------------------------------------------
# drop-in counterparts for ops/scoring.py entry points
# ---------------------------------------------------------------------------


def score_term_pallas(block_docs: jax.Array, block_imps: jax.Array,
                      block_lo: jax.Array, nb_valid: jax.Array,
                      weight: jax.Array, nb_pad: int, cap: int,
                      interpret: bool = False) -> jax.Array:
    """Pallas-backed ops.scoring.score_term: XLA block gather (regular,
    already efficient) + fused one-hot scatter."""
    from .scoring import gather_term_blocks
    docs, imps = gather_term_blocks(block_docs, block_imps, block_lo,
                                    nb_valid, nb_pad, cap)
    return scatter_add_pallas(docs, imps * weight[:, None], cap,
                              interpret=interpret)


def score_terms_fused_pallas(block_docs: jax.Array, block_imps: jax.Array,
                             gather_idx: jax.Array, weights: jax.Array,
                             cap: int, interpret: bool = False) -> jax.Array:
    """Pallas-backed ops.scoring.score_terms_fused."""
    from .scoring import gather_fused_blocks
    docs, vals = gather_fused_blocks(block_docs, block_imps, gather_idx,
                                     weights, cap)
    return scatter_add_pallas(docs, vals, cap, interpret=interpret)


# ---------------------------------------------------------------------------
# dispatch: use the kernels on real TPU, jnp elsewhere
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def pallas_enabled() -> bool:
    """Kernels engage on an actual TPU backend unless ES_TPU_PALLAS=0;
    ES_TPU_PALLAS=1 forces them even off-TPU (in interpret mode — far
    slower than the XLA fallback, for validation only)."""
    import os
    flag = os.environ.get("ES_TPU_PALLAS", "auto").lower()
    if flag in ("0", "false", "off"):
        return False
    if flag in ("1", "true", "on"):
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def interpret_mode() -> bool:
    """Forced-on kernels off-TPU must run the Pallas interpreter —
    Mosaic lowering only exists for TPU backends."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True
