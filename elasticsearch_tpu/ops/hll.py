"""HyperLogLog++ cardinality: mesh-reducible sketch registers.

Reference analog: search/aggregations/metrics/cardinality/
HyperLogLogPlusPlus.java — ES's cardinality agg switches from exact
(linear) counting to an HLL++ sketch past `precision_threshold`.

TPU formulation: register updates are a scatter-MAX of per-value ranks
into a [B, 2^p] register file — exactly the bucket-scatter shape every
other agg uses, so the sketch reduces across segments, shards and the
mesh with an elementwise max (jax.lax.pmax over the shard axis). With
p=12 (4096 registers, ES default 3000-ish threshold regime) standard
error is 1.04/sqrt(4096) ~ 1.6%.

Hashes are computed HOST-side per dictionary TERM (not per doc): the
columnar layout stores ordinals, so each distinct value hashes once and
docs just gather their ordinal's (register, rank) pair.
"""

from __future__ import annotations

import hashlib

import numpy as np

P = 12                     # register address bits
M = 1 << P                 # 4096 registers
_ALPHA = 0.7213 / (1.0 + 1.079 / M)  # alpha_m for m >= 128


def _hash64(term: str) -> int:
    """Stable 64-bit term hash (blake2b — stable across processes,
    unlike Python's salted hash())."""
    return int.from_bytes(
        hashlib.blake2b(term.encode("utf-8", "surrogatepass"),
                        digest_size=8).digest(), "little")


_REGISTER_MEMO: dict[int, tuple] = {}   # id(terms) -> (terms, reg, rank)
_MEMO_CAP = 32


def term_registers(terms: list[str],
                   memo: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Per-term (register index, rank) pairs; empty-safe.

    rank = 1 + number of leading zeros of the remaining 64-p hash bits
    (capped so int8-sized values suffice). Results memoize on the term
    LIST object (global-ordinal term lists are cached per reader and
    reused across queries — hashing a million terms per request would
    dominate the agg); the memo holds a strong reference to the list so
    id() cannot be reused while an entry lives. Callers hashing a
    TRANSIENT list (e.g. shard-merge bucket keys) must pass memo=False
    so one-shot entries don't evict the long-lived per-reader ones.
    """
    hit = _REGISTER_MEMO.get(id(terms)) if memo else None
    if hit is not None and hit[0] is terms:
        return hit[1], hit[2]
    n = len(terms)
    reg = np.zeros(max(n, 1), dtype=np.int32)
    rank = np.zeros(max(n, 1), dtype=np.int32)
    for i, t in enumerate(terms):
        h = _hash64(t)
        reg[i] = h & (M - 1)
        rest = h >> P
        # leading zeros within the (64 - P)-bit remainder
        width = 64 - P
        rank[i] = (width - rest.bit_length()) + 1 if rest else width + 1
    if memo:
        if len(_REGISTER_MEMO) >= _MEMO_CAP:
            _REGISTER_MEMO.pop(next(iter(_REGISTER_MEMO)))
        _REGISTER_MEMO[id(terms)] = (terms, reg, rank)
    return reg, rank


def estimate(registers: np.ndarray) -> float:
    """HLL estimate with the small-range linear-counting correction
    (ref: HyperLogLogPlusPlus.cardinality). registers: [M] max ranks
    (0 = empty register)."""
    regs = np.asarray(registers, dtype=np.float64)
    raw = _ALPHA * M * M / np.sum(np.power(2.0, -regs))
    zeros = int(np.count_nonzero(regs == 0))
    if raw <= 2.5 * M and zeros > 0:
        return M * np.log(M / zeros)          # linear counting
    return raw
