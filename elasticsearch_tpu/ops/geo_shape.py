"""geo_shape support: GeoJSON shapes rasterized onto prefix-tree cells.

Reference analog: common/geo/builders/ShapeBuilder.java (GeoJSON parsing),
index/mapper/geo/GeoShapeFieldMapper.java and the Lucene-spatial
RecursivePrefixTreeStrategy it configures (geohash or quadtree prefix
trees), index/query/GeoShapeQueryParser.java (relations: intersects /
disjoint / within).

TPU-first design: the reference walks a prefix-tree filter per query
against per-doc term iterators. Here a shape is rasterized ONCE at index
time into cell tokens stored in the standard postings layout
(index/segment.py block-CSR), so every geo_shape query becomes a plain
terms disjunction that rides the fused gather->scatter scoring path on
device — no per-doc geometry at search time:

  * index tokens: every tree cell on the descent path of the shape plus
    leaf-marked terminal cells ("<cell>+"), exactly the
    TermQueryPrefixTreeStrategy token scheme;
  * INTERSECTS(query): match any terminal cell of the query covering,
    or a leaf-marked ancestor of one — all exact term matches;
  * WITHIN: intersects(query) AND NOT intersects(complement covering) —
    the complement of a shape is itself a bounded cell covering (coarse
    far away, fine near the boundary);
  * DISJOINT: exists(field) AND NOT intersects(query).

All relations carry constant scores (Lucene ConstantScore semantics).
Geometry predicates are planar in degrees, matching the flat-earth cell
relations of the reference's prefix trees; precision is governed by
tree_levels / precision / distance_error_pct as in GeoShapeFieldMapper.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ..utils.errors import QueryParsingError

DISJOINT = 0
INTERSECTS = 1
CONTAINS_RECT = 2   # shape fully contains the cell rect

LEAF = "+"          # leaf-cell marker suffix (Lucene Cell.isLeaf token)

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"

# mean meters per degree of latitude (GeoUtils: earth circumference/360)
_M_PER_DEG = 111194.93


@dataclass(frozen=True)
class Rect:
    lon_lo: float
    lat_lo: float
    lon_hi: float
    lat_hi: float

    def intersects(self, o: "Rect") -> bool:
        return not (o.lon_lo > self.lon_hi or o.lon_hi < self.lon_lo
                    or o.lat_lo > self.lat_hi or o.lat_hi < self.lat_lo)

    def contains(self, o: "Rect") -> bool:
        return (self.lon_lo <= o.lon_lo and o.lon_hi <= self.lon_hi
                and self.lat_lo <= o.lat_lo and o.lat_hi <= self.lat_hi)

    def contains_pt(self, lon: float, lat: float) -> bool:
        return (self.lon_lo <= lon <= self.lon_hi
                and self.lat_lo <= lat <= self.lat_hi)

    def corners(self):
        return ((self.lon_lo, self.lat_lo), (self.lon_hi, self.lat_lo),
                (self.lon_hi, self.lat_hi), (self.lon_lo, self.lat_hi))

    def edges(self):
        c = self.corners()
        return (c[0], c[1]), (c[1], c[2]), (c[2], c[3]), (c[3], c[0])

    def diagonal_m(self) -> float:
        dx = (self.lon_hi - self.lon_lo) * _M_PER_DEG \
            * math.cos(math.radians((self.lat_lo + self.lat_hi) / 2))
        dy = (self.lat_hi - self.lat_lo) * _M_PER_DEG
        return math.hypot(dx, dy)


WORLD = Rect(-180.0, -90.0, 180.0, 90.0)


# ---------------------------------------------------------------------------
# geometry predicates (planar, degrees)
# ---------------------------------------------------------------------------


def _seg_intersects(p1, p2, p3, p4) -> bool:
    """Do segments p1-p2 and p3-p4 intersect (incl. touching)?"""

    def orient(a, b, c):
        v = (b[0] - a[0]) * (c[1] - a[1]) - (b[1] - a[1]) * (c[0] - a[0])
        return 0 if abs(v) < 1e-12 else (1 if v > 0 else -1)

    def on_seg(a, b, c):
        return (min(a[0], b[0]) - 1e-12 <= c[0] <= max(a[0], b[0]) + 1e-12
                and min(a[1], b[1]) - 1e-12 <= c[1] <= max(a[1], b[1]) + 1e-12)

    o1, o2 = orient(p1, p2, p3), orient(p1, p2, p4)
    o3, o4 = orient(p3, p4, p1), orient(p3, p4, p2)
    if o1 != o2 and o3 != o4:
        return True
    if o1 == 0 and on_seg(p1, p2, p3):
        return True
    if o2 == 0 and on_seg(p1, p2, p4):
        return True
    if o3 == 0 and on_seg(p3, p4, p1):
        return True
    return o4 == 0 and on_seg(p3, p4, p2)


def _point_in_ring(lon: float, lat: float, ring) -> bool:
    """Ray casting; ring is a closed list of (lon, lat)."""
    inside = False
    n = len(ring)
    for i in range(n - 1):
        x1, y1 = ring[i]
        x2, y2 = ring[i + 1]
        if (y1 > lat) != (y2 > lat):
            x_at = x1 + (lat - y1) / (y2 - y1) * (x2 - x1)
            if x_at > lon:
                inside = not inside
    return inside


class Shape:
    """Base: relation of this shape to an axis-aligned cell rect."""

    def bbox(self) -> Rect:
        raise NotImplementedError

    def relate_rect(self, r: Rect) -> int:
        raise NotImplementedError


class PointShape(Shape):
    def __init__(self, lon: float, lat: float):
        self.lon, self.lat = float(lon), float(lat)

    def bbox(self) -> Rect:
        return Rect(self.lon, self.lat, self.lon, self.lat)

    def relate_rect(self, r: Rect) -> int:
        return INTERSECTS if r.contains_pt(self.lon, self.lat) else DISJOINT


class EnvelopeShape(Shape):
    def __init__(self, rect: Rect):
        self.rect = rect

    def bbox(self) -> Rect:
        return self.rect

    def relate_rect(self, r: Rect) -> int:
        if not self.rect.intersects(r):
            return DISJOINT
        if self.rect.contains(r):
            return CONTAINS_RECT
        return INTERSECTS


class CircleShape(Shape):
    """Circle with a radius in meters, evaluated on a locally-scaled
    planar approximation (ref: common/geo/builders/CircleBuilder)."""

    def __init__(self, lon: float, lat: float, radius_m: float):
        self.lon, self.lat, self.radius_m = float(lon), float(lat), \
            float(radius_m)
        self._coslat = max(math.cos(math.radians(self.lat)), 1e-6)
        self._r_deg = radius_m / _M_PER_DEG

    def bbox(self) -> Rect:
        dlat = self._r_deg
        dlon = self._r_deg / self._coslat
        return Rect(self.lon - dlon, self.lat - dlat,
                    self.lon + dlon, self.lat + dlat)

    def _dist_deg(self, lon: float, lat: float) -> float:
        dx = (lon - self.lon) * self._coslat
        dy = lat - self.lat
        return math.hypot(dx, dy)

    def relate_rect(self, r: Rect) -> int:
        # nearest rect point to the center
        nx = min(max(self.lon, r.lon_lo), r.lon_hi)
        ny = min(max(self.lat, r.lat_lo), r.lat_hi)
        if self._dist_deg(nx, ny) > self._r_deg:
            return DISJOINT
        if all(self._dist_deg(x, y) <= self._r_deg for x, y in r.corners()):
            return CONTAINS_RECT
        return INTERSECTS


class LineShape(Shape):
    def __init__(self, coords):  # [(lon, lat), ...]
        if len(coords) < 2:
            raise QueryParsingError(
                "linestring requires at least 2 points")
        self.coords = [(float(x), float(y)) for x, y in coords]
        xs = [p[0] for p in self.coords]
        ys = [p[1] for p in self.coords]
        self._bbox = Rect(min(xs), min(ys), max(xs), max(ys))

    def bbox(self) -> Rect:
        return self._bbox

    def relate_rect(self, r: Rect) -> int:
        for i in range(len(self.coords) - 1):
            a, b = self.coords[i], self.coords[i + 1]
            if r.contains_pt(*a) or r.contains_pt(*b):
                return INTERSECTS
            for e1, e2 in r.edges():
                if _seg_intersects(a, b, e1, e2):
                    return INTERSECTS
        return DISJOINT


class PolygonShape(Shape):
    """Shell + holes, each a closed ring of (lon, lat)."""

    def __init__(self, shell, holes=()):
        self.shell = self._close([(float(x), float(y)) for x, y in shell])
        if len(self.shell) < 4:
            raise QueryParsingError("polygon shell requires >= 3 points")
        self.holes = [self._close([(float(x), float(y)) for x, y in h])
                      for h in holes]
        xs = [p[0] for p in self.shell]
        ys = [p[1] for p in self.shell]
        self._bbox = Rect(min(xs), min(ys), max(xs), max(ys))

    @staticmethod
    def _close(ring):
        if ring and ring[0] != ring[-1]:
            ring = ring + [ring[0]]
        return ring

    def bbox(self) -> Rect:
        return self._bbox

    def contains_pt(self, lon: float, lat: float) -> bool:
        if not _point_in_ring(lon, lat, self.shell):
            return False
        return not any(_point_in_ring(lon, lat, h) for h in self.holes)

    def relate_rect(self, r: Rect) -> int:
        if not self.bbox().intersects(r):
            return DISJOINT
        rings = [self.shell] + self.holes
        for ring in rings:
            for i in range(len(ring) - 1):
                a, b = ring[i], ring[i + 1]
                for e1, e2 in r.edges():
                    if _seg_intersects(a, b, e1, e2):
                        return INTERSECTS
        # no edge crossings: either rect wholly inside the polygon (all
        # corners in), polygon wholly inside rect, rect in a hole, or
        # disjoint
        if self.contains_pt(r.lon_lo, r.lat_lo):
            # a hole lying strictly inside the rect (no edge crossings
            # means wholly inside or wholly outside) punctures it — the
            # rect is then NOT fully contained by the polygon
            for h in self.holes:
                if r.contains_pt(*h[0]):
                    return INTERSECTS
            return CONTAINS_RECT
        if r.contains_pt(*self.shell[0]):
            return INTERSECTS  # polygon inside the rect
        return DISJOINT


class MultiShape(Shape):
    def __init__(self, parts):
        if not parts:
            raise QueryParsingError("empty geometry collection")
        self.parts = list(parts)
        bs = [p.bbox() for p in self.parts]
        self._bbox = Rect(
            min(b.lon_lo for b in bs), min(b.lat_lo for b in bs),
            max(b.lon_hi for b in bs), max(b.lat_hi for b in bs))

    def bbox(self) -> Rect:
        return self._bbox

    def relate_rect(self, r: Rect) -> int:
        best = DISJOINT
        for p in self.parts:
            rel = p.relate_rect(r)
            if rel == CONTAINS_RECT:
                return CONTAINS_RECT
            if rel == INTERSECTS:
                best = INTERSECTS
        return best


def parse_shape(obj) -> Shape:
    """GeoJSON-ish dict -> Shape (ref: ShapeBuilder.parse)."""
    if not isinstance(obj, dict):
        raise QueryParsingError(f"shape must be an object, got {obj!r}")
    typ = str(obj.get("type", "")).lower()
    coords = obj.get("coordinates")
    if typ == "point":
        return PointShape(coords[0], coords[1])
    if typ == "multipoint":
        return MultiShape([PointShape(c[0], c[1]) for c in coords])
    if typ == "envelope":
        (x1, y1), (x2, y2) = coords  # [top-left, bottom-right]
        return EnvelopeShape(Rect(min(x1, x2), min(y1, y2),
                                  max(x1, x2), max(y1, y2)))
    if typ == "circle":
        from .geo import parse_distance
        r = parse_distance(obj.get("radius", "1m"))
        return CircleShape(coords[0], coords[1], r)
    if typ == "linestring":
        return LineShape(coords)
    if typ == "multilinestring":
        return MultiShape([LineShape(c) for c in coords])
    if typ == "polygon":
        return PolygonShape(coords[0], coords[1:])
    if typ == "multipolygon":
        return MultiShape([PolygonShape(c[0], c[1:]) for c in coords])
    if typ == "geometrycollection":
        return MultiShape([parse_shape(g)
                           for g in obj.get("geometries", [])])
    raise QueryParsingError(f"unknown shape type [{typ or obj.get('type')}]")


# ---------------------------------------------------------------------------
# prefix trees (ref: Lucene-spatial GeohashPrefixTree / QuadPrefixTree)
# ---------------------------------------------------------------------------


class QuadTree:
    """Base-4 prefix tree: each level splits a rect 2x2; token digits
    0=SW 1=SE 2=NW 3=NE."""

    name = "quadtree"
    max_levels_cap = 26

    def roots(self):
        yield from self.children("", WORLD)

    def children(self, token: str, r: Rect):
        mx = (r.lon_lo + r.lon_hi) / 2
        my = (r.lat_lo + r.lat_hi) / 2
        yield token + "0", Rect(r.lon_lo, r.lat_lo, mx, my)
        yield token + "1", Rect(mx, r.lat_lo, r.lon_hi, my)
        yield token + "2", Rect(r.lon_lo, my, mx, r.lat_hi)
        yield token + "3", Rect(mx, my, r.lon_hi, r.lat_hi)

    def levels_for_meters(self, m: float) -> int:
        """Smallest level whose cell is still >= m across (quad cell at
        level n is 360/2^n degrees of longitude)."""
        if m <= 0:
            return self.max_levels_cap
        deg = m / _M_PER_DEG
        lv = int(math.ceil(math.log2(360.0 / max(deg, 1e-9))))
        return max(1, min(self.max_levels_cap, lv))


class GeohashTree:
    """Base-32 geohash prefix tree; tokens are true geohash strings
    (8x4 lon/lat split on odd chars, 4x8 on even — bit-interleaved as in
    GeoHashUtils)."""

    name = "geohash"
    max_levels_cap = 12

    def roots(self):
        yield from self.children("", WORLD)

    def children(self, token: str, r: Rect):
        even = len(token) % 2 == 0  # next char position (0-based) even
        dlon = (r.lon_hi - r.lon_lo) / (8 if even else 4)
        dlat = (r.lat_hi - r.lat_lo) / (4 if even else 8)
        for ci in range(32):
            b = [(ci >> k) & 1 for k in (4, 3, 2, 1, 0)]
            if even:   # bits: lon lat lon lat lon
                xi = b[0] * 4 + b[2] * 2 + b[4]
                yi = b[1] * 2 + b[3]
            else:      # bits: lat lon lat lon lat
                yi = b[0] * 4 + b[2] * 2 + b[4]
                xi = b[1] * 2 + b[3]
            yield token + _BASE32[ci], Rect(
                r.lon_lo + xi * dlon, r.lat_lo + yi * dlat,
                r.lon_lo + (xi + 1) * dlon, r.lat_lo + (yi + 1) * dlat)

    def levels_for_meters(self, m: float) -> int:
        # approximate geohash cell heights in meters per level
        # (GeoUtils.geoHashLevelsForPrecision)
        sizes = [5_009_400, 1_252_300, 156_500, 39_100, 4_890, 1_220,
                 153, 38, 4.8, 1.2, 0.15, 0.037]
        for level, size in enumerate(sizes, start=1):
            if size <= m:
                return level
        return self.max_levels_cap


def make_tree(name: str):
    if name == "quadtree":
        return QuadTree()
    if name in ("geohash", None, ""):
        return GeohashTree()
    raise QueryParsingError(f"unknown prefix tree type [{name}]")


def effective_levels(shape: Shape, tree, tree_levels: int,
                     distance_error_pct: float) -> int:
    """Per-shape depth cap (ref: GeoShapeFieldMapper.defaultPrecision —
    distance_error_pct of the shape diagonal bounds the cell size, so
    continent-sized polygons don't rasterize at meter precision)."""
    if distance_error_pct <= 0:
        return tree_levels
    diag = shape.bbox().diagonal_m()
    if diag <= 0:
        return tree_levels  # points: full precision
    return min(tree_levels,
               tree.levels_for_meters(diag * distance_error_pct))


def rasterize(shape: Shape, tree, levels: int
              ) -> tuple[list[str], list[str]]:
    """Shape -> (terminal cells, all descent-path cells).

    Terminals stop either at `levels` or where the shape fully contains
    the cell (the RecursivePrefixTreeStrategy early-stop)."""
    terminals: list[str] = []
    paths: list[str] = []
    bbox = shape.bbox()

    def visit(token: str, rect: Rect, level: int) -> None:
        if not bbox.intersects(rect):
            return
        rel = shape.relate_rect(rect)
        if rel == DISJOINT:
            return
        paths.append(token)
        if rel == CONTAINS_RECT or level >= levels:
            terminals.append(token)
            return
        for ctok, crect in tree.children(token, rect):
            visit(ctok, crect, level + 1)

    for tok, rect in tree.roots():
        visit(tok, rect, 1)
    return terminals, paths


def rasterize_complement(shape: Shape, tree, levels: int) -> list[str]:
    """Covering of the world MINUS the shape interior: maximal fully-
    disjoint cells plus max-level boundary cells (conservative — a doc
    touching the boundary is not WITHIN). Bounded by the boundary
    length: coarse far from the shape, fine only along its edge."""
    out: list[str] = []

    def visit(token: str, rect: Rect, level: int) -> None:
        rel = shape.relate_rect(rect)
        if rel == CONTAINS_RECT:
            return
        if rel == DISJOINT or level >= levels:
            out.append(token)
            return
        for ctok, crect in tree.children(token, rect):
            visit(ctok, crect, level + 1)

    for tok, rect in tree.roots():
        visit(tok, rect, 1)
    return out


def index_tokens(shape: Shape, tree, levels: int) -> list[str]:
    """Tokens stored in the shape field's postings: every descent-path
    cell plus leaf-marked terminals (TermQueryPrefixTreeStrategy)."""
    terminals, paths = rasterize(shape, tree, levels)
    toks = set(paths)
    toks.update(t + LEAF for t in terminals)
    return sorted(toks)


def query_tokens(terminals: list[str]) -> list[str]:
    """Terminal cells of a query covering -> the exact-match token
    disjunction for INTERSECTS: each terminal itself (docs passing
    through it) plus leaf-marked self/ancestors (docs whose own terminal
    is at or above it)."""
    toks: set[str] = set()
    for t in terminals:
        toks.add(t)
        for i in range(1, len(t) + 1):
            toks.add(t[:i] + LEAF)
    return sorted(toks)


# Query-scope memos: the binder runs once per SEGMENT (Lucene
# createWeight-per-reader style), but the rasterization inputs are
# segment-independent — cache so a multi-segment shard (and repeated
# queries) descend the prefix tree once per distinct shape/config.
import functools
import json as _json


@functools.lru_cache(maxsize=128)
def shape_intersect_tokens(shape_json: str, tree_name: str,
                           tree_levels: int,
                           err_pct: float) -> tuple[str, ...]:
    tree = make_tree(tree_name)
    shape = parse_shape(_json.loads(shape_json))
    levels = effective_levels(shape, tree, tree_levels, err_pct)
    terminals, _ = rasterize(shape, tree, levels)
    return tuple(query_tokens(terminals))


@functools.lru_cache(maxsize=128)
def shape_complement_tokens(shape_json: str, tree_name: str,
                            tree_levels: int,
                            err_pct: float) -> tuple[str, ...]:
    tree = make_tree(tree_name)
    shape = parse_shape(_json.loads(shape_json))
    levels = effective_levels(shape, tree, tree_levels, err_pct)
    return tuple(query_tokens(rasterize_complement(shape, tree, levels)))
