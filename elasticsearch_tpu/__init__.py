"""elasticsearch_tpu — a TPU-native distributed search engine.

A brand-new framework with the capabilities of Elasticsearch (reference:
anti-social/elasticsearch, ES 2.0.0-SNAPSHOT / Lucene 5.1.0), re-designed
TPU-first: shards are HBM-resident columnar partitions, BM25 scoring /
top-k / aggregations run as batched JAX+Pallas device programs, and the
cross-shard reduce is performed with ICI collectives inside one jitted
computation instead of on a coordinating node.

Layer map (mirrors reference SURVEY.md §1, re-architected):
  utils/     foundation: settings, errors, metrics, breakers (ref: common/)
  models/    similarity scoring models: BM25 et al (ref: index/similarity/)
  index/     analysis, mapping, columnar segments, engine, translog
             (ref: index/analysis, index/mapper, index/engine, index/translog)
  ops/       device kernels: scoring, top-k, aggregations (ref: the Lucene
             BulkScorer/collector hot loops in search/query/QueryPhase.java)
  search/    query DSL -> IR, per-shard execution, agg tree, shard reduce
             (ref: index/query/, search/)
  parallel/  device mesh, sharded multi-shard search, collectives
             (ref: cluster/routing/ data parallelism + SearchPhaseController)
  cluster/   cluster state, routing, allocation (ref: cluster/)
  transport/ host-side RPC (ref: transport/)
  rest/      HTTP JSON API (ref: rest/)
"""

__version__ = "0.1.0"
