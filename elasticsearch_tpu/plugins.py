"""Plugins framework: discover, load and wire node plugins.

Reference analog: plugins/PluginsService.java + plugins/AbstractPlugin —
ES 1.x scans `<path.plugins>` for plugin directories (each naming a
Plugin class in es-plugin.properties), instantiates them, and lets them
contribute through onModule hooks; `_nodes?plugin=true` and
`_cat/plugins` list what loaded.

Python-native shape: a plugin is a directory under `path.plugins`
containing `plugin.py` that defines a `Plugin` class:

    class Plugin:
        name = "my-analysis"            # defaults to the dir name
        description = "..."
        version = "1.0"
        # every hook below is optional:
        def tokenizers(self):   return {"my_tok": factory}
        def token_filters(self): return {"my_filter": factory}
        def analyzers(self):    return {"my_analyzer": factory}
        def queries(self):      return {"my_query": parse_fn}
        def rest_routes(self, dispatcher): dispatcher.route(...)
        def on_node(self, node): ...

Analysis hooks merge into the module registries consulted by every
AnalysisService (index/analysis.py), query hooks into the QueryParser's
custom-parser registry (search/query_dsl.py) — the same extension
points the reference's AnalysisModule / IndicesQueriesModule expose.
"""

from __future__ import annotations

import importlib.util
import logging
import os

from .utils.settings import Settings

logger = logging.getLogger(__name__)


class PluginInfo:
    def __init__(self, name: str, description: str, version: str,
                 path: str):
        self.name = name
        self.description = description
        self.version = version
        self.path = path

    def to_dict(self) -> dict:
        # shape of NodeInfo.plugins entries (ref: plugins/PluginInfo.java)
        return {"name": self.name, "version": self.version,
                "description": self.description,
                "jvm": False, "site": False, "url": ""}


class PluginsService:
    """Loads plugins once at node construction (ref:
    PluginsService.java:95 loadPluginsIntoClassLoader + onModule
    dispatch)."""

    def __init__(self, settings: Settings = Settings.EMPTY,
                 plugins_dir: str | None = None):
        self.plugins: list[tuple[PluginInfo, object]] = []
        directory = plugins_dir or settings.get_str("path.plugins")
        if directory and os.path.isdir(directory):
            self._load_dir(directory)

    def _load_dir(self, directory: str) -> None:
        for entry in sorted(os.listdir(directory)):
            pdir = os.path.join(directory, entry)
            src = os.path.join(pdir, "plugin.py")
            if not os.path.isfile(src):
                continue
            try:
                spec = importlib.util.spec_from_file_location(
                    f"es_tpu_plugin_{entry}", src)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)  # type: ignore[union-attr]
                cls = getattr(mod, "Plugin", None)
                if cls is None:
                    logger.warning("plugin [%s] has no Plugin class",
                                   entry)
                    continue
                plugin = cls()
                info = PluginInfo(
                    name=str(getattr(plugin, "name", entry) or entry),
                    description=str(getattr(plugin, "description", "")),
                    version=str(getattr(plugin, "version", "NA")),
                    path=pdir)
                self.plugins.append((info, plugin))
                logger.info("loaded plugin [%s]", info.name)
            except Exception:
                # a broken plugin must not kill the node (the reference
                # FAILS startup here; we degrade — surfaced in the log)
                logger.exception("failed to load plugin [%s]", entry)

    # -- hook dispatch ------------------------------------------------------

    def _collect(self, hook: str) -> dict:
        out: dict = {}
        for info, plugin in self.plugins:
            fn = getattr(plugin, hook, None)
            if callable(fn):
                try:
                    out.update(fn() or {})
                except Exception:
                    logger.exception("plugin [%s] hook [%s] failed",
                                     info.name, hook)
        return out

    def apply_analysis_hooks(self) -> None:
        """Merge analysis contributions into the module registries every
        AnalysisService consults (ref: AnalysisModule bindings).
        tokenizers()/token_filters() return bare token-stream callables
        (usable by name in custom chains); *_factories() return
        Settings-parameterized factories."""
        from .index import analysis as a
        a.TOKENIZERS.update(self._collect("tokenizers"))
        a.TOKEN_FILTERS.update(self._collect("token_filters"))
        a.TOKENIZER_FACTORIES.update(self._collect("tokenizer_factories"))
        a.FILTER_FACTORIES.update(
            self._collect("token_filter_factories"))
        for name, factory in self._collect("analyzers").items():
            try:
                a.register_analyzer(name, factory)
            except Exception:
                # degrade, don't fail the node — same contract as every
                # other hook
                logger.exception("plugin analyzer [%s] rejected", name)

    def apply_query_hooks(self) -> None:
        """Ref: IndicesQueriesModule — custom query names dispatched by
        the parser."""
        from .search import query_dsl
        query_dsl.CUSTOM_QUERY_PARSERS.update(self._collect("queries"))

    def apply_rest_hooks(self, dispatcher) -> None:
        for info, plugin in self.plugins:
            fn = getattr(plugin, "rest_routes", None)
            if callable(fn):
                try:
                    fn(dispatcher)
                except Exception:
                    logger.exception("plugin [%s] rest_routes failed",
                                     info.name)

    def apply_node_hooks(self, node) -> None:
        for info, plugin in self.plugins:
            fn = getattr(plugin, "on_node", None)
            if callable(fn):
                try:
                    fn(node)
                except Exception:
                    logger.exception("plugin [%s] on_node failed",
                                     info.name)

    def info(self) -> list[dict]:
        return [i.to_dict() for i, _ in self.plugins]
