"""Action-name-routed request/response transport.

Reference analog: transport/TransportService.java:272-304 (sendRequest),
:393 (registerHandler) over Netty, plus transport/local/LocalTransport.java
— the in-JVM message-passing backend the reference's whole integration
test suite runs on. We keep the same architecture: every node registers
typed handlers under action names ("internal:discovery/ping",
"indices:data/read/search[query]"); requests are routed by (node_id,
action) through a shared in-process hub. A real multi-host deployment
swaps the hub for a gRPC/Arrow-Flight channel with the same interface;
the TPU data plane never goes through here — bulk tensor traffic rides
ICI inside pjit programs, this carries control-plane RPCs only.

Disruption hooks (drop/delay/partition) mirror
test/transport/MockTransportService.java and test/disruption/* — they are
first-class here because the failure-detection code is tested through
them.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable

from ..utils.errors import ElasticsearchTpuError


class TransportError(ElasticsearchTpuError):
    status = 500


class NodeNotConnectedError(TransportError):
    pass


class RequestTimeoutError(TransportError):
    status = 504


class LocalHub:
    """Shared in-process wire: node_id -> Transport. One per test cluster.

    Ref: LocalTransport.transports static map (LocalTransport.java).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._nodes: dict[str, "Transport"] = {}
        # disruption state
        self._partitions: set[frozenset] = set()      # {frozenset({a,b}), ...}
        self._delays: dict[frozenset, float] = {}
        self._dropped_nodes: set[str] = set()
        # (dst_node, action) pairs that fail to send — the per-action
        # rule of test/transport/MockTransportService.java
        self._dropped_actions: set[tuple[str, str]] = set()

    def register(self, node_id: str, transport: "Transport") -> None:
        with self._lock:
            self._nodes[node_id] = transport

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def get(self, node_id: str) -> "Transport | None":
        with self._lock:
            return self._nodes.get(node_id)

    def node_ids(self) -> list[str]:
        with self._lock:
            return list(self._nodes)

    def create_transport(self, node_id: str, **kw) -> "Transport":
        """Factory shared with TcpHub (cluster/tcp_transport.py): nodes
        ask their hub for a transport, so the same node code runs over
        in-process wiring or real sockets."""
        return Transport(node_id, self, **kw)

    # -- disruption schemes (ref: test/disruption/NetworkPartition.java) ----

    def partition(self, side_a: list[str], side_b: list[str]) -> None:
        """Drop all traffic between the two sides, both directions."""
        with self._lock:
            for a in side_a:
                for b in side_b:
                    self._partitions.add(frozenset((a, b)))

    def heal(self) -> None:
        with self._lock:
            self._partitions.clear()
            self._delays.clear()
            self._dropped_nodes.clear()
            self._dropped_actions.clear()

    def drop_action(self, dst: str, action: str) -> None:
        """Fail sends of one ACTION to one node while everything else
        (heartbeats, publishes) flows — MockTransportService's
        per-action fail rule."""
        with self._lock:
            self._dropped_actions.add((dst, action))

    def restore_action(self, dst: str, action: str) -> None:
        with self._lock:
            self._dropped_actions.discard((dst, action))

    def _action_ok(self, dst: str, action: str) -> bool:
        with self._lock:
            return (dst, action) not in self._dropped_actions

    def isolate(self, node_id: str) -> None:
        """Drop all traffic to/from one node (NetworkDisconnectPartition)."""
        with self._lock:
            self._dropped_nodes.add(node_id)

    def rejoin(self, node_id: str) -> None:
        with self._lock:
            self._dropped_nodes.discard(node_id)

    def delay(self, a: str, b: str, seconds: float) -> None:
        """Symmetric link delay (NetworkDelaysPartition)."""
        with self._lock:
            self._delays[frozenset((a, b))] = seconds

    def _link_state(self, src: str, dst: str) -> tuple[bool, float]:
        with self._lock:
            if src in self._dropped_nodes or dst in self._dropped_nodes:
                return False, 0.0
            if frozenset((src, dst)) in self._partitions:
                return False, 0.0
            return True, self._delays.get(frozenset((src, dst)), 0.0)


Handler = Callable[[str, dict], dict]  # (source_node_id, request) -> response


class Transport:
    """Per-node endpoint: handler registry + request sending.

    Ref: TransportService.java:58. Handlers run on a small per-node pool
    (the reference's threadpool executor per action); send_request is
    async returning a Future, with a sync convenience.
    """

    def __init__(self, node_id: str, hub: LocalHub, n_threads: int = 2,
                 tracer_include: tuple = (), tracer_exclude: tuple = ()):
        self.node_id = node_id
        self.hub = hub
        self._handlers: dict[str, Handler] = {}
        self._pool = ThreadPoolExecutor(max_workers=n_threads,
                                        thread_name_prefix=f"transport-{node_id}")
        self._closed = False
        # action tracer (ref: TransportService.java:84-109 —
        # transport.tracer.include/exclude glob patterns, logged on the
        # "transport.tracer" logger)
        self.tracer_include = tuple(tracer_include)
        self.tracer_exclude = tuple(tracer_exclude)
        hub.register(node_id, self)

    def set_tracer(self, include: tuple = (), exclude: tuple = ()) -> None:
        self.tracer_include = tuple(include)
        self.tracer_exclude = tuple(exclude)

    def _trace(self, direction: str, target: str, action: str) -> None:
        if not self.tracer_include:
            return
        import fnmatch
        import logging
        if not any(fnmatch.fnmatch(action, p) for p in self.tracer_include):
            return
        if any(fnmatch.fnmatch(action, p) for p in self.tracer_exclude):
            return
        logging.getLogger("transport.tracer").info(
            "[%s] %s [%s] to/from [%s]", self.node_id, direction, action,
            target)

    def register_handler(self, action: str, handler: Handler) -> None:
        self._handlers[action] = handler

    def add_peer(self, node_id: str, addr) -> None:
        """Interface parity with TcpTransport.add_peer: the in-process
        hub routes by node id (a replacement re-registers under the
        same id, overwriting the dead entry), so there is no address
        to learn — the membership layer calls this unconditionally
        after a join admit."""

    def submit_request(self, target: str, action: str, request: dict,
                       timeout: float = 10.0) -> Future:
        """Async send. The future resolves to the handler's response dict
        or raises TransportError subclasses. `timeout` is accepted for
        interface parity with TcpTransport (callers pass it through the
        shared hub API); the in-process wire has no socket to bound, so
        only the caller's own future wait applies it."""
        fut: Future = Future()
        self._trace("sent request", target, action)
        ok, delay = self.hub._link_state(self.node_id, target)
        ok = ok and self.hub._action_ok(target, action)
        peer = self.hub.get(target)
        if not ok or peer is None or peer._closed:
            fut.set_exception(NodeNotConnectedError(
                f"[{self.node_id}] cannot reach [{target}] for [{action}]"))
            return fut
        src = self.node_id

        def run():
            if delay:
                time.sleep(delay)
            # re-check the link after the delay (partition may have formed)
            ok2, _ = self.hub._link_state(src, target)
            p2 = self.hub.get(target)
            if not ok2 or p2 is None or p2._closed:
                fut.set_exception(NodeNotConnectedError(
                    f"[{src}] lost [{target}] during [{action}]"))
                return
            handler = p2._handlers.get(action)
            if handler is None:
                fut.set_exception(TransportError(
                    f"no handler for [{action}] on [{target}]"))
                return
            try:
                fut.set_result(handler(src, request))
            except BaseException as e:  # noqa: BLE001 — carried to caller
                fut.set_exception(e)

        try:
            peer._pool.submit(run)
        except RuntimeError:  # pool shut down concurrently
            fut.set_exception(NodeNotConnectedError(
                f"[{self.node_id}] cannot reach [{target}] for [{action}]"))
        return fut

    def send_request(self, target: str, action: str, request: dict,
                     timeout: float = 10.0) -> dict:
        fut = self.submit_request(target, action, request)
        try:
            return fut.result(timeout=timeout)
        except TimeoutError:
            raise RequestTimeoutError(
                f"[{action}] to [{target}] timed out after {timeout}s") from None

    def close(self) -> None:
        self._closed = True
        self.hub.unregister(self.node_id)
        self._pool.shutdown(wait=False, cancel_futures=True)
