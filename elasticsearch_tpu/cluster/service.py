"""ClusterService: the single-threaded prioritized state-update loop.

Reference analog: cluster/service/InternalClusterService.java — ALL
cluster-state mutations are ClusterStateUpdateTasks executed one at a
time on one dedicated thread (:78, :151), submitted at :260-285; after a
task produces a new state the service publishes it (master only) and
notifies listeners (UpdateTask.run :349+). Acked tasks
(AckedClusterStateUpdateTask :412-418) complete when every node confirms
the published version.

Serializing mutations through one loop is what makes the immutable-state
model race-free: tasks are pure functions ClusterState -> ClusterState.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
from concurrent.futures import Future
from typing import Callable

from .state import ClusterState

logger = logging.getLogger("elasticsearch_tpu.cluster")

# priority values — ref: common/Priority.java (IMMEDIATE..LANGUID)
IMMEDIATE, URGENT, HIGH, NORMAL, LOW = 0, 1, 2, 3, 4

StateUpdate = Callable[[ClusterState], ClusterState]
StateListener = Callable[[ClusterState, ClusterState], None]


class ClusterService:
    """Owns `self.state` (the node's current ClusterState) and the update
    thread. On master nodes `publisher` pushes each new state to the rest
    of the cluster before listeners run (publish-then-apply, like
    ZenDiscovery.publish); non-masters receive state via
    `apply_published_state`.
    """

    def __init__(self, initial: ClusterState, node_id: str,
                 publisher: Callable[[ClusterState], None] | None = None):
        self.node_id = node_id
        self.state = initial
        self.publisher = publisher
        self._listeners: list[StateListener] = []
        self._queue: list[tuple[int, int, str, StateUpdate, Future]] = []
        self._seq = itertools.count()
        self._cv = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, name=f"clusterService#updateTask[{node_id}]",
            daemon=True)
        self._thread.start()

    # -- listeners ----------------------------------------------------------

    def add_listener(self, listener: StateListener) -> None:
        self._listeners.append(listener)

    # -- task submission ----------------------------------------------------

    def submit_state_update_task(self, source: str, task: StateUpdate,
                                 priority: int = NORMAL) -> Future:
        """Ref: InternalClusterService.submitStateUpdateTask:260-285.
        Returns a Future resolving to the resulting ClusterState."""
        fut: Future = Future()
        with self._cv:
            if self._stopped:
                fut.set_exception(RuntimeError("cluster service stopped"))
                return fut
            heapq.heappush(self._queue,
                           (priority, next(self._seq), source, task, fut))
            self._cv.notify()
        return fut

    def apply_published_state(self, new_state: ClusterState) -> Future:
        """Non-master path: adopt a state the master published. Runs on
        the same single update thread to preserve ordering; stale
        versions are rejected (ref: ZenDiscovery.processNextPendingClusterState
        version checks)."""
        def adopt(current: ClusterState) -> ClusterState:
            if (new_state.master_term, new_state.version) < \
                    (current.master_term, current.version):
                logger.debug("[%s] dropping stale published state v%d < v%d",
                             self.node_id, new_state.version, current.version)
                return current
            return new_state
        return self.submit_state_update_task("published-state", adopt,
                                             priority=URGENT)

    # -- loop ---------------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    self._cv.wait()
                if self._stopped and not self._queue:
                    return
                _, _, source, task, fut = heapq.heappop(self._queue)
            prev = self.state
            try:
                new = task(prev)
            except Exception as e:
                logger.exception("[%s] cluster state task [%s] failed",
                                 self.node_id, source)
                fut.set_exception(e)
                continue
            if new is prev or new == prev:
                fut.set_result(prev)
                continue
            self.state = new
            if self.publisher is not None and \
                    new.nodes.master_node_id == self.node_id:
                try:
                    self.publisher(new)
                except Exception:
                    logger.exception("[%s] publish of v%d failed",
                                     self.node_id, new.version)
            for listener in list(self._listeners):
                try:
                    listener(prev, new)
                except Exception:
                    logger.exception("[%s] cluster state listener failed "
                                     "(source=%s)", self.node_id, source)
            fut.set_result(new)

    def close(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
