"""Wire format: versioned, compressed serialization for inter-node RPC.

Reference analog: common/io/stream/ (Streamable binary wire format) +
the LZF compression PublishClusterStateAction applies to full-state
publishes (discovery/zen/publish/PublishClusterStateAction.java:114).

Deviation: instead of per-class Streamable implementations, one tagged
JSON codec covers every payload the transport carries — plain JSON
scalars/dicts/lists plus:

  {"__b64__": ...}   bytes (doc sources, translog ops)
  {"__nd__": ...}    numpy arrays (distributed agg partials)
  {"__nps__": ...}   numpy scalars
  {"__cs__": ...}    ClusterState (the publish payload)
  {"__sr__": ...}    ShardRouting (shard started/failed reports)

zlib replaces LZF (same role — stdlib has no LZF; zlib level 1 is in
the same speed class). Frames on the socket are 4-byte big-endian
length + compressed body, little enough protocol that any language
could speak it.
"""

from __future__ import annotations

import base64
import json
import zlib
from dataclasses import asdict

import numpy as np

from .state import (ClusterBlock, ClusterBlocks, ClusterState,
                    DiscoveryNode, DiscoveryNodes, IndexMetadata,
                    IndexRoutingTable, IndexShardRoutingTable, Metadata,
                    RoutingTable, ShardRouting, ShardState)

WIRE_VERSION = 1


# ---------------------------------------------------------------------------
# ClusterState tree <-> plain dicts
# ---------------------------------------------------------------------------


def shard_to_dict(s: ShardRouting) -> dict:
    return {"index": s.index, "shard": s.shard, "primary": s.primary,
            "state": s.state.value, "node_id": s.node_id,
            "relocating_node_id": s.relocating_node_id,
            "allocation_id": s.allocation_id,
            "was_assigned": s.was_assigned}


def shard_from_dict(d: dict) -> ShardRouting:
    return ShardRouting(
        index=d["index"], shard=d["shard"], primary=d["primary"],
        state=ShardState(d["state"]), node_id=d.get("node_id"),
        relocating_node_id=d.get("relocating_node_id"),
        allocation_id=d.get("allocation_id"),
        was_assigned=bool(d.get("was_assigned", False)))


def state_to_dict(cs: ClusterState) -> dict:
    """Full-state serialization (ref: ClusterState.writeTo)."""
    return {
        "cluster_name": cs.cluster_name,
        "version": cs.version,
        "master_term": cs.master_term,
        "nodes": {
            "master_node_id": cs.nodes.master_node_id,
            "local_node_id": cs.nodes.local_node_id,
            "nodes": {nid: asdict(n)
                      for nid, n in cs.nodes.nodes.items()},
        },
        "routing_table": {
            name: [[shard_to_dict(c) for c in group.copies]
                   for group in tbl.shards]
            for name, tbl in cs.routing_table.indices.items()
        },
        "metadata": {
            "version": cs.metadata.version,
            "indices": {name: asdict(imd)
                        for name, imd in cs.metadata.indices.items()},
            "templates": dict(cs.metadata.templates),
            "persistent_settings": dict(cs.metadata.persistent_settings),
            "transient_settings": dict(cs.metadata.transient_settings),
        },
        "blocks": {
            "global": [asdict(b) for b in cs.blocks.global_blocks],
            "indices": {name: [asdict(b) for b in blocks]
                        for name, blocks in
                        cs.blocks.index_blocks.items()},
        },
    }


def state_from_dict(d: dict) -> ClusterState:
    nodes = DiscoveryNodes(
        nodes={nid: DiscoveryNode(**n)
               for nid, n in d["nodes"]["nodes"].items()},
        master_node_id=d["nodes"].get("master_node_id"),
        local_node_id=d["nodes"].get("local_node_id"))
    indices = {}
    for name, groups in d["routing_table"].items():
        tables = []
        for sid, copies in enumerate(groups):
            tables.append(IndexShardRoutingTable(
                name, sid, tuple(shard_from_dict(c) for c in copies)))
        indices[name] = IndexRoutingTable(name, tuple(tables))
    md = d["metadata"]

    def block(b: dict) -> ClusterBlock:
        return ClusterBlock(block_id=b["block_id"],
                            description=b["description"],
                            retryable=b["retryable"],
                            levels=tuple(b["levels"]))
    return ClusterState(
        cluster_name=d["cluster_name"],
        version=d["version"],
        master_term=d.get("master_term", 0),
        nodes=nodes,
        routing_table=RoutingTable(indices),
        metadata=Metadata(
            indices={name: IndexMetadata(**{
                **imd, "aliases": tuple(imd.get("aliases", ()))})
                for name, imd in md["indices"].items()},
            templates=md.get("templates", {}),
            persistent_settings=md.get("persistent_settings", {}),
            transient_settings=md.get("transient_settings", {}),
            version=md.get("version", 0)),
        blocks=ClusterBlocks(
            global_blocks=tuple(block(b) for b in d["blocks"]["global"]),
            index_blocks={name: tuple(block(b) for b in blocks)
                          for name, blocks in
                          d["blocks"]["indices"].items()}),
    )


# ---------------------------------------------------------------------------
# tagged payload codec
# ---------------------------------------------------------------------------


_TAGS = ("__cs__", "__sr__", "__b64__", "__nd__", "__nps__", "__kvs__",
         "__esc__")


def _is_tagged(d: dict) -> bool:
    return len(d) == 1 and next(iter(d)) in _TAGS


def to_wire(obj):
    """Payload object -> JSON-compatible structure."""
    if isinstance(obj, ClusterState):
        return {"__cs__": state_to_dict(obj)}
    if isinstance(obj, ShardRouting):
        return {"__sr__": shard_to_dict(obj)}
    if isinstance(obj, ShardState):
        return obj.value
    if isinstance(obj, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(obj)).decode()}
    if isinstance(obj, np.ndarray):
        return {"__nd__": {
            "dtype": str(obj.dtype), "shape": list(obj.shape),
            "data": base64.b64encode(
                np.ascontiguousarray(obj).tobytes()).decode()}}
    if isinstance(obj, np.generic):
        return {"__nps__": {"dtype": str(obj.dtype),
                            "value": obj.item()}}
    if isinstance(obj, dict):
        if any(not isinstance(k, str) for k in obj):
            # non-string keys (histogram epoch-millis buckets, percentile
            # bin centers) survive as typed key/value pairs — JSON would
            # silently stringify them and break cross-shard merges
            return {"__kvs__": [[to_wire(k), to_wire(v)]
                                for k, v in obj.items()]}
        if _is_tagged(obj):
            # USER data that happens to look like one of our tags must
            # round-trip unchanged, not be decoded as the tagged type
            return {"__esc__": {k: to_wire(v) for k, v in obj.items()}}
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    return obj


def _key_from_wire(k):
    k = from_wire(k)
    if isinstance(k, list):
        return tuple(k)  # tuple keys decode as lists; restore hashable
    return k


def from_wire(obj):
    if isinstance(obj, dict):
        if _is_tagged(obj):
            tag, val = next(iter(obj.items()))
            if tag == "__cs__":
                return state_from_dict(val)
            if tag == "__sr__":
                return shard_from_dict(val)
            if tag == "__b64__":
                return base64.b64decode(val)
            if tag == "__nd__":
                return np.frombuffer(
                    base64.b64decode(val["data"]),
                    dtype=np.dtype(val["dtype"])).reshape(val["shape"])
            if tag == "__nps__":
                return np.dtype(val["dtype"]).type(val["value"])
            if tag == "__kvs__":
                return {_key_from_wire(k): from_wire(v) for k, v in val}
            if tag == "__esc__":
                return {k: from_wire(v) for k, v in val.items()}
        return {k: from_wire(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [from_wire(v) for v in obj]
    return obj


def encode_frame(msg: dict) -> bytes:
    """Message dict -> compressed wire body (no length prefix)."""
    body = json.dumps({"v": WIRE_VERSION, "msg": to_wire(msg)},
                      separators=(",", ":")).encode()
    return zlib.compress(body, level=1)


def decode_frame(data: bytes) -> dict:
    wrapper = json.loads(zlib.decompress(data))
    if wrapper.get("v") != WIRE_VERSION:
        raise ValueError(f"wire version mismatch: {wrapper.get('v')}")
    return from_wire(wrapper["msg"])
