"""ClusterNode: one control-plane participant; LocalCluster: N of them.

Reference analog: node/Node.java wiring (ClusterService + Discovery +
AllocationService + metadata services through Guice, :166-200) and the
test harness test/InternalTestCluster.java:330 which boots a whole
multi-node cluster inside one process over LocalTransport — the pattern
this module reproduces with plain composition instead of DI.

Master-side metadata mutations (create/delete index, settings, mapping)
are ClusterStateUpdateTasks exactly like
cluster/metadata/MetaDataCreateIndexService.java etc.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future

from .allocation import AllocationService
from .discovery import Discovery
from .service import ClusterService, HIGH
from .state import (ClusterState, ClusterBlocks, DiscoveryNode,
                    DiscoveryNodes, IndexMetadata, IndexRoutingTable,
                    STATE_NOT_RECOVERED_BLOCK, ShardState, health_of)
from .transport import LocalHub, Transport, TransportError
from ..utils.errors import (IllegalArgumentError, IndexAlreadyExistsError,
                            IndexNotFoundError)

CREATE_INDEX_ACTION = "internal:admin/index/create"
DELETE_INDEX_ACTION = "internal:admin/index/delete"
UPDATE_SETTINGS_ACTION = "internal:admin/settings/update"
PUT_MAPPING_ACTION = "internal:admin/mapping/put"
UPDATE_ALIASES_ACTION = "internal:admin/aliases/update"
PUT_TEMPLATE_ACTION = "internal:admin/template/put"
DELETE_TEMPLATE_ACTION = "internal:admin/template/delete"
REROUTE_ACTION = "internal:admin/reroute"
ALLOCATION_EXPLAIN_ACTION = "internal:admin/allocation/explain"


class ClusterNode:
    """Control-plane node: join, elect, publish, allocate, metadata ops.

    The data plane (actual shards: engines + device columns) attaches via
    `state_appliers` — callables invoked on every cluster state change,
    the IndicesClusterStateService.clusterChanged analog.
    """

    def __init__(self, node_id: str, hub: LocalHub, *,
                 master_eligible: bool = True, data: bool = True,
                 attributes: dict | None = None,
                 min_master_nodes: int = 1,
                 cluster_name: str = "elasticsearch-tpu",
                 allocation: AllocationService | None = None):
        self.node = DiscoveryNode(node_id, master_eligible=master_eligible,
                                  data=data, attributes=attributes or {})
        # the hub (LocalHub or TcpHub) decides the transport backend
        self.transport = hub.create_transport(node_id)
        initial = ClusterState(
            cluster_name=cluster_name,
            nodes=DiscoveryNodes({node_id: self.node},
                                 local_node_id=node_id),
            blocks=ClusterBlocks(global_blocks=(STATE_NOT_RECOVERED_BLOCK,)))
        self.allocation = allocation or AllocationService()
        self.cluster = ClusterService(initial, node_id,
                                      publisher=self._publish)
        self.discovery = Discovery(self.node, self.transport, self.cluster,
                                   self.allocation,
                                   min_master_nodes=min_master_nodes)
        self.transport.register_handler(CREATE_INDEX_ACTION, self._on_create_index)
        self.transport.register_handler(DELETE_INDEX_ACTION, self._on_delete_index)
        self.transport.register_handler(UPDATE_SETTINGS_ACTION,
                                        self._on_update_settings)
        self.transport.register_handler(PUT_MAPPING_ACTION, self._on_put_mapping)
        self.transport.register_handler(UPDATE_ALIASES_ACTION,
                                        self._on_update_aliases)
        self.transport.register_handler(PUT_TEMPLATE_ACTION,
                                        self._on_put_template)
        self.transport.register_handler(DELETE_TEMPLATE_ACTION,
                                        self._on_delete_template)
        self.transport.register_handler(REROUTE_ACTION, self._on_reroute)
        self.transport.register_handler(ALLOCATION_EXPLAIN_ACTION,
                                        self._on_allocation_explain)
        # dynamic transport action tracing: cluster settings
        # transport.tracer.{include,exclude} (comma'd glob patterns)
        # apply live on every node (ref: TransportService.java:84-109
        # TRACE_LOG_INCLUDE/EXCLUDE_SETTING dynamic updates)
        self._tracer_key: tuple | None = None
        self.cluster.add_listener(self._apply_tracer_settings)

    def _apply_tracer_settings(self, prev, new) -> None:
        merged = {**new.metadata.persistent_settings,
                  **new.metadata.transient_settings}
        inc = str(merged.get("transport.tracer.include", "") or "")
        exc = str(merged.get("transport.tracer.exclude", "") or "")
        key = (inc, exc)
        if key == self._tracer_key:
            return
        self._tracer_key = key
        set_tracer = getattr(self.transport, "set_tracer", None)
        if set_tracer is not None:
            set_tracer(tuple(p.strip() for p in inc.split(",")
                             if p.strip()),
                       tuple(p.strip() for p in exc.split(",")
                             if p.strip()))

    # -- lifecycle ----------------------------------------------------------

    def _publish(self, state: ClusterState) -> None:
        self.discovery.publish(state)

    @property
    def state(self) -> ClusterState:
        return self.cluster.state

    @property
    def is_master(self) -> bool:
        return self.discovery.is_master

    def join(self) -> None:
        self.discovery.join_cluster()
        # initial state is recovered once a master exists (GatewayService
        # analog: lift STATE_NOT_RECOVERED once recover_after_nodes is met)
        if self.is_master:
            self._recover_persisted_state()

            def lift(cur: ClusterState) -> ClusterState:
                if not cur.blocks.has_global_block(STATE_NOT_RECOVERED_BLOCK):
                    return cur
                return cur.bump(blocks=cur.blocks.without_global(
                    STATE_NOT_RECOVERED_BLOCK))
            self.cluster.submit_state_update_task("state-recovered",
                                                  lift, HIGH).result(10)

    def _recover_persisted_state(self) -> None:
        """Hook for gateway metadata recovery (DataNode overrides);
        runs on the elected master BEFORE the not-recovered block lifts."""

    def close(self) -> None:
        self.discovery.stop_heartbeats()
        self.cluster.close()
        self.transport.close()

    # -- master-node operation template -------------------------------------

    def _to_master(self, action: str, request: dict, retries: int = 3) -> dict:
        """Forward an admin op to the elected master (ref:
        TransportMasterNodeOperationAction.java, retry on no-master)."""
        import time as _time
        for attempt in range(retries):
            master = self.state.nodes.master_node_id
            if master is None:
                self.discovery.join_cluster()
                master = self.state.nodes.master_node_id
                if master is None:
                    if attempt == retries - 1:
                        raise TransportError("no elected master")
                    _time.sleep(0.1)
                    continue
            if master == self.node.node_id:
                handler = self.transport._handlers[action]
                return handler(self.node.node_id, request)
            try:
                return self.transport.send_request(master, action, request)
            except TransportError:
                if attempt == retries - 1:
                    raise
                _time.sleep(0.1)
        raise TransportError("unreachable")  # pragma: no cover

    # -- metadata services (master side) -------------------------------------

    def _on_create_index(self, src: str, req: dict) -> dict:
        name = req["index"]
        # explicit request values (args or request settings) outrank
        # template settings; bare defaults only apply when neither spoke
        shards_req = req.get("number_of_shards")
        replicas_req = req.get("number_of_replicas")
        settings = dict(req.get("settings") or {})
        mappings = dict(req.get("mappings") or {})

        def task(cur: ClusterState) -> ClusterState:
            if cur.metadata.index(name) is not None:
                raise IndexAlreadyExistsError(name)
            # apply matching cluster templates, lowest order first (ref:
            # MetaDataCreateIndexService template merge)
            import fnmatch
            t_settings: dict = {}
            t_mappings: dict = {}
            matching = sorted(
                (t for t in cur.metadata.templates.values()
                 if any(fnmatch.fnmatch(name, p) for p in
                        ([t.get("template")] if isinstance(
                            t.get("template"), str)
                         else list(t.get("index_patterns") or [])))),
                key=lambda t: int(t.get("order", 0)))
            for t in matching:
                t_settings.update(t.get("settings") or {})
                t_mappings.update(t.get("mappings") or {})
            eff_settings = {**t_settings, **settings}
            eff_mappings = {**t_mappings, **mappings}

            def _eff(key: str, explicit, default: int) -> int:
                if explicit is not None:
                    return int(explicit)
                for src_ in (settings, t_settings):
                    for k in (key, f"index.{key}"):
                        if k in src_:
                            return int(src_[k])
                return default

            eff_shards = _eff("number_of_shards", shards_req, 1)
            eff_replicas = _eff("number_of_replicas", replicas_req, 0)
            imd = IndexMetadata(name, number_of_shards=eff_shards,
                                number_of_replicas=eff_replicas,
                                settings=eff_settings,
                                mappings=eff_mappings)
            md = cur.metadata.with_index(imd)
            rt = cur.routing_table.with_index(
                IndexRoutingTable.new(name, eff_shards, eff_replicas))
            return self.allocation.reroute(cur.bump(metadata=md,
                                                    routing_table=rt))
        self.cluster.submit_state_update_task(
            f"create-index[{name}]", task, HIGH).result(10)
        return {"acknowledged": True, "index": name}

    def _on_delete_index(self, src: str, req: dict) -> dict:
        name = req["index"]

        def task(cur: ClusterState) -> ClusterState:
            if cur.metadata.index(name) is None:
                raise IndexNotFoundError(name)
            return cur.bump(metadata=cur.metadata.without_index(name),
                            routing_table=cur.routing_table.without_index(name))
        self.cluster.submit_state_update_task(
            f"delete-index[{name}]", task, HIGH).result(10)
        return {"acknowledged": True}

    def _on_update_settings(self, src: str, req: dict) -> dict:
        persistent = dict(req.get("persistent") or {})
        transient = dict(req.get("transient") or {})
        index = req.get("index")
        index_settings = dict(req.get("index_settings") or {})

        def task(cur: ClusterState) -> ClusterState:
            md = cur.metadata
            if index is not None:
                imd = md.index(index)
                if imd is None:
                    raise IndexNotFoundError(index)
                new_settings = {**imd.settings, **index_settings}
                changes = {"settings": new_settings}
                if "index.number_of_replicas" in index_settings:
                    n_rep = int(index_settings["index.number_of_replicas"])
                    changes["number_of_replicas"] = n_rep
                import dataclasses
                imd2 = dataclasses.replace(imd, version=imd.version + 1,
                                           **changes)
                md = md.with_index(imd2)
                new = cur.bump(metadata=md)
                if "index.number_of_replicas" in index_settings:
                    new = _resize_replicas(new, index,
                                           imd2.number_of_replicas)
                    new = self.allocation.reroute(new)
                return new
            import dataclasses
            md = dataclasses.replace(
                md,
                persistent_settings={**md.persistent_settings, **persistent},
                transient_settings={**md.transient_settings, **transient},
                version=md.version + 1)
            return self.allocation.reroute(cur.bump(metadata=md))
        self.cluster.submit_state_update_task("update-settings", task,
                                              HIGH).result(10)
        return {"acknowledged": True}

    def _on_reroute(self, src: str, req: dict) -> dict:
        """Explicit allocation commands (ref: action/admin/cluster/
        reroute/TransportClusterRerouteAction + the command classes under
        cluster/routing/allocation/command/)."""
        commands = list(req.get("commands") or [])

        def task(cur: ClusterState) -> ClusterState:
            state = cur
            from ..utils.errors import IllegalArgumentError
            for cmd in commands:
                if not isinstance(cmd, dict) or not cmd:
                    raise IllegalArgumentError(
                        "malformed reroute command (expected "
                        "{\"<command>\": {...}})")
                name, args = next(iter(cmd.items()))
                args = dict(args or {})
                index = args.get("index")
                shard = int(args.get("shard", 0))
                if name == "move":
                    state = self.allocation.move(
                        state, index, shard,
                        str(args.get("from_node")), str(args.get("to_node")))
                elif name == "cancel":
                    state = self.allocation.cancel_relocation(
                        state, index, shard, str(args.get("node")))
                else:
                    raise IllegalArgumentError(
                        f"unknown reroute command [{name}]")
            # bare reroute request (no commands): run the allocator
            return state if state is not cur \
                else self.allocation.reroute(state)
        self.cluster.submit_state_update_task("cluster-reroute", task,
                                              HIGH).result(10)
        return {"acknowledged": True}

    def reroute(self, commands: list[dict] | None = None) -> dict:
        return self._to_master(REROUTE_ACTION, {"commands": commands or []})

    def _on_allocation_explain(self, src: str, req: dict) -> dict:
        """Ref: the _cluster/allocation/explain API — a read of the
        master's current state through the deciders, no state task."""
        state = self.state
        index = req.get("index")
        shard = req.get("shard")
        primary = bool(req.get("primary", True))
        if index is None:
            # default: the first unassigned copy, like the reference API
            un = next((s for s in state.routing_table.all_shards()
                       if s.state == ShardState.UNASSIGNED), None)
            if un is None:
                from ..utils.errors import IllegalArgumentError
                raise IllegalArgumentError(
                    "no unassigned shard to explain; specify index/"
                    "shard/primary")
            index, shard, primary = un.index, un.shard, un.primary
        return self.allocation.explain_shard(state, str(index),
                                             int(shard or 0), primary)

    def allocation_explain(self, body: dict | None = None) -> dict:
        return self._to_master(ALLOCATION_EXPLAIN_ACTION, body or {})

    def _on_put_mapping(self, src: str, req: dict) -> dict:
        index, mappings = req["index"], dict(req["mappings"])

        def task(cur: ClusterState) -> ClusterState:
            imd = cur.metadata.index(index)
            if imd is None:
                raise IndexNotFoundError(index)
            import dataclasses
            merged = dict(imd.mappings)
            props = dict(merged.get("properties", {}))
            props.update(mappings.get("properties", {}))
            merged["properties"] = props
            imd2 = dataclasses.replace(imd, mappings=merged,
                                       version=imd.version + 1)
            return cur.bump(metadata=cur.metadata.with_index(imd2))
        self.cluster.submit_state_update_task(
            f"put-mapping[{index}]", task, HIGH).result(10)
        return {"acknowledged": True}

    # -- public admin API ----------------------------------------------------

    def create_index(self, name: str, number_of_shards: int | None = None,
                     number_of_replicas: int | None = None,
                     settings: dict | None = None,
                     mappings: dict | None = None) -> dict:
        # None = not specified, so template-provided values can apply
        # (explicit request values outrank templates, ref:
        # MetaDataCreateIndexService request-over-template precedence)
        req: dict = {"index": name, "settings": settings,
                     "mappings": mappings}
        if number_of_shards is not None:
            req["number_of_shards"] = number_of_shards
        if number_of_replicas is not None:
            req["number_of_replicas"] = number_of_replicas
        return self._to_master(CREATE_INDEX_ACTION, req)

    def delete_index(self, name: str) -> dict:
        return self._to_master(DELETE_INDEX_ACTION, {"index": name})

    def update_settings(self, persistent: dict | None = None,
                        transient: dict | None = None,
                        index: str | None = None,
                        index_settings: dict | None = None) -> dict:
        return self._to_master(UPDATE_SETTINGS_ACTION, {
            "persistent": persistent, "transient": transient,
            "index": index, "index_settings": index_settings})

    def put_mapping(self, index: str, mappings: dict) -> dict:
        return self._to_master(PUT_MAPPING_ACTION,
                               {"index": index, "mappings": mappings})

    # -- aliases / templates as master metadata tasks (ref:
    # MetaDataIndexAliasesService + MetaDataIndexTemplateService —
    # cluster-level metadata, published to every node, NOT node-local
    # dictionaries) --------------------------------------------------------

    def _on_update_aliases(self, src: str, req: dict) -> dict:
        actions = req.get("actions") or []

        for entry in actions:
            # validate OUTSIDE the state task: malformed input must be
            # a 400, not an opaque executor failure
            if not isinstance(entry, dict) or len(entry) != 1:
                raise IllegalArgumentError(
                    "[aliases] action must be a single add/remove object")
            op, spec = next(iter(entry.items()))
            if op not in ("add", "remove"):
                raise IllegalArgumentError(
                    f"unknown alias action [{op}]")
            if not isinstance(spec, dict) or not spec.get("index") \
                    or not spec.get("alias"):
                raise IllegalArgumentError(
                    "[aliases] action requires [index] and [alias]")

        def task(cur: ClusterState) -> ClusterState:
            md = cur.metadata
            import dataclasses
            for entry in actions:
                op, spec = next(iter(entry.items()))
                index = spec.get("index")
                alias = spec.get("alias")
                imd = md.index(index)
                if imd is None:
                    raise IndexNotFoundError(index)
                aliases = set(imd.aliases)
                if op == "add":
                    aliases.add(alias)
                else:
                    aliases.discard(alias)
                md = md.with_index(dataclasses.replace(
                    imd, aliases=tuple(sorted(aliases))))
            return cur.bump(metadata=md)
        self.cluster.submit_state_update_task(
            "update-aliases", task, HIGH).result(10)
        return {"acknowledged": True}

    def _on_put_template(self, src: str, req: dict) -> dict:
        name = req["name"]
        body = dict(req.get("body") or {})

        def task(cur: ClusterState) -> ClusterState:
            templates = dict(cur.metadata.templates)
            templates[name] = body
            import dataclasses
            return cur.bump(metadata=dataclasses.replace(
                cur.metadata, templates=templates,
                version=cur.metadata.version + 1))
        self.cluster.submit_state_update_task(
            f"put-template[{name}]", task, HIGH).result(10)
        return {"acknowledged": True}

    def _on_delete_template(self, src: str, req: dict) -> dict:
        name = req["name"]

        def task(cur: ClusterState) -> ClusterState:
            if name not in cur.metadata.templates:
                raise IndexNotFoundError(f"index_template [{name}]")
            templates = dict(cur.metadata.templates)
            templates.pop(name)
            import dataclasses
            return cur.bump(metadata=dataclasses.replace(
                cur.metadata, templates=templates,
                version=cur.metadata.version + 1))
        self.cluster.submit_state_update_task(
            f"delete-template[{name}]", task, HIGH).result(10)
        return {"acknowledged": True}

    def update_aliases(self, actions: list[dict]) -> dict:
        return self._to_master(UPDATE_ALIASES_ACTION, {"actions": actions})

    def put_template(self, name: str, body: dict) -> dict:
        return self._to_master(PUT_TEMPLATE_ACTION,
                               {"name": name, "body": body})

    def delete_template(self, name: str) -> dict:
        return self._to_master(DELETE_TEMPLATE_ACTION, {"name": name})

    def health(self) -> dict:
        return health_of(self.state)


def _resize_replicas(state: ClusterState, index: str, n_replicas: int
                     ) -> ClusterState:
    """Adjust each shard group to n_replicas replica copies."""
    from .state import ShardRouting
    import dataclasses
    tbl = state.routing_table.index(index)
    if tbl is None:
        return state
    groups = []
    for g in tbl.shards:
        replicas = [c for c in g.copies if not c.primary]
        primary = [c for c in g.copies if c.primary]
        if len(replicas) < n_replicas:
            replicas += [ShardRouting(index, g.shard, primary=False)
                         for _ in range(n_replicas - len(replicas))]
        elif len(replicas) > n_replicas:
            # drop unassigned first, then extra assigned copies
            replicas.sort(key=lambda c: c.assigned)
            replicas = replicas[len(replicas) - n_replicas:] \
                if n_replicas else []
        groups.append(dataclasses.replace(
            g, copies=tuple(primary + replicas)))
    return state.with_routing(state.routing_table.with_index(
        dataclasses.replace(tbl, shards=tuple(groups))))


class LocalCluster:
    """Boot N ClusterNodes on one LocalHub and form a cluster.

    Ref: test/InternalTestCluster.java (es.node.mode=local). Sequential
    deterministic formation: nodes join in id order, master = lowest id.
    """

    def __init__(self, n_nodes: int = 3, *, min_master_nodes: int | None = None,
                 attributes: list[dict] | None = None,
                 cluster_name: str = "test-cluster"):
        self.hub = LocalHub()
        if min_master_nodes is None:
            min_master_nodes = n_nodes // 2 + 1
        self.nodes: dict[str, ClusterNode] = {}
        for i in range(n_nodes):
            nid = f"node-{i}"
            attrs = attributes[i] if attributes else {}
            self.nodes[nid] = ClusterNode(
                nid, self.hub, attributes=attrs,
                min_master_nodes=min_master_nodes,
                cluster_name=cluster_name)
        for nid in sorted(self.nodes):
            self.nodes[nid].join()

    @property
    def master(self) -> ClusterNode | None:
        for n in self.nodes.values():
            if n.is_master:
                return n
        return None

    def any_node(self) -> ClusterNode:
        return next(iter(self.nodes.values()))

    def tick_all(self, rounds: int = 1) -> None:
        """Run failure-detection heartbeat rounds on every node."""
        for _ in range(rounds):
            for n in list(self.nodes.values()):
                n.discovery.fd_tick()

    def stop_node(self, node_id: str) -> None:
        node = self.nodes.pop(node_id)
        node.close()

    def close(self) -> None:
        for n in self.nodes.values():
            n.close()
        self.nodes.clear()
