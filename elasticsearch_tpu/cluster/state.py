"""Immutable cluster state model.

Reference analog: cluster/ClusterState.java:117-129 — a single immutable
value (version + RoutingTable + DiscoveryNodes + MetaData + ClusterBlocks)
that the elected master mutates through serialized update tasks and
publishes to every node. Here the state is a tree of frozen dataclasses
with functional `with_*` update helpers; equality/diffing is structural.

The TPU-first rationale is the same as the reference's: one immutable
value makes the control plane a pure function `state -> state'` that can
be reasoned about, diffed, and published atomically — the control-plane
analog of JAX's functional transforms on pytrees.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from enum import Enum
from typing import Iterator, Mapping


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DiscoveryNode:
    """Ref: cluster/node/DiscoveryNode.java."""

    node_id: str
    name: str = ""
    address: str = "local"
    master_eligible: bool = True
    data: bool = True
    attributes: Mapping[str, str] = field(default_factory=dict)

    def __post_init__(self):
        if not self.name:
            object.__setattr__(self, "name", self.node_id)
        object.__setattr__(self, "attributes", dict(self.attributes))

    def __hash__(self):
        return hash(self.node_id)


@dataclass(frozen=True)
class DiscoveryNodes:
    """Ref: cluster/node/DiscoveryNodes.java — membership + elected master
    + the id of the local node this copy of the state lives on."""

    nodes: Mapping[str, DiscoveryNode] = field(default_factory=dict)
    master_node_id: str | None = None
    local_node_id: str | None = None

    def __post_init__(self):
        object.__setattr__(self, "nodes", dict(self.nodes))

    def get(self, node_id: str) -> DiscoveryNode | None:
        return self.nodes.get(node_id)

    @property
    def master_node(self) -> DiscoveryNode | None:
        return self.nodes.get(self.master_node_id) if self.master_node_id else None

    @property
    def local_node(self) -> DiscoveryNode | None:
        return self.nodes.get(self.local_node_id) if self.local_node_id else None

    @property
    def data_nodes(self) -> dict[str, DiscoveryNode]:
        return {i: n for i, n in self.nodes.items() if n.data}

    @property
    def master_eligible_nodes(self) -> dict[str, DiscoveryNode]:
        return {i: n for i, n in self.nodes.items() if n.master_eligible}

    def with_node(self, node: DiscoveryNode) -> "DiscoveryNodes":
        nodes = dict(self.nodes)
        nodes[node.node_id] = node
        return replace(self, nodes=nodes)

    def without_node(self, node_id: str) -> "DiscoveryNodes":
        nodes = dict(self.nodes)
        nodes.pop(node_id, None)
        master = self.master_node_id if self.master_node_id != node_id else None
        return replace(self, nodes=nodes, master_node_id=master)

    def with_master(self, node_id: str | None) -> "DiscoveryNodes":
        return replace(self, master_node_id=node_id)

    def with_local(self, node_id: str) -> "DiscoveryNodes":
        return replace(self, local_node_id=node_id)

    def __iter__(self) -> Iterator[DiscoveryNode]:
        return iter(self.nodes.values())

    def __len__(self) -> int:
        return len(self.nodes)


# ---------------------------------------------------------------------------
# Shard routing
# ---------------------------------------------------------------------------


class ShardState(str, Enum):
    """Ref: cluster/routing/ShardRoutingState.java."""

    UNASSIGNED = "UNASSIGNED"
    INITIALIZING = "INITIALIZING"
    STARTED = "STARTED"
    RELOCATING = "RELOCATING"


@dataclass(frozen=True)
class ShardRouting:
    """One shard copy. Ref: cluster/routing/ShardRouting.java."""

    index: str
    shard: int
    primary: bool
    state: ShardState = ShardState.UNASSIGNED
    node_id: str | None = None
    relocating_node_id: str | None = None
    # fresh id per assignment (ref: cluster/routing/AllocationId.java) —
    # lets a node distinguish "my running copy" from "a NEW allocation
    # of the same shard back to me" after a failure round-trip
    allocation_id: str | None = None
    # has this copy EVER been assigned? (ref: UnassignedInfo.Reason
    # INDEX_CREATED vs NODE_LEFT/ALLOCATION_FAILED — drives the
    # new_primaries/new-allocation deciders; fail() keeps it True)
    was_assigned: bool = False

    @property
    def assigned(self) -> bool:
        return self.node_id is not None

    @property
    def active(self) -> bool:
        return self.state in (ShardState.STARTED, ShardState.RELOCATING)

    def initialize(self, node_id: str) -> "ShardRouting":
        assert self.state == ShardState.UNASSIGNED, self
        import uuid
        return replace(self, state=ShardState.INITIALIZING,
                       node_id=node_id, was_assigned=True,
                       allocation_id=uuid.uuid4().hex[:12])

    def start(self) -> "ShardRouting":
        assert self.state in (ShardState.INITIALIZING, ShardState.RELOCATING), self
        return replace(self, state=ShardState.STARTED, relocating_node_id=None)

    def relocate(self, target_node_id: str) -> "ShardRouting":
        assert self.state == ShardState.STARTED, self
        return replace(self, state=ShardState.RELOCATING,
                       relocating_node_id=target_node_id)

    def fail(self) -> "ShardRouting":
        return replace(self, state=ShardState.UNASSIGNED, node_id=None,
                       relocating_node_id=None, allocation_id=None)

    def demote(self) -> "ShardRouting":
        return replace(self, primary=False)

    def promote(self) -> "ShardRouting":
        return replace(self, primary=True)

    @property
    def shard_key(self) -> tuple[str, int]:
        return (self.index, self.shard)


@dataclass(frozen=True)
class IndexShardRoutingTable:
    """All copies of one shard group. Ref: IndexShardRoutingTable.java."""

    index: str
    shard: int
    copies: tuple[ShardRouting, ...] = ()

    @property
    def primary(self) -> ShardRouting | None:
        for c in self.copies:
            if c.primary:
                return c
        return None

    @property
    def replicas(self) -> tuple[ShardRouting, ...]:
        return tuple(c for c in self.copies if not c.primary)

    @property
    def active_copies(self) -> tuple[ShardRouting, ...]:
        return tuple(c for c in self.copies if c.active)


@dataclass(frozen=True)
class IndexRoutingTable:
    """Ref: cluster/routing/IndexRoutingTable.java."""

    index: str
    shards: tuple[IndexShardRoutingTable, ...] = ()

    def shard(self, sid: int) -> IndexShardRoutingTable:
        return self.shards[sid]

    @staticmethod
    def new(index: str, num_shards: int, num_replicas: int) -> "IndexRoutingTable":
        groups = []
        for sid in range(num_shards):
            copies = [ShardRouting(index, sid, primary=True)]
            copies += [ShardRouting(index, sid, primary=False)
                       for _ in range(num_replicas)]
            groups.append(IndexShardRoutingTable(index, sid, tuple(copies)))
        return IndexRoutingTable(index, tuple(groups))


@dataclass(frozen=True)
class RoutingTable:
    """Ref: cluster/routing/RoutingTable.java."""

    indices: Mapping[str, IndexRoutingTable] = field(default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "indices", dict(self.indices))

    def index(self, name: str) -> IndexRoutingTable | None:
        return self.indices.get(name)

    def with_index(self, table: IndexRoutingTable) -> "RoutingTable":
        indices = dict(self.indices)
        indices[table.index] = table
        return replace(self, indices=indices)

    def without_index(self, name: str) -> "RoutingTable":
        indices = dict(self.indices)
        indices.pop(name, None)
        return replace(self, indices=indices)

    def all_shards(self) -> Iterator[ShardRouting]:
        for tbl in self.indices.values():
            for group in tbl.shards:
                yield from group.copies

    def shards_on_node(self, node_id: str) -> list[ShardRouting]:
        return [s for s in self.all_shards() if s.node_id == node_id
                or s.relocating_node_id == node_id]

    def _with_group_copies(self, index: str, shard: int,
                           copies: list[ShardRouting]) -> "RoutingTable":
        """Rebuild one shard group with `copies` (sorted primary-first —
        the single place the copy-ordering invariant lives)."""
        tbl = self.indices[index]
        group = tbl.shards[shard]
        copies = sorted(copies,
                        key=lambda c: (not c.primary, c.node_id or ""))
        new_group = replace(group, copies=tuple(copies))
        new_shards = tuple(new_group if g.shard == group.shard else g
                           for g in tbl.shards)
        return self.with_index(replace(tbl, shards=new_shards))

    def update_shard(self, old: ShardRouting, new: ShardRouting | None
                     ) -> "RoutingTable":
        """Replace one shard copy (or drop it when new is None)."""
        copies = list(self.indices[old.index].shards[old.shard].copies)
        try:
            copies.remove(old)  # exactly one — groups may hold several
        except ValueError:      # equal (e.g. UNASSIGNED) copies
            raise KeyError(f"shard copy not in table: {old}") from None
        if new is not None:
            copies.append(new)
        return self._with_group_copies(old.index, old.shard, copies)

    def add_shard_copy(self, copy: ShardRouting) -> "RoutingTable":
        """Add an extra copy to a shard group — the relocation TARGET
        entry (ref: RoutingNodes.relocate creating the shadow
        initializing shard on the target node)."""
        copies = list(self.indices[copy.index].shards[copy.shard].copies)
        copies.append(copy)
        return self._with_group_copies(copy.index, copy.shard, copies)


# ---------------------------------------------------------------------------
# Metadata
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class IndexMetadata:
    """Ref: cluster/metadata/IndexMetaData.java."""

    index: str
    number_of_shards: int = 1
    number_of_replicas: int = 0
    settings: Mapping[str, object] = field(default_factory=dict)
    mappings: Mapping[str, object] = field(default_factory=dict)
    aliases: tuple[str, ...] = ()
    version: int = 1
    state: str = "open"  # open | close

    def __post_init__(self):
        object.__setattr__(self, "settings", dict(self.settings))
        object.__setattr__(self, "mappings", dict(self.mappings))


@dataclass(frozen=True)
class Metadata:
    """Ref: cluster/metadata/MetaData.java."""

    indices: Mapping[str, IndexMetadata] = field(default_factory=dict)
    templates: Mapping[str, dict] = field(default_factory=dict)
    persistent_settings: Mapping[str, object] = field(default_factory=dict)
    transient_settings: Mapping[str, object] = field(default_factory=dict)
    version: int = 0

    def __post_init__(self):
        for k in ("indices", "templates", "persistent_settings",
                  "transient_settings"):
            object.__setattr__(self, k, dict(getattr(self, k)))

    def index(self, name: str) -> IndexMetadata | None:
        return self.indices.get(name)

    def with_index(self, imd: IndexMetadata) -> "Metadata":
        indices = dict(self.indices)
        indices[imd.index] = imd
        return replace(self, indices=indices, version=self.version + 1)

    def without_index(self, name: str) -> "Metadata":
        indices = dict(self.indices)
        indices.pop(name, None)
        return replace(self, indices=indices, version=self.version + 1)


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ClusterBlock:
    """Ref: cluster/block/ClusterBlock.java."""

    block_id: int
    description: str
    retryable: bool = True
    levels: tuple[str, ...] = ("read", "write", "metadata_read", "metadata_write")


STATE_NOT_RECOVERED_BLOCK = ClusterBlock(
    1, "state not recovered / initialized", retryable=True)
NO_MASTER_BLOCK = ClusterBlock(2, "no master", retryable=True)
INDEX_READ_ONLY_BLOCK = ClusterBlock(
    5, "index read-only (api)", retryable=False, levels=("write", "metadata_write"))


@dataclass(frozen=True)
class ClusterBlocks:
    """Ref: cluster/block/ClusterBlocks.java."""

    global_blocks: tuple[ClusterBlock, ...] = ()
    index_blocks: Mapping[str, tuple[ClusterBlock, ...]] = field(default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "index_blocks", dict(self.index_blocks))

    def has_global_block(self, block: ClusterBlock) -> bool:
        return block in self.global_blocks

    def blocked(self, level: str, index: str | None = None) -> ClusterBlock | None:
        for b in self.global_blocks:
            if level in b.levels:
                return b
        if index is not None:
            for b in self.index_blocks.get(index, ()):
                if level in b.levels:
                    return b
        return None

    def with_global(self, block: ClusterBlock) -> "ClusterBlocks":
        if block in self.global_blocks:
            return self
        return replace(self, global_blocks=self.global_blocks + (block,))

    def without_global(self, block: ClusterBlock) -> "ClusterBlocks":
        return replace(self, global_blocks=tuple(
            b for b in self.global_blocks if b != block))


# ---------------------------------------------------------------------------
# ClusterState
# ---------------------------------------------------------------------------

_state_uid = itertools.count(1)


@dataclass(frozen=True)
class ClusterState:
    """Ref: cluster/ClusterState.java:117-129."""

    cluster_name: str = "elasticsearch-tpu"
    version: int = 0
    nodes: DiscoveryNodes = field(default_factory=DiscoveryNodes)
    routing_table: RoutingTable = field(default_factory=RoutingTable)
    metadata: Metadata = field(default_factory=Metadata)
    blocks: ClusterBlocks = field(default_factory=ClusterBlocks)
    # who produced this version (for publish-ordering sanity checks)
    master_term: int = 0

    def bump(self, **changes) -> "ClusterState":
        return replace(self, version=self.version + 1, **changes)

    def with_nodes(self, nodes: DiscoveryNodes) -> "ClusterState":
        return self.bump(nodes=nodes)

    def with_routing(self, rt: RoutingTable) -> "ClusterState":
        return self.bump(routing_table=rt)

    def with_metadata(self, md: Metadata) -> "ClusterState":
        return self.bump(metadata=md)

    def with_blocks(self, blocks: ClusterBlocks) -> "ClusterState":
        return self.bump(blocks=blocks)

    def summary(self) -> dict:
        """JSON-ish view for the _cluster/state API."""
        return {
            "cluster_name": self.cluster_name,
            "version": self.version,
            "master_node": self.nodes.master_node_id,
            "nodes": {nid: {"name": n.name, "attributes": dict(n.attributes),
                            "master_eligible": n.master_eligible, "data": n.data}
                      for nid, n in self.nodes.nodes.items()},
            "blocks": [b.description for b in self.blocks.global_blocks],
            "metadata": {"indices": {
                name: {"state": imd.state,
                       "settings": {
                           "index.number_of_shards": imd.number_of_shards,
                           "index.number_of_replicas": imd.number_of_replicas},
                       "mappings": dict(imd.mappings)}
                for name, imd in self.metadata.indices.items()}},
            "routing_table": {"indices": {
                name: {"shards": {
                    str(g.shard): [
                        {"state": c.state.value, "primary": c.primary,
                         "node": c.node_id, "shard": c.shard, "index": c.index,
                         "relocating_node": c.relocating_node_id}
                        for c in g.copies]
                    for g in tbl.shards}}
                for name, tbl in self.routing_table.indices.items()}},
        }


def health_of(state: ClusterState) -> dict:
    """Cluster health from routing table. Ref: ClusterHealthResponse /
    ClusterStateHealth — green: all copies active; yellow: all primaries
    active; red: some primary not active."""
    active_primary = total_primary = 0
    active = initializing = unassigned = relocating = total = 0
    for s in state.routing_table.all_shards():
        total += 1
        if s.primary:
            total_primary += 1
            if s.active:
                active_primary += 1
        if s.active:
            active += 1
        if s.state == ShardState.INITIALIZING:
            initializing += 1
        if s.state == ShardState.UNASSIGNED:
            unassigned += 1
        if s.state == ShardState.RELOCATING:
            relocating += 1
    if active_primary < total_primary:
        status = "red"
    elif active < total:
        status = "yellow"
    else:
        status = "green"
    if state.blocks.has_global_block(STATE_NOT_RECOVERED_BLOCK) or \
            state.blocks.has_global_block(NO_MASTER_BLOCK):
        status = "red"
    return {
        "cluster_name": state.cluster_name,
        "status": status,
        "number_of_nodes": len(state.nodes),
        "number_of_data_nodes": len(state.nodes.data_nodes),
        "active_primary_shards": active_primary,
        "active_shards": active,
        "initializing_shards": initializing,
        "relocating_shards": relocating,
        "unassigned_shards": unassigned,
        "timed_out": False,
    }
