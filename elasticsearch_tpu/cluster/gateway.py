"""Gateway: persisted cluster metadata, restored on full-cluster restart.

Reference analog: gateway/ — MetaDataStateFormat.java:48-52 (checksummed,
atomically-renamed state files, generation-numbered), GatewayMetaState
write-on-change (:115,:147), and GatewayService recovery gating
(STATE_NOT_RECOVERED_BLOCK until recover_after_nodes, :50,:94-95).

Files: <path>/_state/global-<gen>.json — JSON with an embedded sha256;
newer generation wins; corrupt files are skipped (fall back to the
previous generation), like the reference's best-effort state recovery.
"""

from __future__ import annotations

import hashlib
import json
import os

from .state import ClusterState, IndexMetadata


class GatewayMetaState:
    def __init__(self, path: str):
        self.dir = os.path.join(path, "_state")
        os.makedirs(self.dir, exist_ok=True)

    def _generations(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("global-") and name.endswith(".json"):
                try:
                    out.append(int(name[len("global-"):-len(".json")]))
                except ValueError:
                    pass
        return sorted(out)

    def persist(self, state: ClusterState) -> None:
        """Write-on-change of the index metadata (ref:
        GatewayMetaState.clusterChanged:115)."""
        doc = {"indices": {
            name: {"number_of_shards": imd.number_of_shards,
                   "number_of_replicas": imd.number_of_replicas,
                   "settings": dict(imd.settings),
                   "mappings": dict(imd.mappings),
                   "aliases": sorted(imd.aliases),
                   "version": imd.version}
            for name, imd in state.metadata.indices.items()},
            "templates": dict(state.metadata.templates),
            "persistent_settings": dict(state.metadata.persistent_settings)}
        payload = json.dumps(doc, sort_keys=True)
        gens = self._generations()
        if gens:  # skip rewrite when nothing changed
            try:
                cur = self._read_gen(gens[-1])
                if cur is not None and json.dumps(cur, sort_keys=True) == payload:
                    return
            except Exception:
                pass
        gen = (gens[-1] if gens else 0) + 1
        wrapped = json.dumps({"sha256": hashlib.sha256(
            payload.encode()).hexdigest(), "meta": doc})
        path = os.path.join(self.dir, f"global-{gen}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(wrapped)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        for old in gens[:-1]:  # keep previous gen as fallback
            try:
                os.remove(os.path.join(self.dir, f"global-{old}.json"))
            except OSError:
                pass

    def _read_gen(self, gen: int) -> dict | None:
        with open(os.path.join(self.dir, f"global-{gen}.json")) as f:
            wrapped = json.load(f)
        payload = json.dumps(wrapped["meta"], sort_keys=True)
        if hashlib.sha256(payload.encode()).hexdigest() != wrapped["sha256"]:
            return None
        return wrapped["meta"]

    def load(self) -> dict | None:
        """Newest intact generation, or None."""
        for gen in reversed(self._generations()):
            try:
                meta = self._read_gen(gen)
            except Exception:
                meta = None
            if meta is not None:
                return meta
        return None

    @staticmethod
    def to_index_metadata(meta: dict) -> list[IndexMetadata]:
        out = []
        for name, e in (meta.get("indices") or {}).items():
            out.append(IndexMetadata(
                name, number_of_shards=int(e.get("number_of_shards", 1)),
                number_of_replicas=int(e.get("number_of_replicas", 0)),
                settings=e.get("settings") or {},
                mappings=e.get("mappings") or {},
                aliases=tuple(e.get("aliases") or ()),
                version=int(e.get("version", 1))))
        return out
