"""Tribe node: one federated view over several independent clusters.

Reference analog: tribe/TribeService.java:74 — a tribe node runs an
inner client node per configured cluster, merges their cluster states
into one view (indices resolve to their owning tribe; conflicts follow
`tribe.on_conflict`: any | prefer_<tribe>), serves reads and document
writes against the merged view, and BLOCKS metadata writes (the tribe
is not a master of anything).

Here the inner clients are DataNode handles that already joined their
clusters; cross-cluster search reuses the QUERY-phase scatter of each
cluster and reduces everything in ONE merge_shard_results pass — shard
agg partials are keyed by term/numeric value, so buckets from different
clusters meet exactly (the same property the mesh's cross-generation
merge relies on)."""

from __future__ import annotations

import fnmatch

from ..search.aggregations import parse_aggs
from ..search.suggest import parse_suggest
from ..utils.errors import (IllegalArgumentError, IndexNotFoundError)


class TribeNode:
    """Federates {tribe_name: DataNode client} handles."""

    BLOCKED = ("create_index", "delete_index", "put_mapping",
               "update_settings", "reroute")

    def __init__(self, tribes: dict, on_conflict: str = "any"):
        if not tribes:
            raise IllegalArgumentError("tribe node requires tribes")
        self.tribes = dict(tribes)
        allowed = {"any"} | {f"prefer_{t}" for t in self.tribes}
        if on_conflict not in allowed:
            raise IllegalArgumentError(
                f"invalid tribe.on_conflict [{on_conflict}] "
                f"(expected one of {sorted(allowed)})")
        self.on_conflict = on_conflict

    # -- merged view -------------------------------------------------------

    def merged_indices(self) -> dict[str, str]:
        """index name -> owning tribe. Conflicts (same index in two
        clusters) resolve by `on_conflict`: "any" keeps the FIRST tribe
        (iteration order) like the reference's default; "prefer_<t>"
        pins the named tribe's copy. Cached per (tribe state versions)
        so per-document routing is O(1), rebuilt only when some
        cluster's state moved."""
        versions = tuple((t, c.state.version)
                         for t, c in self.tribes.items())
        cached = getattr(self, "_view_cache", None)
        if cached is not None and cached[0] == versions:
            return cached[1]
        prefer = (self.on_conflict[len("prefer_"):]
                  if self.on_conflict.startswith("prefer_") else None)
        out: dict[str, str] = {}
        for tname, client in self.tribes.items():
            for index in client.state.metadata.indices:
                if index not in out:
                    out[index] = tname
                elif prefer is not None and tname == prefer:
                    out[index] = tname
        self._view_cache = (versions, out)
        return out

    def _owner(self, index: str):
        view = self.merged_indices()
        tname = view.get(index)
        if tname is None:
            raise IndexNotFoundError(index)
        return self.tribes[tname]

    def health(self) -> dict:
        """Worst-of across tribes (the merged state's health)."""
        rank = {"green": 0, "yellow": 1, "red": 2}
        worst = "green"
        total = 0
        for client in self.tribes.values():
            h = client.health()
            total += int(h.get("active_shards", 0))
            if rank.get(h.get("status"), 2) > rank[worst]:
                worst = h["status"]
        return {"status": worst, "active_shards": total,
                "number_of_tribes": len(self.tribes)}

    # -- document ops (route to the owning tribe) --------------------------

    def index_doc(self, index: str, doc_id, body, **kw) -> dict:
        return self._owner(index).index_doc(index, doc_id, body, **kw)

    def get_doc(self, index: str, doc_id: str, **kw) -> dict:
        return self._owner(index).get_doc(index, doc_id, **kw)

    def delete_doc(self, index: str, doc_id: str, **kw) -> dict:
        return self._owner(index).delete_doc(index, doc_id, **kw)

    def refresh_index(self, index: str | None = None) -> dict:
        if index is not None:
            return self._owner(index).refresh_index(index)
        for client in self.tribes.values():
            client.refresh_index()
        return {"acknowledged": True}

    # -- metadata writes are BLOCKED (ref: TribeService write blocks) ------

    def __getattr__(self, name: str):
        if name in self.BLOCKED:
            def blocked(*_a, **_k):
                raise IllegalArgumentError(
                    f"blocked by: [{name}] — tribe node cannot make "
                    "cluster metadata changes (ref: TribeService "
                    "TRIBE_METADATA_BLOCK)")
            return blocked
        raise AttributeError(name)

    # -- federated search --------------------------------------------------

    def search(self, index: str | None, body: dict | None = None) -> dict:
        """ONE reduce over every tribe's shard responses: scatter in
        each owning cluster, merge hits/aggs/suggest globally — scores
        and agg buckets from different clusters meet in the same
        SearchPhaseController pass a single cluster uses."""
        body = body or {}
        view = self.merged_indices()
        # resolution matches DataNode._resolve_index_names: only `*`
        # wildcards; a CONCRETE name absent from the merged view is an
        # error, not a silent skip
        patterns = (["*"] if index in (None, "", "_all", "*")
                    else [p.strip() for p in str(index).split(",")])
        per_tribe: dict[str, list[str]] = {}
        for p in patterns:
            if "*" in p:
                hits = [n for n in view if fnmatch.fnmatch(n, p)]
            else:
                if p not in view:
                    raise IndexNotFoundError(p)
                hits = [p]
            for name in hits:
                names = per_tribe.setdefault(view[name], [])
                if name not in names:
                    names.append(name)
        agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations"))
        suggest_specs = parse_suggest(body.get("suggest"))
        frm = int(body.get("from", 0))
        size = int(body.get("size", 10))
        shard_body = dict(body)
        shard_body["from"] = 0
        shard_body["size"] = frm + size
        responses, partials, suggest_parts = [], [], []
        n_shards = 0
        # scatter all clusters CONCURRENTLY: tribe latency is the max
        # of the per-cluster latencies, not their sum
        from concurrent.futures import ThreadPoolExecutor
        items = sorted(per_tribe.items())
        if items:
            with ThreadPoolExecutor(max_workers=len(items)) as pool:
                futures = [pool.submit(
                    self.tribes[tname]._scatter_search,
                    sorted(names), shard_body)
                    for tname, names in items]
                for f in futures:
                    r, p, s, n = f.result(timeout=60)
                    responses.extend(r)
                    partials.extend(p)
                    suggest_parts.extend(s)
                    n_shards += n
        from .distributed_node import _reduce_search
        return _reduce_search(responses, partials, suggest_parts,
                              n_shards, body, agg_specs, suggest_specs,
                              frm, size)
