"""TCP transport: the LocalHub/Transport API over real sockets.

Reference analog: transport/netty/NettyTransport.java — action-name-
routed request/response over TCP with a compressed binary wire format
(here: cluster/wire.py frames). One listening socket per node; requests
open short-lived connections (localhost focus — the reference keeps
typed channel pools per peer, which matters across real networks and
can layer on later without changing callers).

API parity with cluster/transport.py: `register_handler`,
`send_request`, `submit_request`, `close`, and a `hub` exposing
`node_ids()` — so ClusterNode/DataNode/Discovery run unchanged over
either backend, and a cluster can span real processes
(tests/proc_node_runner.py boots one node per process).

Error semantics: handler exceptions serialize as {type, reason,
status} and are reconstructed as the SAME ElasticsearchTpuError
subclass on the caller (isinstance checks like the fan-out's
ShardNotFoundError skip keep working across the wire); connection
failures surface as NodeNotConnectedError exactly like a dropped
LocalHub link.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import struct
import threading
from concurrent.futures import Future, ThreadPoolExecutor

from .transport import (NodeNotConnectedError, RequestTimeoutError,
                        TransportError)
from .wire import decode_frame, encode_frame
from ..utils import errors as error_registry
from ..utils.errors import ElasticsearchTpuError

logger = logging.getLogger("elasticsearch_tpu.tcp_transport")

_LEN = struct.Struct(">I")


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, msg: dict) -> None:
    body = encode_frame(msg)
    sock.sendall(_LEN.pack(len(body)) + body)


def _recv_frame(sock: socket.socket) -> dict:
    (n,) = _LEN.unpack(_read_exact(sock, _LEN.size))
    return decode_frame(_read_exact(sock, n))


def _rebuild_error(spec: dict) -> Exception:
    """{type, reason, status} -> the matching error instance.

    Bypasses subclass __init__ (signatures vary) but restores the FULL
    base contract — message/info/status — so isinstance checks AND
    to_dict() rendering behave exactly like a locally raised error."""
    reason = spec.get("reason", "remote error")
    cls = getattr(error_registry, spec.get("type", ""), None)
    if isinstance(cls, type) and issubclass(cls, ElasticsearchTpuError):
        err = cls.__new__(cls)
        ElasticsearchTpuError.__init__(err, reason)
        err.status = spec.get("status", getattr(cls, "status", 500))
        return err
    err2 = TransportError(reason)
    err2.status = spec.get("status", 500)
    return err2


class TcpHub:
    """Static seed map node_id -> (host, port), shared by every process
    of one cluster (the unicast-hosts list of
    discovery/zen/ping/unicast/UnicastZenPing.java)."""

    def __init__(self, seeds: dict[str, tuple[str, int]]):
        self.seeds = {nid: (str(h), int(p))
                      for nid, (h, p) in seeds.items()}

    def node_ids(self) -> list[str]:
        return list(self.seeds)

    def address(self, node_id: str) -> tuple[str, int] | None:
        return self.seeds.get(node_id)

    def add_seed(self, node_id: str, addr: tuple[str, int]) -> None:
        """Learn (or update) a member's address at runtime — how a
        survivor reaches a REPLACEMENT process that bound a fresh port
        without every process restarting on a new static seed list."""
        self.seeds[str(node_id)] = (str(addr[0]), int(addr[1]))

    def create_transport(self, node_id: str,
                         n_threads: int = 4) -> "TcpTransport":
        return TcpTransport(node_id, self, n_threads=n_threads)


class TcpTransport:
    def __init__(self, node_id: str, hub: TcpHub, n_threads: int = 4):
        self.node_id = node_id
        self.hub = hub
        self._handlers: dict[str, object] = {}
        self._pool = ThreadPoolExecutor(
            max_workers=n_threads, thread_name_prefix=f"tcp-{node_id}")
        self._closed = False
        addr = hub.address(node_id)
        if addr is None:
            raise ValueError(f"no seed address for [{node_id}]")
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    req = _recv_frame(self.request)
                except (ConnectionError, ValueError):
                    return
                action = req.get("action")
                handler = outer._handlers.get(action)
                if handler is None:
                    _send_frame(self.request, {
                        "ok": False, "error": {
                            "type": "TransportError",
                            "reason": f"no handler for [{action}] on "
                                      f"[{outer.node_id}]",
                            "status": 500}})
                    return
                try:
                    resp = handler(req.get("src", "?"), req["payload"])
                    _send_frame(self.request,
                                {"ok": True, "payload": resp})
                except Exception as e:  # noqa: BLE001 — carried to caller
                    _send_frame(self.request, {
                        "ok": False, "error": {
                            "type": type(e).__name__,
                            "reason": str(e),
                            "status": getattr(e, "status", 500)}})

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server(addr, Handler)
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"tcp-server-{node_id}")
        self._server_thread.start()

    # -- API (mirrors cluster/transport.py Transport) ----------------------

    def register_handler(self, action: str, handler) -> None:
        self._handlers[action] = handler

    @property
    def advertise_addr(self) -> tuple[str, int]:
        """The (host, port) peers should dial — the ACTUAL bound
        address (port 0 in the seed resolves to the kernel-assigned
        port), carried in the pod-join admit so survivors learn a
        replacement's fresh endpoint."""
        host, _seed_port = self.hub.address(self.node_id)
        return (host, self._server.server_address[1])

    def add_peer(self, node_id: str, addr: tuple[str, int]) -> None:
        """Route future requests for `node_id` to `addr` — invoked by
        the membership layer when a join/commit carries a replacement
        member's advertised address."""
        self.hub.add_seed(node_id, addr)

    def submit_request(self, target: str, action: str, request: dict,
                       timeout: float = 10.0) -> Future:
        """`timeout` bounds the SOCKET work too: a hung (not dead) peer
        must release the worker thread when the caller gives up, or a
        4-thread pool wedges behind one stuck node."""
        fut: Future = Future()
        if self._closed:
            fut.set_exception(NodeNotConnectedError(
                f"[{self.node_id}] transport closed"))
            return fut
        addr = self.hub.address(target)
        if addr is None:
            fut.set_exception(NodeNotConnectedError(
                f"[{self.node_id}] unknown node [{target}]"))
            return fut

        def run():
            try:
                with socket.create_connection(
                        addr, timeout=min(timeout, 10.0)) as s:
                    s.settimeout(timeout + 2.0)
                    _send_frame(s, {"action": action,
                                    "src": self.node_id,
                                    "payload": request})
                    resp = _recv_frame(s)
            except (OSError, ConnectionError, ValueError) as e:
                fut.set_exception(NodeNotConnectedError(
                    f"[{self.node_id}] cannot reach [{target}] for "
                    f"[{action}]: {e}"))
                return
            if resp.get("ok"):
                fut.set_result(resp.get("payload"))
            else:
                fut.set_exception(_rebuild_error(resp.get("error", {})))

        try:
            self._pool.submit(run)
        except RuntimeError:
            fut.set_exception(NodeNotConnectedError(
                f"[{self.node_id}] transport closed"))
        return fut

    def send_request(self, target: str, action: str, request: dict,
                     timeout: float = 10.0) -> dict:
        fut = self.submit_request(target, action, request,
                                  timeout=timeout)
        try:
            return fut.result(timeout)
        except TimeoutError:
            raise RequestTimeoutError(
                f"[{self.node_id}] request [{action}] to [{target}] "
                f"timed out after {timeout}s") from None

    def set_tracer(self, include: tuple = (), exclude: tuple = ()) -> None:
        pass  # tracing hooks live on the in-process transport for now

    def close(self) -> None:
        self._closed = True
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass
        self._pool.shutdown(wait=False, cancel_futures=True)
