"""Shard allocation: deciders + weighted balancer + reroute.

Reference analog: cluster/routing/allocation/ —
AllocationService.reroute/applyStartedShards/applyFailedShards
(AllocationService.java:73-127), the weighted BalancedShardsAllocator
(allocator/BalancedShardsAllocator.java:67-79, index weight 0.55 / shard
weight 0.45 / threshold 1.0) and the pluggable AllocationDeciders
(decider/, 18 of them). We implement the deciders that matter for a
TPU deployment: SameShard (never two copies of a shard group on one
host), ReplicaAfterPrimaryActive, Throttling (bounded concurrent
recoveries — device-memory uploads are expensive), Filter
(include/exclude by node attribute), Awareness (spread copies across a
zone attribute), ShardsLimit, and a DiskThreshold analog driven by an
HBM budget per node (the reference watches disk watermarks; the scarce
resource here is accelerator memory).

Everything is a pure function on ClusterState — reroute(state) returns a
new state; no hidden registries.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable

from .state import (ClusterState, DiscoveryNode, RoutingTable, ShardRouting,
                    ShardState)

YES, NO, THROTTLE = "YES", "NO", "THROTTLE"


@dataclass
class AllocationContext:
    """View of the state the deciders consult."""

    state: ClusterState
    # node_id -> shard copies currently on it (assigned or relocating in)
    node_shards: dict[str, list[ShardRouting]] = field(default_factory=dict)

    @classmethod
    def of(cls, state: ClusterState) -> "AllocationContext":
        ctx = cls(state)
        for nid in state.nodes.data_nodes:
            ctx.node_shards[nid] = []
        for s in state.routing_table.all_shards():
            if s.node_id in ctx.node_shards:
                ctx.node_shards[s.node_id].append(s)
        return ctx


class Decider:
    name = "decider"

    def can_allocate(self, shard: ShardRouting, node: DiscoveryNode,
                     ctx: AllocationContext) -> str:
        return YES

    def can_rebalance(self, shard: ShardRouting,
                      ctx: AllocationContext) -> str:
        """May this STARTED copy start relocating for balance? (ref:
        AllocationDecider.canRebalance)."""
        return YES

    def can_remain(self, shard: ShardRouting, node: DiscoveryNode,
                   ctx: AllocationContext) -> str:
        """May this STARTED copy STAY where it is? NO triggers an
        eviction relocation in reroute (ref: AllocationDecider.canRemain
        — the disk-watermark / filter-change move-away path)."""
        return YES

    def can_move(self, shard: ShardRouting,
                 ctx: AllocationContext) -> str:
        """May this copy be relocated AT ALL (explicit move, rebalance,
        or eviction)? NO pins it in place — e.g. a primary currently
        streaming a snapshot."""
        return YES


class SameShardDecider(Decider):
    """Ref: decider/SameShardAllocationDecider.java — no two copies of a
    shard group on the same node."""

    name = "same_shard"

    def can_allocate(self, shard, node, ctx):
        for s in ctx.node_shards.get(node.node_id, ()):
            if s.shard_key == shard.shard_key:
                return NO
        return YES


class ReplicaAfterPrimaryActiveDecider(Decider):
    """Ref: decider/ReplicaAfterPrimaryActiveAllocationDecider.java."""

    name = "replica_after_primary_active"

    def can_allocate(self, shard, node, ctx):
        if shard.primary:
            return YES
        tbl = ctx.state.routing_table.index(shard.index)
        primary = tbl.shard(shard.shard).primary if tbl else None
        return YES if primary is not None and primary.active else NO


class ThrottlingDecider(Decider):
    """Ref: decider/ThrottlingAllocationDecider.java — bound concurrent
    incoming recoveries per node (default 2; device uploads are the
    costly phase here, the analog of the reference's disk+network copy)."""

    name = "throttling"

    def __init__(self, concurrent_recoveries: int = 2):
        self.concurrent_recoveries = concurrent_recoveries

    def can_allocate(self, shard, node, ctx):
        initializing = sum(
            1 for s in ctx.node_shards.get(node.node_id, ())
            if s.state == ShardState.INITIALIZING)
        return THROTTLE if initializing >= self.concurrent_recoveries else YES


class FilterDecider(Decider):
    """Ref: decider/FilterAllocationDecider.java — cluster-level
    include/exclude/require on node attributes via settings
    `cluster.routing.allocation.{include,exclude,require}.<attr>`.
    canRemain enforces the same rules on STARTED copies, so tightening
    an exclude filter MOVES existing shards away (the decommissioning
    workflow)."""

    name = "filter"

    @staticmethod
    def _check(node, ctx) -> str:
        settings = {**ctx.state.metadata.persistent_settings,
                    **ctx.state.metadata.transient_settings}
        for key, value in settings.items():
            parts = key.split(".")
            if len(parts) != 5 or parts[:3] != ["cluster", "routing", "allocation"]:
                continue
            mode, attr = parts[3], parts[4]
            if mode not in ("include", "exclude", "require"):
                continue
            values = {v.strip() for v in str(value).split(",") if v.strip()}
            attr_val = (node.attributes.get(attr) if attr != "_id"
                        else node.node_id)
            if mode == "exclude" and attr_val in values:
                return NO
            if mode == "require" and attr_val not in values:
                return NO
            if mode == "include" and values and attr_val not in values:
                return NO
        return YES

    def can_allocate(self, shard, node, ctx):
        return self._check(node, ctx)

    def can_remain(self, shard, node, ctx):
        return self._check(node, ctx)


class AwarenessDecider(Decider):
    """Ref: decider/AwarenessAllocationDecider.java — spread copies of a
    shard group evenly across values of an awareness attribute (zone)."""

    name = "awareness"

    def __init__(self, attributes: tuple[str, ...] = ()):
        self.attributes = attributes

    def can_allocate(self, shard, node, ctx):
        attrs = self.attributes or tuple(
            str(ctx.state.metadata.persistent_settings.get(
                "cluster.routing.allocation.awareness.attributes", "")).split(","))
        attrs = tuple(a for a in attrs if a)
        if not attrs:
            return YES
        tbl = ctx.state.routing_table.index(shard.index)
        group = tbl.shard(shard.shard) if tbl else None
        if group is None:
            return YES
        n_copies = len(group.copies)
        for attr in attrs:
            values = {n.attributes.get(attr) for n in
                      ctx.state.nodes.data_nodes.values()}
            values.discard(None)
            if not values:
                continue
            per_value_cap = -(-n_copies // len(values))  # ceil
            my_value = node.attributes.get(attr)
            assigned_same = 0
            for c in group.copies:
                if c.node_id and c.node_id != node.node_id:
                    peer = ctx.state.nodes.get(c.node_id)
                    if peer is not None and peer.attributes.get(attr) == my_value:
                        assigned_same += 1
            if assigned_same + 1 > per_value_cap:
                return NO
        return YES


class ShardsLimitDecider(Decider):
    """Ref: decider/ShardsLimitAllocationDecider.java — per-index
    `index.routing.allocation.total_shards_per_node`."""

    name = "shards_limit"

    def can_allocate(self, shard, node, ctx):
        imd = ctx.state.metadata.index(shard.index)
        if imd is None:
            return YES
        limit = imd.settings.get("index.routing.allocation.total_shards_per_node")
        if limit is None:
            return YES
        count = sum(1 for s in ctx.node_shards.get(node.node_id, ())
                    if s.index == shard.index)
        return NO if count >= int(limit) else YES


class HbmThresholdDecider(Decider):
    """DiskThresholdDecider analog for accelerator memory: nodes declare
    an HBM budget (node attribute `hbm_bytes`), indices an estimated
    per-shard footprint (`index.estimated_shard_bytes`). Like the
    reference's disk watermarks (DiskThresholdDecider.java):

      * LOW watermark (default 0.85) gates NEW allocations — a node
        past it takes no more shards;
      * HIGH watermark (default 0.90) evicts — a node past it fails
        canRemain and reroute relocates shards away until it is back
        under.

    Overridable live via cluster settings
    `cluster.routing.allocation.hbm.watermark.{low,high}`."""

    name = "hbm_threshold"

    def __init__(self, low_watermark: float = 0.85,
                 high_watermark: float = 0.9):
        self.low_watermark = low_watermark
        self.high_watermark = high_watermark

    def _marks(self, ctx) -> tuple[float, float]:
        lo = _cluster_setting(
            ctx, "cluster.routing.allocation.hbm.watermark.low",
            self.low_watermark)
        hi = _cluster_setting(
            ctx, "cluster.routing.allocation.hbm.watermark.high",
            self.high_watermark)
        return float(lo), float(hi)

    @staticmethod
    def _usage(node, ctx) -> tuple[float, float] | None:
        budget = node.attributes.get("hbm_bytes")
        if budget is None:
            return None
        used = 0.0
        for s in ctx.node_shards.get(node.node_id, ()):
            # copies already RELOCATING out are departing: projecting
            # them as freed is what stops one over-watermark node from
            # evicting EVERY shard in a single reroute pass
            if s.state == ShardState.RELOCATING:
                continue
            imd = ctx.state.metadata.index(s.index)
            if imd is not None:
                used += float(imd.settings.get(
                    "index.estimated_shard_bytes", 0))
        return float(budget), used

    def can_allocate(self, shard, node, ctx):
        usage = self._usage(node, ctx)
        if usage is None:
            return YES
        budget, used = usage
        imd = ctx.state.metadata.index(shard.index)
        incoming = float(imd.settings.get("index.estimated_shard_bytes", 0)
                         ) if imd else 0.0
        low, _hi = self._marks(ctx)
        return NO if used + incoming > budget * low else YES

    def can_remain(self, shard, node, ctx):
        usage = self._usage(node, ctx)
        if usage is None:
            return YES
        budget, used = usage
        _lo, high = self._marks(ctx)
        return NO if used > budget * high else YES


def _cluster_setting(ctx: AllocationContext, key: str, default=None):
    s = ctx.state.metadata.transient_settings.get(key)
    if s is None:
        s = ctx.state.metadata.persistent_settings.get(key, default)
    return s


class EnableAllocationDecider(Decider):
    """Ref: decider/EnableAllocationDecider.java —
    `cluster.routing.allocation.enable` (and the per-index
    `index.routing.allocation.enable`): all | primaries | new_primaries
    | none."""

    name = "enable"

    @staticmethod
    def _mode(ctx: AllocationContext, shard: ShardRouting) -> str:
        imd = ctx.state.metadata.index(shard.index)
        mode = (imd.settings.get("index.routing.allocation.enable")
                if imd is not None else None)
        if mode is None:
            mode = _cluster_setting(
                ctx, "cluster.routing.allocation.enable", "all")
        return str(mode).lower()

    def can_allocate(self, shard, node, ctx):
        mode = self._mode(ctx, shard)
        if mode == "all":
            return YES
        if mode == "none":
            return NO
        if mode == "primaries":
            return YES if shard.primary else NO
        if mode == "new_primaries":
            # only primaries never assigned before (fresh index) — a
            # failed existing primary stays frozen (was_assigned
            # survives fail(), the UnassignedInfo.Reason analog)
            return YES if shard.primary and not shard.was_assigned \
                else NO
        return YES

    def can_rebalance(self, shard, ctx):
        mode = str(_cluster_setting(
            ctx, "cluster.routing.allocation.rebalance.enable",
            _cluster_setting(ctx, "cluster.routing.rebalance.enable",
                             "all"))).lower()
        if mode == "none":
            return NO
        if mode == "primaries":
            return YES if shard.primary else NO
        if mode == "replicas":
            return NO if shard.primary else YES
        return YES


class DisableAllocationDecider(Decider):
    """Legacy disable flags (ref: decider/DisableAllocationDecider.java):
    cluster.routing.allocation.disable_allocation /
    disable_new_allocation / disable_replica_allocation + the
    index.routing.allocation.disable_* forms."""

    name = "disable"

    def can_allocate(self, shard, node, ctx):
        imd = ctx.state.metadata.index(shard.index)

        def flag(name: str) -> bool:
            v = (imd.settings.get(f"index.routing.allocation.{name}")
                 if imd is not None else None)
            if v is None:
                v = _cluster_setting(
                    ctx, f"cluster.routing.allocation.{name}", "false")
            return str(v).lower() == "true"

        if flag("disable_allocation"):
            return NO
        if not shard.primary and flag("disable_replica_allocation"):
            return NO
        if flag("disable_new_allocation") and not shard.was_assigned:
            return NO
        return YES


class ClusterRebalanceDecider(Decider):
    """Ref: decider/ClusterRebalanceAllocationDecider.java —
    cluster.routing.allocation.allow_rebalance: always |
    indices_primaries_active | indices_all_active (default)."""

    name = "cluster_rebalance"

    def can_rebalance(self, shard, ctx):
        mode = str(_cluster_setting(
            ctx, "cluster.routing.allocation.allow_rebalance",
            "indices_all_active")).lower()
        if mode == "always":
            return YES
        shards = list(ctx.state.routing_table.all_shards())
        if mode == "indices_primaries_active":
            return YES if all(
                s.active or s.relocating_node_id is not None
                for s in shards if s.primary) else NO
        # indices_all_active: nothing may be unassigned/initializing
        # (relocation targets excluded — they ARE the rebalance)
        return YES if all(
            s.active or s.relocating_node_id is not None
            for s in shards) else NO


class ConcurrentRebalanceDecider(Decider):
    """Ref: decider/ConcurrentRebalanceAllocationDecider.java —
    cluster.routing.allocation.cluster_concurrent_rebalance (default 2,
    -1 = unlimited)."""

    name = "concurrent_rebalance"

    def can_rebalance(self, shard, ctx):
        limit = int(_cluster_setting(
            ctx, "cluster.routing.allocation.cluster_concurrent_rebalance",
            2))
        if limit < 0:
            return YES
        relocating = sum(
            1 for s in ctx.state.routing_table.all_shards()
            if s.state == ShardState.RELOCATING)
        return THROTTLE if relocating >= limit else YES


def _node_version(node: DiscoveryNode) -> tuple[int, ...]:
    v = str(node.attributes.get("version", "1.0.0"))
    out = []
    for part in v.split("."):
        digits = "".join(c for c in part if c.isdigit())
        out.append(int(digits) if digits else 0)
    return tuple(out)


class NodeVersionDecider(Decider):
    """Ref: decider/NodeVersionAllocationDecider.java — a replica or
    relocation target recovers BY STREAMING from the primary/source, so
    it must not land on a node running an OLDER version than the node
    it streams from (older software can't read newer formats). Node
    versions ride the `version` node attribute; nodes without one are
    treated uniformly."""

    name = "node_version"

    def can_allocate(self, shard, node, ctx):
        if shard.relocating_node_id is not None:
            source = ctx.state.nodes.get(shard.relocating_node_id)
            if source is not None and \
                    _node_version(node) < _node_version(source):
                return NO
            return YES
        if shard.primary:
            return YES
        tbl = ctx.state.routing_table.index(shard.index)
        primary = tbl.shard(shard.shard).primary if tbl else None
        if primary is None or primary.node_id is None:
            return YES
        pnode = ctx.state.nodes.get(primary.node_id)
        if pnode is not None and \
                _node_version(node) < _node_version(pnode):
            return NO
        return YES


SNAPSHOT_IN_PROGRESS_SETTING = "cluster.snapshot.in_progress"


def parse_snapshot_pin(tok: str) -> tuple[str, int, str | None] | None:
    """One pin token -> (index, shard, owner_node_id | None). Pins are
    "index:shard@coordinator-node-id" (the owner id lets failover prune
    pins whose coordinator died mid-snapshot); the pre-owner "index:
    shard" form still parses with owner None."""
    tok, _, owner = tok.strip().partition("@")
    if ":" not in tok:
        return None
    idx, sid = tok.rsplit(":", 1)
    try:
        return idx, int(sid), (owner or None)
    except ValueError:
        return None


def prune_stale_snapshot_pins(state):
    """Drop snapshot shard pins whose coordinating node is no longer in
    the cluster (ref: the master-owned SnapshotsInProgress custom that
    SnapshotsService cleans up on node-leave). Without this, a
    coordinator dying mid-snapshot would pin its primaries FOREVER
    (SnapshotInProgressDecider.can_move == NO) — the marker is only
    removed in the coordinator's `finally`. Runs inside master state
    tasks (become-master, node-removed). Returns the (possibly
    unchanged) state."""
    raw = str(state.metadata.transient_settings.get(
        SNAPSHOT_IN_PROGRESS_SETTING, ""))
    keys = [k for k in raw.split(",") if k.strip()]
    if not keys:
        return state
    live = set(state.nodes.nodes)
    kept = []
    for k in keys:
        pin = parse_snapshot_pin(k)
        # ownerless (legacy) pins cannot be attributed, so they are
        # pruned too on membership change — a stale pin that outlives
        # its snapshot is strictly worse than re-pinning a live one
        if pin is not None and pin[2] in live:
            kept.append(k)
    if len(kept) == len(keys):
        return state
    from dataclasses import replace as _replace
    tr = dict(state.metadata.transient_settings)
    if kept:
        tr[SNAPSHOT_IN_PROGRESS_SETTING] = ",".join(sorted(kept))
    else:
        tr.pop(SNAPSHOT_IN_PROGRESS_SETTING, None)
    md = _replace(state.metadata, transient_settings=tr,
                  version=state.metadata.version + 1)
    return state.bump(metadata=md)


MESH_DEGRADED_SETTING = "cluster.mesh.degraded_rows"


def parse_degraded_row(tok: str) -> tuple[str, int] | None:
    """One token of the degraded-rows marker -> (index, physical row).
    Tokens are "index:row" — the mesh analog of an unassigned shard
    copy in the routing table."""
    tok = tok.strip()
    if ":" not in tok:
        return None
    idx, row = tok.rsplit(":", 1)
    try:
        return idx, int(row)
    except ValueError:
        return None


def mesh_degraded_rows(state) -> set[tuple[str, int]]:
    """Every (index, physical replica row) currently evicted from its
    mesh — the cluster-state surface of the elastic repack lifecycle
    (parallel/repack.py), readable by any node like the routing
    table."""
    raw = str(state.metadata.transient_settings.get(
        MESH_DEGRADED_SETTING, ""))
    out = set()
    for tok in raw.split(","):
        parsed = parse_degraded_row(tok)
        if parsed is not None:
            out.add(parsed)
    return out


def _with_degraded_rows(state, rows: set[tuple[str, int]]):
    from dataclasses import replace as _replace
    tr = dict(state.metadata.transient_settings)
    if rows:
        tr[MESH_DEGRADED_SETTING] = ",".join(
            sorted(f"{i}:{r}" for i, r in rows))
    else:
        tr.pop(MESH_DEGRADED_SETTING, None)
    md = _replace(state.metadata, transient_settings=tr,
                  version=state.metadata.version + 1)
    return state.bump(metadata=md)


def mark_mesh_row_dead(state, index: str, row: int):
    """Reroute-style pure transform: record an evicted (index, replica
    row) in cluster state — the AllocationService.applyFailedShards
    analog for a mesh row. Idempotent (returns the unchanged state when
    the marker already stands)."""
    rows = mesh_degraded_rows(state)
    if (index, row) in rows:
        return state
    return _with_degraded_rows(state, rows | {(index, row)})


def clear_mesh_row_dead(state, index: str, row: int):
    """Re-expansion transform: drop the marker when a probed row
    rejoins (applyStartedShards for a mesh row)."""
    rows = mesh_degraded_rows(state)
    if (index, row) not in rows:
        return state
    return _with_degraded_rows(state, rows - {(index, row)})


def apply_mesh_row_decision(state, decision: dict):
    """Fold one ElasticMeshSearcher decision (parallel/repack.py
    `decisions` / `on_decision`) into cluster state. Unknown decision
    kinds (repack_swapped, repack_aborted) change nothing — only
    membership events touch the marker."""
    index = decision.get("index")
    kind = decision.get("decision")
    if kind == "evict_row":
        return mark_mesh_row_dead(state, index, decision["row"])
    if kind in ("row_alive", "re_expand"):
        for row in decision.get("rows", ()):
            state = clear_mesh_row_dead(state, index, row)
        return state
    return state


class SnapshotInProgressDecider(Decider):
    """Ref: decider/SnapshotInProgressAllocationDecider.java — a primary
    whose shard is being snapshotted must not MOVE (the snapshot streams
    from that copy). The coordinator marks shards in the transient
    setting `cluster.snapshot.in_progress` ("index:shard@coordinator",
    see parse_snapshot_pin) for the duration of the snapshot
    (cluster_snapshot in distributed_node.py); stale pins are pruned on
    master failover / node-leave (prune_stale_snapshot_pins)."""

    name = "snapshot_in_progress"

    @staticmethod
    def _snapshotting(ctx) -> set[tuple[str, int]]:
        raw = str(_cluster_setting(ctx, SNAPSHOT_IN_PROGRESS_SETTING, ""))
        out = set()
        for tok in raw.split(","):
            pin = parse_snapshot_pin(tok)
            if pin is not None:
                out.add((pin[0], pin[1]))
        return out

    def can_move(self, shard, ctx):
        # blocks MOVING the streaming copy only — (re)allocating an
        # unassigned copy (e.g. a primary whose node died mid-snapshot)
        # must stay possible, so this is a move gate, not an allocate
        # gate
        if shard.primary and \
                (shard.index, shard.shard) in self._snapshotting(ctx):
            return NO
        return YES

    def can_rebalance(self, shard, ctx):
        return self.can_move(shard, ctx)


DEFAULT_DECIDERS: tuple[Decider, ...] = (
    SameShardDecider(),
    ReplicaAfterPrimaryActiveDecider(),
    EnableAllocationDecider(),
    DisableAllocationDecider(),
    FilterDecider(),
    AwarenessDecider(),
    ShardsLimitDecider(),
    HbmThresholdDecider(),
    NodeVersionDecider(),
    SnapshotInProgressDecider(),
    ClusterRebalanceDecider(),
    ConcurrentRebalanceDecider(),
    ThrottlingDecider(),
)


class AllocationService:
    """Ref: AllocationService.java:35. Pure state -> state transforms."""

    def __init__(self, deciders: Iterable[Decider] = DEFAULT_DECIDERS,
                 index_balance: float = 0.55, shard_balance: float = 0.45):
        self.deciders = tuple(deciders)
        self.index_balance = index_balance
        self.shard_balance = shard_balance

    # -- decision -----------------------------------------------------------

    def decide(self, shard: ShardRouting, node: DiscoveryNode,
               ctx: AllocationContext) -> str:
        verdict = YES
        for d in self.deciders:
            v = d.can_allocate(shard, node, ctx)
            if v == NO:
                return NO
            if v == THROTTLE:
                verdict = THROTTLE
        return verdict

    def decide_rebalance(self, shard: ShardRouting,
                         ctx: AllocationContext) -> str:
        verdict = YES
        for d in self.deciders:
            v = d.can_rebalance(shard, ctx)
            if v == NO:
                return NO
            if v == THROTTLE:
                verdict = THROTTLE
        return verdict

    def can_remain(self, shard: ShardRouting, node: DiscoveryNode,
                   ctx: AllocationContext) -> str:
        for d in self.deciders:
            if d.can_remain(shard, node, ctx) == NO:
                return NO
        return YES

    def can_move(self, shard: ShardRouting,
                 ctx: AllocationContext) -> str:
        for d in self.deciders:
            if d.can_move(shard, ctx) == NO:
                return NO
        return YES

    def explain(self, shard: ShardRouting, node: DiscoveryNode,
                ctx: AllocationContext) -> list[tuple[str, str]]:
        """Per-decider verdicts — the _cluster/allocation/explain analog."""
        return [(d.name, d.can_allocate(shard, node, ctx))
                for d in self.deciders]

    def explain_shard(self, state: ClusterState, index: str,
                      shard_id: int, primary: bool = True) -> dict:
        """The `_cluster/allocation/explain` report: where the copy is,
        why it can('t) go to each node, and why it may(n't) stay.
        Ref: the reference's decider multiExplanation surfaced per node
        (cluster/routing/allocation/decider/)."""
        from ..utils.errors import IllegalArgumentError
        tbl = state.routing_table.index(index)
        if tbl is None or not 0 <= shard_id < len(tbl.shards):
            raise IllegalArgumentError(
                f"[allocation explain] shard [{index}][{shard_id}] "
                "not found")
        group = tbl.shard(shard_id)
        copy = next((c for c in group.copies if c.primary == primary),
                    None)
        if copy is None:
            copy = ShardRouting(index=index, shard=shard_id,
                                primary=primary)
        ctx = AllocationContext.of(state)
        nodes = []
        for nid, node in sorted(state.nodes.data_nodes.items()):
            if copy.node_id == nid:
                deciders = [{"decider": d.name,
                             "decision": d.can_remain(copy, node, ctx)}
                            for d in self.deciders]
                decision = NO if any(e["decision"] == NO
                                     for e in deciders) else YES
                nodes.append({"node_id": nid, "node_name": node.name,
                              "current": True,
                              "can_remain": decision,
                              "deciders": [e for e in deciders
                                           if e["decision"] != YES]})
            else:
                probe = (copy.fail() if copy.assigned else copy)
                deciders = [{"decider": d.name,
                             "decision": d.can_allocate(probe, node, ctx)}
                            for d in self.deciders]
                if any(e["decision"] == NO for e in deciders):
                    decision = NO
                elif any(e["decision"] == THROTTLE for e in deciders):
                    decision = THROTTLE
                else:
                    decision = YES
                nodes.append({"node_id": nid, "node_name": node.name,
                              "current": False,
                              "decision": decision,
                              "weight": self._weight(ctx, nid, index),
                              "deciders": [e for e in deciders
                                           if e["decision"] != YES]})
        return {
            "shard": {"index": index, "shard": shard_id,
                      "primary": primary},
            "current_state": copy.state.value
            if hasattr(copy.state, "value") else str(copy.state),
            "current_node": copy.node_id,
            "nodes": nodes,
        }

    # -- weight (BalancedShardsAllocator.java:67-79) -------------------------

    def _weight(self, ctx: AllocationContext, node_id: str, index: str) -> float:
        shards_on_node = len(ctx.node_shards.get(node_id, ()))
        index_on_node = sum(1 for s in ctx.node_shards.get(node_id, ())
                            if s.index == index)
        n_nodes = max(len(ctx.node_shards), 1)
        total = sum(len(v) for v in ctx.node_shards.values())
        total_index = sum(1 for s in ctx.state.routing_table.all_shards()
                          if s.index == index and s.assigned)
        avg_shards = total / n_nodes
        avg_index = total_index / n_nodes
        return (self.shard_balance * (shards_on_node - avg_shards)
                + self.index_balance * (index_on_node - avg_index))

    # -- reroute ------------------------------------------------------------

    def reroute(self, state: ClusterState) -> ClusterState:
        """Assign unassigned shard copies to the least-loaded allowed data
        node. Ref: AllocationService.reroute:119."""
        rt = state.routing_table
        changed = False
        ctx = AllocationContext.of(state)
        # primaries first (replicas depend on an active primary)
        unassigned = sorted(
            (s for s in rt.all_shards() if s.state == ShardState.UNASSIGNED),
            key=lambda s: (not s.primary, s.index, s.shard))
        for shard in unassigned:
            candidates = []
            for nid, node in ctx.state.nodes.data_nodes.items():
                v = self.decide(shard, node, ctx)
                if v == YES:
                    candidates.append(
                        (self._weight(ctx, nid, shard.index), nid))
            if not candidates:
                continue
            candidates.sort()
            target = candidates[0][1]
            new_shard = shard.initialize(target)
            rt = rt.update_shard(shard, new_shard)
            ctx = AllocationContext.of(state.bump(routing_table=rt))
            changed = True
        if changed:
            state = state.with_routing(rt)
        return self._evict_unremainable(state)

    def _evict_unremainable(self, state: ClusterState) -> ClusterState:
        """Move STARTED copies whose node now fails canRemain (filter
        exclusions, HBM high watermark) to the best allowed node — the
        reference's moveShards pass (AllocationService via
        ShardsAllocator.moveShards / DiskThresholdDecider high
        watermark)."""
        ctx = AllocationContext.of(state)
        for shard in list(state.routing_table.all_shards()):
            if shard.state != ShardState.STARTED:
                continue
            node = state.nodes.get(shard.node_id)
            if node is None or self.can_remain(shard, node, ctx) == YES:
                continue
            if self.can_move(shard, ctx) == NO:
                continue  # pinned (snapshot stream): watermark waits
            candidates = []
            for nid, cand in ctx.state.nodes.data_nodes.items():
                if nid == shard.node_id:
                    continue
                if self.decide(shard.fail(), cand, ctx) == YES:
                    candidates.append(
                        (self._weight(ctx, nid, shard.index), nid))
            if not candidates:
                continue  # nowhere better: stay (same as the reference)
            candidates.sort()
            state = self.start_relocation(state, shard, candidates[0][1])
            ctx = AllocationContext.of(state)
        return state

    @staticmethod
    def _relocation_counterpart(group, copy: ShardRouting,
                                state: "ShardState") -> ShardRouting | None:
        """The other half of a relocation pair: the copy on
        `copy.relocating_node_id` in `state` whose own relocating pointer
        aims back at `copy.node_id`."""
        return next(
            (s for s in group.copies
             if s.node_id == copy.relocating_node_id
             and s.state == state
             and s.relocating_node_id == copy.node_id), None)

    def apply_started_shards(self, state: ClusterState,
                             started: list[ShardRouting]) -> ClusterState:
        """Ref: AllocationService.applyStartedShards:73. A started
        relocation TARGET completes the handoff: the RELOCATING source
        copy leaves the table and the target inherits its primary flag
        (ref: RoutingNodes.started on a relocation target)."""
        rt = state.routing_table
        changed = False
        for shard in started:
            tbl = rt.index(shard.index)
            if tbl is None:
                continue
            for c in tbl.shard(shard.shard).copies:
                if (c.node_id == shard.node_id and c.primary == shard.primary
                        and c.state == ShardState.INITIALIZING
                        and (shard.allocation_id is None
                             or c.allocation_id == shard.allocation_id)):
                    # allocation-id match keeps a delayed started-report
                    # for a dead allocation from activating its
                    # still-recovering successor (ref: AllocationId)
                    source = None
                    if c.relocating_node_id is not None:
                        source = self._relocation_counterpart(
                            tbl.shard(shard.shard), c, ShardState.RELOCATING)
                    started_copy = c.start()
                    if source is not None and source.primary:
                        started_copy = started_copy.promote()
                    rt = rt.update_shard(c, started_copy)
                    if source is not None:
                        rt = rt.update_shard(source, None)
                    changed = True
                    break
        if not changed:
            return state
        return self.reroute(state.with_routing(rt))

    def apply_failed_shards(self, state: ClusterState,
                            failed: list[ShardRouting]) -> ClusterState:
        """Ref: AllocationService.applyFailedShards:102 — failed primary:
        promote an active replica; failed copy goes back to UNASSIGNED."""
        rt = state.routing_table
        changed = False
        for shard in failed:
            tbl = rt.index(shard.index)
            if tbl is None:
                continue
            group = tbl.shard(shard.shard)
            target = next((c for c in group.copies
                           if c.node_id == shard.node_id
                           and c.primary == shard.primary
                           and (shard.allocation_id is None
                                or c.allocation_id
                                == shard.allocation_id)), None)
            if target is None:
                # stale report: the named allocation is gone (already
                # failed and re-allocated) — never fail its successor
                # (ref: ShardStateAction matching by AllocationId)
                continue
            if target.state == ShardState.INITIALIZING \
                    and target.relocating_node_id is not None:
                # failed relocation TARGET: drop it, source resumes as a
                # plain STARTED copy (ref: RoutingNodes cancelRelocation)
                rt = rt.update_shard(target, None)
                source = self._relocation_counterpart(
                    group, target, ShardState.RELOCATING)
                if source is not None:
                    rt = rt.update_shard(source, source.start())
                changed = True
                continue
            if target.state == ShardState.RELOCATING:
                # failed relocation SOURCE: its in-flight target loses
                # its recovery source — cancel it too, then the normal
                # fail path reallocates
                tgt = self._relocation_counterpart(
                    group, target, ShardState.INITIALIZING)
                if tgt is not None:
                    rt = rt.update_shard(tgt, None)
            # demote only when an active replica can take over the
            # primary flag; otherwise the unassigned copy must stay
            # primary or ReplicaAfterPrimaryActiveDecider would refuse
            # to ever reallocate the group
            group = rt.index(shard.index).shard(shard.shard)
            promo = next((c for c in group.copies
                          if not c.primary and c.active
                          and c is not target), None) \
                if target.primary else None
            rt = rt.update_shard(target, target.fail().demote()
                                 if promo is not None else target.fail())
            changed = True
            if promo is not None:
                rt = rt.update_shard(promo, promo.promote())
        if not changed:
            return state
        return self.reroute(state.with_routing(rt))

    def disassociate_dead_nodes(self, state: ClusterState) -> ClusterState:
        """Fail every copy on nodes no longer in the cluster — ref:
        AllocationService.deassociateDeadNodes."""
        live = set(state.nodes.nodes)
        dead_copies = [s for s in state.routing_table.all_shards()
                       if s.node_id is not None and s.node_id not in live]
        if not dead_copies:
            return self.reroute(state)
        return self.apply_failed_shards(state, dead_copies)

    def start_relocation(self, state: ClusterState, shard: ShardRouting,
                         to_node: str) -> ClusterState:
        """STARTED copy -> RELOCATING source + INITIALIZING target pair.
        The source keeps serving (and stays primary) until the target
        reports started — ref: RoutingNodes.relocate +
        IndexShard.relocated handoff (index/shard/IndexShard.java:345)."""
        import uuid
        rt = state.routing_table.update_shard(shard, shard.relocate(to_node))
        target = ShardRouting(
            index=shard.index, shard=shard.shard, primary=False,
            state=ShardState.INITIALIZING, node_id=to_node,
            relocating_node_id=shard.node_id,
            allocation_id=uuid.uuid4().hex[:12])
        return state.with_routing(rt.add_shard_copy(target))

    def move(self, state: ClusterState, index: str, shard_id: int,
             from_node: str, to_node: str) -> ClusterState:
        """The `_cluster/reroute` move command (ref:
        cluster/routing/allocation/command/MoveAllocationCommand.java)."""
        from ..utils.errors import IllegalArgumentError
        tbl = state.routing_table.index(index)
        if tbl is None or not 0 <= shard_id < len(tbl.shards):
            raise IllegalArgumentError(f"[move] shard [{index}][{shard_id}]"
                                       f" not found")
        source = next((c for c in tbl.shard(shard_id).copies
                       if c.node_id == from_node), None)
        if source is None or source.state != ShardState.STARTED:
            raise IllegalArgumentError(
                f"[move] shard [{index}][{shard_id}] on node [{from_node}]"
                f" is not started")
        node = state.nodes.get(to_node)
        if node is None:
            raise IllegalArgumentError(f"[move] node [{to_node}] not found")
        ctx = AllocationContext.of(state)
        if self.can_move(source, ctx) == NO:
            raise IllegalArgumentError(
                f"[move] shard [{index}][{shard_id}] cannot relocate "
                "(pinned — e.g. snapshot in progress)")
        if self.decide(source.fail(), node, ctx) != YES:
            raise IllegalArgumentError(
                f"[move] allocation deciders reject [{index}][{shard_id}]"
                f" on node [{to_node}]")
        return self.start_relocation(state, source, to_node)

    def cancel_relocation(self, state: ClusterState, index: str,
                          shard_id: int, node_id: str) -> ClusterState:
        """The `_cluster/reroute` cancel command for a relocation target
        (ref: command/CancelAllocationCommand.java)."""
        from ..utils.errors import IllegalArgumentError
        tbl = state.routing_table.index(index)
        target = None
        if tbl is not None and 0 <= shard_id < len(tbl.shards):
            target = next(
                (c for c in tbl.shard(shard_id).copies
                 if c.node_id == node_id
                 and c.state == ShardState.INITIALIZING
                 and c.relocating_node_id is not None), None)
        if target is None:
            raise IllegalArgumentError(
                f"[cancel] no cancellable copy of [{index}][{shard_id}] "
                f"on node [{node_id}]")
        return self.apply_failed_shards(state, [target])

    def rebalance(self, state: ClusterState, max_moves: int = 1) -> ClusterState:
        """Relocate STARTED shards from overweight to underweight nodes
        when the weight delta exceeds threshold 1.0 — the
        BalancedShardsAllocator rebalance pass. The moved copy keeps
        serving from its source until the target catches up
        (start_relocation handoff)."""
        moves = 0
        for _ in range(max_moves):
            ctx = AllocationContext.of(state)
            if len(ctx.node_shards) < 2:
                break
            loads = sorted(((len(v), k) for k, v in ctx.node_shards.items()))
            (lo_n, lo_id), (hi_n, hi_id) = loads[0], loads[-1]
            if hi_n - lo_n <= 1:  # threshold 1.0
                break
            candidates = [s for s in ctx.node_shards[hi_id]
                          if s.state == ShardState.STARTED
                          and self.decide_rebalance(s, ctx) == YES]
            moved = False
            for shard in candidates:
                node = state.nodes.get(lo_id)
                if node and self.decide(shard.fail(), node, ctx) == YES:
                    state = self.start_relocation(state, shard, lo_id)
                    moves += 1
                    moved = True
                    break
            if not moved:
                break
        return state
