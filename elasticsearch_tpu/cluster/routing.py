"""Document -> shard routing.

Reference analog: cluster/routing/OperationRouting.java:259-282 —
shard = hash(routing ?: id) % number_of_shards, with DjbHash as the 2.0
default and Murmur3HashFunction optional (it became the only hash
later). We use DjbHash so placements match the reference exactly (the
REST YAML suites encode specific id->shard assignments); murmur3_32
remains available for murmur3-routed indices and the murmur3 field
type. Data directories written before the DjbHash switch place docs by
murmur3 and must be reindexed — there is no on-disk hash-version
marker yet (pre-release format change).
"""

from __future__ import annotations

import struct


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86 32-bit (public algorithm, Austin Appleby)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n = len(data)
    rounded = n - (n & 3)
    for off in range(0, rounded, 4):
        (k,) = struct.unpack_from("<I", data, off)
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def djb_hash(value: str) -> int:
    """DJB2 string hash (public Bernstein algorithm) — the 2.0 default
    routing hash (cluster/routing/operation/hash/djb/DjbHashFunction),
    over UTF-16 code units like Java's char iteration."""
    h = 5381
    for ch in value:
        # Java hashes char-by-char; surrogate pairs hash as two units
        for unit in ([ord(ch)] if ord(ch) < 0x10000 else [
                0xD800 + ((ord(ch) - 0x10000) >> 10),
                0xDC00 + ((ord(ch) - 0x10000) & 0x3FF)]):
            h = ((h << 5) + h + unit) & 0xFFFFFFFF
    return h


def shard_id(doc_id: str, num_shards: int, routing: str | None = None) -> int:
    """Ref: OperationRouting.generateShardId — hash(routing ?: id) %
    shards, DjbHash as in the reference's 2.0 default (the YAML suites
    encode its exact placements, e.g. delete/50_refresh.yaml's comment
    about ids 1 vs 3)."""
    key = routing if routing is not None else doc_id
    h = djb_hash(key)
    if h >= 1 << 31:            # Java int is signed; MathUtils.mod
        h -= 1 << 32            # folds negatives back to [0, n)
    return ((h % num_shards) + num_shards) % num_shards
