"""Discovery: ping, master election, join flow, state publish, heartbeats.

Reference analog: discovery/zen/ — ZenDiscovery.java:354-358
(innerJoinCluster/findMaster), ElectMasterService (election = minimum
node id among master-eligible candidates), MembershipAction (join/leave),
PublishClusterStateAction.java:98-131 (master pushes the FULL state to
every node, nodes ack), and discovery/zen/fd/ bidirectional heartbeats
(MasterFaultDetection.java:228-282 nodes->master,
NodesFaultDetection.java master->nodes) with ping_interval/timeout/
retries (FaultDetection.java:39-41). The quorum guard is
`discovery.zen.minimum_master_nodes` (rejoin at ZenDiscovery.java:512-513).

In-process the published state travels by reference over the Transport
hub; a multi-host deployment serializes `ClusterState.summary()` plus the
routing/metadata trees over gRPC — the flow (single master, full-state
publish, version-ordered adoption, ack) is identical.

Heartbeats are pull-driven: `FaultDetector.tick()` does one round, and
`Discovery.start_heartbeats(interval)` runs ticks on a daemon thread.
Tests drive ticks manually for determinism (the reference's tests do the
same via ThreadPool time mocking + disruption schemes).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import wait
from dataclasses import replace

from .allocation import AllocationService
from .service import ClusterService, URGENT, IMMEDIATE
from .state import (ClusterState, DiscoveryNode, DiscoveryNodes,
                    NO_MASTER_BLOCK, ShardRouting)
from .transport import Transport, TransportError

logger = logging.getLogger("elasticsearch_tpu.discovery")

PING_ACTION = "internal:discovery/zen/ping"
JOIN_ACTION = "internal:discovery/zen/join"
LEAVE_ACTION = "internal:discovery/zen/leave"
PUBLISH_ACTION = "internal:discovery/zen/publish"
MASTER_PING_ACTION = "internal:discovery/zen/fd/master_ping"
NODE_PING_ACTION = "internal:discovery/zen/fd/ping"
SHARD_STARTED_ACTION = "internal:cluster/shard/started"
SHARD_FAILED_ACTION = "internal:cluster/shard/failure"


def elect_master(candidates: list[DiscoveryNode]) -> DiscoveryNode | None:
    """Ref: ElectMasterService.electMaster — sort by node id, pick first."""
    eligible = sorted((c for c in candidates if c.master_eligible),
                      key=lambda n: n.node_id)
    return eligible[0] if eligible else None


class Discovery:
    """One node's discovery/membership agent."""

    def __init__(self, local_node: DiscoveryNode, transport: Transport,
                 cluster_service: ClusterService,
                 allocation: AllocationService,
                 seed_ids: list[str] | None = None,
                 min_master_nodes: int = 1,
                 fd_retries: int = 3):
        self.local = local_node
        self.transport = transport
        self.cluster = cluster_service
        self.allocation = allocation
        self.seed_ids = seed_ids
        self.min_master_nodes = min_master_nodes
        self.fd_retries = fd_retries
        self._fd_failures: dict[str, int] = {}
        self._hb_thread: threading.Thread | None = None
        self._hb_stop = threading.Event()
        self._term = 0

        t = transport
        t.register_handler(PING_ACTION, self._on_ping)
        t.register_handler(JOIN_ACTION, self._on_join)
        t.register_handler(LEAVE_ACTION, self._on_leave)
        t.register_handler(PUBLISH_ACTION, self._on_publish)
        t.register_handler(MASTER_PING_ACTION, self._on_master_ping)
        t.register_handler(NODE_PING_ACTION, self._on_node_ping)
        t.register_handler(SHARD_STARTED_ACTION, self._on_shard_started)
        t.register_handler(SHARD_FAILED_ACTION, self._on_shard_failed)

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    @property
    def state(self) -> ClusterState:
        return self.cluster.state

    @property
    def is_master(self) -> bool:
        return self.state.nodes.master_node_id == self.local.node_id

    def _node_from_wire(self, d: dict) -> DiscoveryNode:
        return DiscoveryNode(node_id=d["node_id"], name=d.get("name", ""),
                             master_eligible=d.get("master_eligible", True),
                             data=d.get("data", True),
                             attributes=d.get("attributes", {}))

    def _node_to_wire(self, n: DiscoveryNode) -> dict:
        return {"node_id": n.node_id, "name": n.name,
                "master_eligible": n.master_eligible, "data": n.data,
                "attributes": dict(n.attributes)}

    # ------------------------------------------------------------------
    # join / election (ZenDiscovery.innerJoinCluster / findMaster)
    # ------------------------------------------------------------------

    def join_cluster(self, timeout: float = 5.0) -> None:
        """Ping seeds, find or elect a master, join it (or become it)."""
        seeds = self.seed_ids if self.seed_ids is not None \
            else self.transport.hub.node_ids()
        responses: list[dict] = []
        futures = {sid: self.transport.submit_request(
            sid, PING_ACTION, {"node": self._node_to_wire(self.local)})
            for sid in seeds if sid != self.local.node_id}
        if futures:
            wait(list(futures.values()), timeout=timeout)
        for sid, fut in futures.items():
            if fut.done() and fut.exception() is None:
                responses.append(fut.result())

        # does anyone already have a master? Trust a claim "master is M"
        # only if M itself confirms (it answered our ping, or answers one
        # now) — a peer may not yet have noticed the old master dying.
        responded = {r["node"]["node_id"] for r in responses}
        claimed = {r["master"] for r in responses if r.get("master")}
        claimed.discard(self.local.node_id)
        active_masters = set()
        for m in claimed:
            if m in responded:
                active_masters.add(m)
            else:
                try:
                    self.transport.send_request(m, PING_ACTION, {
                        "node": self._node_to_wire(self.local)}, timeout=2.0)
                    active_masters.add(m)
                except TransportError:
                    pass
        if active_masters:
            master_id = sorted(active_masters)[0]
            self._send_join(master_id, timeout)
            return

        # full election among all master-eligible pinged nodes + self
        candidates = [self.local] + [self._node_from_wire(r["node"])
                                     for r in responses]
        eligible = [c for c in candidates if c.master_eligible]
        if len(eligible) < self.min_master_nodes:
            logger.info("[%s] not enough master nodes (%d < %d), waiting",
                        self.local.node_id, len(eligible),
                        self.min_master_nodes)
            self._set_no_master()
            return
        winner = elect_master(candidates)
        if winner is None:
            self._set_no_master()
            return
        if winner.node_id == self.local.node_id:
            self._become_master()
        else:
            self._send_join(winner.node_id, timeout)

    def _become_master(self) -> None:
        self._term += 1
        term = self._term

        def task(cur: ClusterState) -> ClusterState:
            from .allocation import prune_stale_snapshot_pins
            nodes = cur.nodes.with_node(self.local) \
                .with_master(self.local.node_id) \
                .with_local(self.local.node_id)
            blocks = cur.blocks.without_global(NO_MASTER_BLOCK)
            new = cur.bump(nodes=nodes, blocks=blocks,
                           master_term=max(cur.master_term + 1, term))
            # a new master inherits whatever snapshot pins the old one
            # published; pins whose coordinator is gone would otherwise
            # freeze those primaries forever
            new = prune_stale_snapshot_pins(new)
            # fail shard copies stranded on nodes no longer in the
            # cluster BEFORE rerouting: the master-death path
            # (_handle_master_loss) only drops the node from the node
            # set, so without this the dead master's copies stay
            # STARTED-on-a-ghost forever — its primaries are never
            # demoted, replicas never promoted, and the group can
            # never heal (found by the ISSUE 15 corrupt-primary heal
            # arc; _remove_node already does this for non-master death)
            new = self.allocation.disassociate_dead_nodes(new)
            return self.allocation.reroute(new)
        self.cluster.submit_state_update_task("become-master", task,
                                              URGENT).result(10)

    def _send_join(self, master_id: str, timeout: float) -> None:
        try:
            self.transport.send_request(
                master_id, JOIN_ACTION,
                {"node": self._node_to_wire(self.local)}, timeout=timeout)
        except TransportError:
            logger.info("[%s] join to [%s] failed; will retry election",
                        self.local.node_id, master_id)
            self._set_no_master()

    def _set_no_master(self) -> None:
        def task(cur: ClusterState) -> ClusterState:
            nodes = cur.nodes.with_node(self.local).with_local(
                self.local.node_id).with_master(None)
            return cur.bump(nodes=nodes,
                            blocks=cur.blocks.with_global(NO_MASTER_BLOCK))
        self.cluster.submit_state_update_task("no-master", task,
                                              IMMEDIATE).result(10)

    # ------------------------------------------------------------------
    # handlers
    # ------------------------------------------------------------------

    def _on_ping(self, src: str, req: dict) -> dict:
        return {"node": self._node_to_wire(self.local),
                "master": self.state.nodes.master_node_id,
                "cluster_name": self.state.cluster_name}

    def _on_join(self, src: str, req: dict) -> dict:
        """Master side of MembershipAction.JoinRequest."""
        joiner = self._node_from_wire(req["node"])
        # Zen "election context": a join can land on a node that hasn't
        # finished its own election yet. If we have no master and would
        # win the election against the joiner, accept the mandate and
        # become master (ref: ZenDiscovery join-thread election accounting).
        if not self.is_master and self.state.nodes.master_node_id is None \
                and self.local.master_eligible \
                and self.local.node_id < joiner.node_id:
            self._become_master()

        def task(cur: ClusterState) -> ClusterState:
            if cur.nodes.master_node_id != self.local.node_id:
                raise TransportError(
                    f"[{self.local.node_id}] not master, cannot accept join")
            nodes = cur.nodes.with_node(joiner)
            new = cur.bump(nodes=nodes)
            return self.allocation.reroute(new)
        self.cluster.submit_state_update_task(
            f"node-join[{joiner.node_id}]", task, URGENT).result(10)
        return {"ok": True, "master": self.local.node_id}

    def _on_leave(self, src: str, req: dict) -> dict:
        node_id = req["node_id"]
        self._remove_node(node_id, reason="left")
        return {"ok": True}

    def _on_publish(self, src: str, req: dict) -> dict:
        new_state: ClusterState = req["state"]
        local_id = self.local.node_id
        # adopt with our local_node_id stamped in
        adopted = replace(new_state,
                          nodes=new_state.nodes.with_local(local_id))
        self.cluster.apply_published_state(adopted).result(10)
        return {"ack": True, "version": new_state.version}

    def _on_master_ping(self, src: str, req: dict) -> dict:
        """Node asks 'are you still master?' — ref
        MasterFaultDetection.MasterPingRequestHandler."""
        return {"is_master": self.is_master}

    def _on_node_ping(self, src: str, req: dict) -> dict:
        """Master asks 'are you still there?'"""
        return {"ok": True, "node_id": self.local.node_id}

    def _on_shard_started(self, src: str, req: dict) -> dict:
        """Ref: ShardStateAction.java:55 — data node reports a shard copy
        STARTED; master applies + reroutes + publishes."""
        shard = ShardRouting(**req["shard"])

        def task(cur: ClusterState) -> ClusterState:
            return self.allocation.apply_started_shards(cur, [shard])
        self.cluster.submit_state_update_task(
            f"shard-started[{shard.index}][{shard.shard}]", task).result(10)
        return {"ok": True}

    def _on_shard_failed(self, src: str, req: dict) -> dict:
        shard = ShardRouting(**req["shard"])

        def task(cur: ClusterState) -> ClusterState:
            return self.allocation.apply_failed_shards(cur, [shard])
        self.cluster.submit_state_update_task(
            f"shard-failed[{shard.index}][{shard.shard}]", task).result(10)
        return {"ok": True}

    # ------------------------------------------------------------------
    # publish (master side)
    # ------------------------------------------------------------------

    def publish(self, state: ClusterState) -> None:
        """Push the new state to every other node; wait for acks.
        Ref: PublishClusterStateAction.java:98-131."""
        futures = []
        for node in state.nodes:
            if node.node_id == self.local.node_id:
                continue
            futures.append(self.transport.submit_request(
                node.node_id, PUBLISH_ACTION, {"state": state}))
        if futures:
            done, not_done = wait(futures, timeout=5.0)
            n_failed = len(not_done) + sum(
                1 for f in done if f.exception() is not None)
            if n_failed:
                logger.debug("[%s] publish v%d: %d nodes did not ack",
                             self.local.node_id, state.version, n_failed)

    # ------------------------------------------------------------------
    # fault detection
    # ------------------------------------------------------------------

    def fd_tick(self) -> None:
        """One heartbeat round. Master pings all nodes (NodesFaultDetection);
        non-masters ping the master (MasterFaultDetection). `fd_retries`
        consecutive failures trigger removal / re-election."""
        st = self.state
        if self.is_master:
            for node in list(st.nodes):
                nid = node.node_id
                if nid == self.local.node_id:
                    continue
                try:
                    self.transport.send_request(nid, NODE_PING_ACTION, {},
                                                timeout=2.0)
                    self._fd_failures.pop(nid, None)
                except TransportError:
                    n = self._fd_failures.get(nid, 0) + 1
                    self._fd_failures[nid] = n
                    if n >= self.fd_retries:
                        self._fd_failures.pop(nid, None)
                        logger.info("[%s] node [%s] failed %d pings, removing",
                                    self.local.node_id, nid, n)
                        self._remove_node(nid, reason="failed heartbeats")
        else:
            master_id = st.nodes.master_node_id
            if master_id is None:
                self.join_cluster()
                return
            ok = False
            try:
                resp = self.transport.send_request(
                    master_id, MASTER_PING_ACTION, {}, timeout=2.0)
                ok = bool(resp.get("is_master"))
            except TransportError:
                ok = False
            if ok:
                self._fd_failures.pop(master_id, None)
            else:
                n = self._fd_failures.get(master_id, 0) + 1
                self._fd_failures[master_id] = n
                if n >= self.fd_retries:
                    self._fd_failures.pop(master_id, None)
                    logger.info("[%s] master [%s] unreachable, re-electing",
                                self.local.node_id, master_id)
                    self._handle_master_loss(master_id)

    def _handle_master_loss(self, old_master: str) -> None:
        """Ref: ZenDiscovery.handleMasterGone:531 — drop the master from
        our node set, then run a fresh election among the remainder."""
        def task(cur: ClusterState) -> ClusterState:
            nodes = cur.nodes.without_node(old_master)
            return cur.bump(nodes=nodes,
                            blocks=cur.blocks.with_global(NO_MASTER_BLOCK))
        self.cluster.submit_state_update_task("master-gone", task,
                                              IMMEDIATE).result(10)
        self.join_cluster()

    def _remove_node(self, node_id: str, reason: str) -> None:
        """Master removes a node: quorum check, fail its shards, publish.
        Ref: ZenDiscovery.handleNodeFailure:535 + rejoin :512-513."""
        def task(cur: ClusterState) -> ClusterState:
            if cur.nodes.master_node_id != self.local.node_id:
                return cur
            nodes = cur.nodes.without_node(node_id)
            remaining_masters = len(nodes.master_eligible_nodes)
            if remaining_masters < self.min_master_nodes:
                # step down: not enough master-eligible nodes left
                logger.info("[%s] stepping down: %d master nodes < "
                            "minimum %d", self.local.node_id,
                            remaining_masters, self.min_master_nodes)
                nodes = nodes.with_master(None)
                return cur.bump(nodes=nodes,
                                blocks=cur.blocks.with_global(NO_MASTER_BLOCK))
            nodes = nodes.with_master(self.local.node_id)
            new = cur.bump(nodes=nodes)
            # node-leave cleanup: drop snapshot pins the departed node
            # coordinated (ref: SnapshotsInProgress cleanup on
            # node-leave) before reallocating its shards
            from .allocation import prune_stale_snapshot_pins
            new = prune_stale_snapshot_pins(new)
            return self.allocation.disassociate_dead_nodes(new)
        self.cluster.submit_state_update_task(
            f"node-removed[{node_id}][{reason}]", task, URGENT).result(10)

    # ------------------------------------------------------------------
    # background heartbeats
    # ------------------------------------------------------------------

    def start_heartbeats(self, interval: float = 1.0) -> None:
        if self._hb_thread is not None:
            return
        self._hb_stop.clear()

        def loop():
            while not self._hb_stop.wait(interval):
                try:
                    self.fd_tick()
                except Exception:
                    logger.exception("[%s] heartbeat tick failed",
                                     self.local.node_id)
        self._hb_thread = threading.Thread(
            target=loop, name=f"fd[{self.local.node_id}]", daemon=True)
        self._hb_thread.start()

    def stop_heartbeats(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
            self._hb_thread = None

    # -- shard state reporting (data-node side) -----------------------------

    def report_shard_started(self, shard: ShardRouting) -> None:
        master = self.state.nodes.master_node_id
        if master is None:
            return
        payload = {"shard": {"index": shard.index, "shard": shard.shard,
                             "primary": shard.primary, "state": shard.state,
                             "node_id": shard.node_id,
                             "allocation_id": shard.allocation_id}}
        if master == self.local.node_id:
            self._on_shard_started(self.local.node_id, payload)
        else:
            self.transport.send_request(master, SHARD_STARTED_ACTION, payload)

    def report_shard_failed(self, shard: ShardRouting) -> None:
        master = self.state.nodes.master_node_id
        if master is None:
            return
        payload = {"shard": {"index": shard.index, "shard": shard.shard,
                             "primary": shard.primary, "state": shard.state,
                             "node_id": shard.node_id,
                             "allocation_id": shard.allocation_id}}
        if master == self.local.node_id:
            self._on_shard_failed(self.local.node_id, payload)
        else:
            self.transport.send_request(master, SHARD_FAILED_ACTION, payload)
