"""DataNode: cluster-state-driven shards + replicated writes + fan-out search.

Reference analogs:
- indices/cluster/IndicesClusterStateService.java:150-706 — applying each
  published ClusterState to the local node: create/remove shard engines,
  trigger recoveries, report SHARD_STARTED back to the master.
- action/support/replication/TransportShardReplicationOperationAction.java
  :67,:118-120 — the primary/replica write template with write-consistency
  check (:124) and replica fan-out.
- action/search/type/TransportSearchQueryThenFetchAction.java — the
  scatter phase over one copy of every shard group, reduced by
  search/controller.py (SearchPhaseController analog).
- indices/recovery/RecoverySourceHandler.java — peer recovery; here the
  doc stream replaces the Lucene file-diff because device-side columnar
  segments are rebuilt from documents, not copied as files.

Threading: cluster-state application work (engine creation, recovery,
started-reports) runs on a dedicated applier executor so the cluster
update thread never blocks on itself (the reference uses the `generic`
pool for exactly this).
"""

from __future__ import annotations

import itertools
import logging
import threading
from concurrent.futures import ThreadPoolExecutor, wait
from dataclasses import replace

from .cluster_node import ClusterNode
from .routing import shard_id as route_shard
from .state import ClusterState, IndexMetadata, ShardRouting, ShardState
from .transport import LocalHub, TransportError
from ..index.engine import Engine
from ..index.mapping import MapperService
from ..search.aggregations import parse_aggs
from ..search.controller import merge_shard_results
from ..utils.errors import (DocumentMissingError, ElasticsearchTpuError,
                            IndexNotFoundError, ShardFailedError,
                            ShardNotFoundError)
from ..utils.settings import Settings

logger = logging.getLogger("elasticsearch_tpu.datanode")

WRITE_PRIMARY_ACTION = "indices:data/write/shard[p]"
WRITE_REPLICA_ACTION = "indices:data/write/shard[r]"
SEARCH_QUERY_ACTION = "indices:data/read/search[query]"
GET_ACTION = "indices:data/read/get"
RECOVERY_ACTION = "internal:index/shard/recovery/docs"
REFRESH_ACTION = "indices:admin/refresh[shard]"
SNAPSHOT_SHARD_ACTION = "internal:snapshot/shard"
SHARD_STATS_ACTION = "internal:indices/stats/shard"
SEGMENTS_ACTION = "internal:indices/segments/shard"
CACHE_CLEAR_ACTION = "internal:indices/cache/clear"
NODE_STATS_ACTION = "internal:cluster/nodes/stats"
HOT_THREADS_ACTION = "internal:cluster/nodes/hot_threads"


class WriteConsistencyError(ElasticsearchTpuError):
    status = 503


class DataNode(ClusterNode):
    """A master-eligible data node carrying real shard engines."""

    def __init__(self, node_id: str, hub: LocalHub, *,
                 data_path: str | None = None, **kw):
        super().__init__(node_id, hub, **kw)
        self.data_path = data_path
        self.gateway = None
        self._gateway_meta = None
        if data_path:
            from .gateway import GatewayMetaState
            from .state import STATE_NOT_RECOVERED_BLOCK
            import os
            os.makedirs(data_path, exist_ok=True)
            self.gateway = GatewayMetaState(data_path)
            # read BEFORE any state change can trigger write-on-change —
            # an empty post-election state must not clobber the saved
            # metadata (ref: GatewayService recovers before persisting)
            self._gateway_meta = self.gateway.load()

            def _persist(prev, new):
                if not new.blocks.has_global_block(STATE_NOT_RECOVERED_BLOCK):
                    self.gateway.persist(new)
            self.cluster.add_listener(_persist)
        self.engines: dict[tuple[str, int], Engine] = {}
        self.mappers: dict[str, MapperService] = {}
        # (index, shard) copies whose corrupt local files were wiped
        # before peer recovery — counted under
        # `peer_recoveries_after_corruption` once the stream lands
        self._wiped_corrupt: set[tuple[str, int]] = set()
        # corrupt copies already reported SHARD_FAILED once: when the
        # master hands the same corrupt PRIMARY back (nothing to
        # promote), reporting again would cycle fail→reallocate
        # forever — the copy stays contained (structured 503s, shard
        # red) until the marker clears or a peer copy appears
        self._corrupt_reported: set[tuple[str, int]] = set()
        self._local_states: dict[tuple[str, int], str] = {}
        # allocation id each local copy was recovered under — a NEW id
        # for the same (index, shard) means the master rebuilt the copy
        # after a failure, so it must re-recover (ref: AllocationId)
        self._local_aids: dict[tuple[str, int], str | None] = {}
        self._engines_lock = threading.RLock()
        self._applier = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"applier-{node_id}")
        self._rr = itertools.count()  # round-robin copy rotation
        self._scrolls: dict[str, dict] = {}  # distributed scroll contexts

        t = self.transport
        t.register_handler(WRITE_PRIMARY_ACTION, self._on_write_primary)
        t.register_handler(WRITE_REPLICA_ACTION, self._on_write_replica)
        t.register_handler(SEARCH_QUERY_ACTION, self._on_search_query)
        t.register_handler(GET_ACTION, self._on_get)
        t.register_handler(RECOVERY_ACTION, self._on_recovery_docs)
        t.register_handler(REFRESH_ACTION, self._on_refresh_shard)
        t.register_handler(SNAPSHOT_SHARD_ACTION, self._on_snapshot_shard)
        t.register_handler(SHARD_STATS_ACTION, self._on_shard_stats)
        t.register_handler(SEGMENTS_ACTION, self._on_shard_segments)
        t.register_handler(CACHE_CLEAR_ACTION, self._on_cache_clear)
        t.register_handler(NODE_STATS_ACTION, self._on_node_stats)
        t.register_handler(HOT_THREADS_ACTION, self._on_hot_threads)
        self.cluster.add_listener(self._cluster_changed)

    # ------------------------------------------------------------------
    # cluster state application (IndicesClusterStateService analog)
    # ------------------------------------------------------------------

    def _cluster_changed(self, prev: ClusterState, new: ClusterState) -> None:
        """Runs ON the cluster-service update thread: the LOCAL part of
        state application (shard removal, mapping sync, engine creation)
        happens synchronously so the publish ack the master waits on
        covers it — a state that says "moved away" is never acked while
        the source engine is still registered (ref:
        IndicesClusterStateService.clusterChanged applying removals
        before the publish round completes). Recovery streaming and
        master reports do transport work, so they go to the applier
        executor (report_shard_started on this thread would deadlock a
        master reporting to itself)."""
        try:
            to_finish = self._apply_state_sync(new)
        except Exception:
            logger.exception("[%s] state application failed",
                             self.node.node_id)
            return
        if to_finish:
            self._applier.submit(self._finish_recoveries, to_finish, new)

    def _apply_state_sync(self, state: ClusterState) -> list:
        my_id = self.node.node_id
        # remove local shards that are no longer assigned here
        with self._engines_lock:
            for key in list(self.engines):
                index, sid = key
                still = any(s for s in state.routing_table.all_shards()
                            if s.index == index and s.shard == sid
                            and s.node_id == my_id)
                if not still or state.metadata.index(index) is None:
                    eng = self.engines.pop(key)
                    self._local_states.pop(key, None)
                    self._local_aids.pop(key, None)
                    eng.close()
        # sync mappings from metadata (master is the authority)
        for name, imd in state.metadata.indices.items():
            mapper = self.mappers.get(name)
            if mapper is not None and imd.mappings:
                mapper.merge_mapping(dict(imd.mappings))
        # create newly assigned copies; recovery finishes on the applier
        to_finish = []
        for s in state.routing_table.all_shards():
            if s.node_id != my_id or s.state != ShardState.INITIALIZING:
                continue
            key = (s.index, s.shard)
            imd = state.metadata.index(s.index)
            if imd is None:
                continue
            with self._engines_lock:
                if self._local_states.get(key) in ("recovering",
                                                   "started"):
                    if self._local_aids.get(key) == s.allocation_id:
                        continue
                    # same shard, NEW allocation: the master failed
                    # and rebuilt this copy — drop the stale engine
                    # and recover fresh
                    old = self.engines.pop(key, None)
                    if old is not None:
                        old.close()
                self._local_states[key] = "recovering"
                self._local_aids[key] = s.allocation_id
            try:
                eng = self._create_engine(s.index, s.shard, imd,
                                          wipe_corrupt=not s.primary)
                # register BEFORE recovery so in-flight writes fan
                # out here while the doc stream runs; versioned
                # apply_replicated converges stream vs live writes
                # (ref: RecoverySourceHandler phase2 translog replay
                # racing ongoing ops — same convergence rule)
                with self._engines_lock:
                    prev = self.engines.get(key)
                    self.engines[key] = eng
                if prev is not None and prev is not eng:
                    prev.close()
                if eng.failed is not None:
                    # corrupt local copy CONTAINED (ISSUE 15): it stays
                    # registered — reads answer structured 503s, never
                    # a wedged node — and is reported SHARD_FAILED so
                    # allocation promotes/re-sources a surviving copy;
                    # the re-allocation arrives under a fresh
                    # allocation id and (as a replica) wipes the
                    # corrupt files before peer recovery heals it.
                    # Reported at most ONCE per copy: when the master
                    # hands the same corrupt primary straight back (no
                    # surviving copy to promote), a second report
                    # would cycle fail→reallocate forever — the copy
                    # instead settles contained-and-red until the
                    # marker clears
                    logger.warning(
                        "[%s] local copy of [%s][%d] is corrupt "
                        "(contained): %s", my_id, s.index, s.shard,
                        eng.failed["reason"])
                    if key in self._corrupt_reported:
                        continue
                    self._corrupt_reported.add(key)
                    with self._engines_lock:
                        self._local_states.pop(key, None)
                    to_finish.append(
                        replace(s, state=ShardState.UNASSIGNED))
                else:
                    self._corrupt_reported.discard(key)
                    to_finish.append(s)
            except Exception:
                logger.exception("[%s] engine creation for [%s][%d] failed",
                                 my_id, s.index, s.shard)
                with self._engines_lock:
                    self._local_states.pop(key, None)
                to_finish.append(replace(s, state=ShardState.UNASSIGNED))
        return to_finish

    def _finish_recoveries(self, shards: list, state: ClusterState) -> None:
        """Applier half of state application: stream docs from the
        primary, flip to started, report to the master."""
        my_id = self.node.node_id
        for s in shards:
            key = (s.index, s.shard)
            if s.state == ShardState.UNASSIGNED:  # creation failed above
                try:
                    self.discovery.report_shard_failed(
                        replace(s, state=ShardState.INITIALIZING))
                except TransportError:
                    pass
                continue
            with self._engines_lock:
                eng = self.engines.get(key)
                stale = (eng is None
                         or self._local_aids.get(key) != s.allocation_id
                         or self._local_states.get(key) != "recovering")
            if stale:
                continue  # a newer state already superseded this copy
            try:
                if not s.primary:
                    self._recover_from_primary(eng, s, state)
                    if key in self._wiped_corrupt:
                        # a corrupt copy healed from a surviving peer —
                        # the end-to-end arc the containment exists for
                        self._wiped_corrupt.discard(key)
                        from ..index import durability
                        durability.on_peer_recovery_after_corruption()
                with self._engines_lock:
                    self._local_states[key] = "started"
                self.discovery.report_shard_started(s)
            except Exception:
                # a newer state may have superseded this copy mid-stream
                # (sync half closed our engine and registered a NEW
                # allocation under the same key): tearing down or
                # reporting failure then would destroy the new copy, so
                # only clean up when the registration is still OURS
                with self._engines_lock:
                    ours = self._local_aids.get(key) == s.allocation_id
                    if ours:
                        self._local_states.pop(key, None)
                        bad = self.engines.pop(key, None)
                    else:
                        bad = None
                if not ours:
                    logger.info("[%s] recovery of [%s][%d] aborted: "
                                "allocation superseded", my_id, s.index,
                                s.shard)
                    continue
                logger.exception("[%s] recovery of [%s][%d] failed",
                                 my_id, s.index, s.shard)
                if bad is not None:
                    bad.close()
                try:
                    self.discovery.report_shard_failed(s)
                except TransportError:
                    pass

    def _create_engine(self, index: str, sid: int, imd: IndexMetadata,
                       wipe_corrupt: bool = False) -> Engine:
        mapper = self.mappers.get(index)
        if mapper is None:
            settings = Settings(dict(imd.settings))
            mapping = dict(imd.mappings) if imd.mappings else None
            if mapping and "properties" not in mapping:
                first = next(iter(mapping.values()), None)
                if isinstance(first, dict) and "properties" in first:
                    mapping = first
            mapper = MapperService(settings, mapping)
            self.mappers[index] = mapper
        path = None
        if self.data_path:
            import os
            path = os.path.join(self.data_path, index, str(sid))
            os.makedirs(path, exist_ok=True)
            if wipe_corrupt:
                # REPLICA allocations re-converge from the primary's
                # doc stream, so a corrupt local copy is advisory-only:
                # verify before opening and wipe on damage — one round
                # of peer recovery heals instead of two (fail, report,
                # re-allocate). NEVER done for a primary: its local
                # store may be the only copy of the data
                self._maybe_wipe_corrupt(index, sid, path)
        eng = Engine(index, sid, mapper, path=path,
                     settings=Settings(dict(imd.settings)))
        # runtime containment callback (a failed flush, an external
        # verify): report to the master OFF the failing thread so
        # allocation promotes a surviving copy (ref: IndexShard
        # failShard -> ShardStateAction)
        eng.on_failed = lambda _e, i=index, s=sid: self._applier.submit(
            self._report_engine_failed, i, s)
        return eng

    def _maybe_wipe_corrupt(self, index: str, sid: int,
                            path: str) -> None:
        import os
        import shutil
        from ..index.store import Store
        if not os.path.isdir(os.path.join(path, "store")):
            return
        st = Store(path, index=index, shard=sid)
        if st.corruption_marker() is None \
                and st.verify_integrity()["clean"]:
            return
        logger.warning("[%s] wiping corrupt local copy of [%s][%d] "
                       "before peer recovery", self.node.node_id,
                       index, sid)
        shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)
        self._wiped_corrupt.add((index, sid))

    def _report_engine_failed(self, index: str, sid: int) -> None:
        """Report OUR copy of (index, sid) failed to the master."""
        tbl = self.state.routing_table.index(index)
        if tbl is None or not 0 <= sid < len(tbl.shards):
            return
        copy = next((c for c in tbl.shard(sid).copies
                     if c.node_id == self.node.node_id), None)
        if copy is None:
            return
        try:
            self.discovery.report_shard_failed(copy)
        except TransportError:
            logger.warning("[%s] could not report corrupt shard "
                           "[%s][%d]", self.node.node_id, index, sid)

    def _recover_from_primary(self, eng: Engine, shard: ShardRouting,
                              state: ClusterState) -> None:
        """Pull the primary's live-doc stream (peer recovery)."""
        tbl = state.routing_table.index(shard.index)
        primary = tbl.shard(shard.shard).primary if tbl else None
        if primary is None or not primary.active or primary.node_id is None:
            return
        if primary.node_id == self.node.node_id:
            return
        resp = self.transport.send_request(
            primary.node_id, RECOVERY_ACTION,
            {"index": shard.index, "shard": shard.shard}, timeout=30.0)
        for doc_id, version, source in resp["docs"]:
            eng.apply_replicated(doc_id, source, version)
        eng.refresh()

    def _on_recovery_docs(self, src: str, req: dict) -> dict:
        eng = self._engine(req["index"], req["shard"])
        return {"docs": eng.snapshot_docs()}

    # ------------------------------------------------------------------
    # cluster-coordinated snapshot/restore (ref: snapshots/
    # SnapshotsService.java:75-87 — the coordinator records intent, each
    # shard's PRIMARY uploads its data to the shared repository, and the
    # coordinator finalizes the manifest; restore replays through the
    # normal replicated write path so replicas rebuild for free)
    # ------------------------------------------------------------------

    def _on_snapshot_shard(self, src: str, req: dict) -> dict:
        """Shard-level snapshot work, executed on the node holding the
        primary (ref: SnapshotShardsService.snapshot): serialize the
        live doc stream, content-address it, upload if new."""
        from ..snapshots import FsRepository, upload_shard
        eng = self._engine(req["index"], req["shard"])
        digest, uploaded = upload_shard(FsRepository(req["location"]),
                                        eng.snapshot_docs())
        return {"digest": digest, "uploaded": uploaded}

    # ------------------------------------------------------------------
    # cluster-wide broadcast / nodes-level admin ops
    # (ref: action/support/broadcast/TransportBroadcastOperationAction
    #  + support/nodes/TransportNodesOperationAction — every node
    #  contributes its local truth; the coordinator merges)
    # ------------------------------------------------------------------

    def _on_shard_stats(self, src: str, req: dict) -> dict:
        out = {}
        with self._engines_lock:
            engines = dict(self.engines)
        for (index, sid), eng in engines.items():
            st = eng.segment_stats()
            out[f"{index}:{sid}"] = {
                "docs": eng.doc_count(),
                "segments_count": st["count"],
                "memory_in_bytes": st["memory_in_bytes"],
                "buffered_docs": st["buffered_docs"],
            }
        return {"node": self.node.node_id, "shards": out}

    def _on_shard_segments(self, src: str, req: dict) -> dict:
        """Per-shard segment detail (ref: TransportIndicesSegmentsAction
        shard-level response). The index filter is pushed down so nodes
        never serialize segment metadata the coordinator would drop."""
        want = req.get("index")
        out = {}
        with self._engines_lock:
            engines = dict(self.engines)
        for (index, sid), eng in engines.items():
            if want is not None and index != want:
                continue
            segs = []
            with eng._lock:
                for s in eng.segments:
                    segs.append({
                        "name": s.seg_id,
                        "num_docs": int(s.num_docs),
                        "deleted_docs": int(
                            s.num_docs
                            - eng.live[s.seg_id][: s.num_docs].sum()),
                        "memory_in_bytes": int(s.nbytes()),
                    })
            out[f"{index}:{sid}"] = segs
        return {"node": self.node.node_id, "shards": out}

    def _on_cache_clear(self, src: str, req: dict) -> dict:
        """Drop request-scoped caches on this node's engines (ref:
        TransportClearIndicesCacheAction shard operation). Invalidates
        the cached reader — request-cache entries and micro-batchers
        are reader-scoped and die with it — WITHOUT a refresh (cache
        clear must never change document visibility)."""
        index = req.get("index")
        cleared = 0
        with self._engines_lock:
            engines = dict(self.engines)
        for (idx, _sid), eng in engines.items():
            if index is not None and idx != index:
                continue
            eng.invalidate_reader()
            cleared += 1
        return {"node": self.node.node_id, "cleared_shards": cleared}

    def _assigned_copies(self, index: str | None) -> int:
        """Assigned shard copies for `index` (or all) from the routing
        table — the broadcast ops' true _shards.total, so copies on
        unreachable nodes count as FAILED, not as absent."""
        return sum(1 for s in self.state.routing_table.all_shards()
                   if s.assigned
                   and (index is None or s.index == index))

    def cluster_segments(self, index: str | None = None) -> dict:
        """Cluster-wide `_segments`: every data node reports its shard
        engines' segment lists (ref:
        TransportIndicesSegmentsAction merge)."""
        results, _failed = self._fan_out_nodes(
            SEGMENTS_ACTION, {"index": index} if index else {},
            data_only=True)
        indices: dict[str, dict] = {}
        n_ok = 0
        for nid, resp in results.items():
            for key, segs in resp["shards"].items():
                idx, sid = key.rsplit(":", 1)
                n_ok += 1
                indices.setdefault(idx, {"shards": {}})[
                    "shards"].setdefault(sid, []).append(
                        {"routing": {"node": nid}, "segments": segs})
        total = self._assigned_copies(index)
        return {"_shards": {"total": total, "successful": n_ok,
                            "failed": max(total - n_ok, 0)},
                "indices": indices}

    def cluster_cache_clear(self, index: str | None = None) -> dict:
        results, _failed = self._fan_out_nodes(
            CACHE_CLEAR_ACTION, {"index": index} if index else {},
            data_only=True)
        n_ok = sum(r["cleared_shards"] for r in results.values())
        total = self._assigned_copies(index)
        return {"_shards": {"total": total, "successful": n_ok,
                            "failed": max(total - n_ok, 0)}}

    def _on_node_stats(self, src: str, req: dict) -> dict:
        from ..utils import monitor
        return {"node": self.node.node_id,
                "name": self.node.name,
                "os": monitor.os_stats(),
                "process": monitor.process_stats(),
                "runtime": monitor.runtime_stats(),
                "shard_count": len(self.engines)}

    def _on_hot_threads(self, src: str, req: dict) -> dict:
        from ..utils.monitor import hot_threads
        return {"node": self.node.node_id,
                "text": hot_threads(int(req.get("threads", 3)),
                                    int(req.get("interval_ms", 100)))}

    _LOCAL_HANDLERS = {SHARD_STATS_ACTION: "_on_shard_stats",
                       SEGMENTS_ACTION: "_on_shard_segments",
                       CACHE_CLEAR_ACTION: "_on_cache_clear",
                       NODE_STATS_ACTION: "_on_node_stats",
                       HOT_THREADS_ACTION: "_on_hot_threads"}

    def _fan_out_nodes(self, action: str, req: dict | None = None,
                       data_only: bool = False, timeout: float = 15.0
                       ) -> tuple[dict, list[str]]:
        """Dispatch to every (data) node incl. self, collect responses.
        Unreachable nodes are reported, not fatal — partial stats beat
        no stats (the reference's per-node failures array)."""
        state = self.state
        targets = (state.nodes.data_nodes if data_only
                   else state.nodes.nodes)
        futures = {}
        for nid in targets:
            if nid == self.node.node_id:
                continue
            futures[nid] = self.transport.submit_request(
                nid, action, req or {})
        results = {}
        if self.node.node_id in targets:
            handler = getattr(self, self._LOCAL_HANDLERS[action])
            results[self.node.node_id] = handler(self.node.node_id,
                                                 req or {})
        failed = []
        for nid, f in futures.items():
            try:
                results[nid] = f.result(timeout=timeout)
            except Exception:
                failed.append(nid)
        return results, failed

    def cluster_indices_stats(self, index: str | None = None) -> dict:
        """The whole cluster's `_stats` truth: every data node reports
        its shard engines; the coordinator splits primaries vs total
        using the routing table."""
        results, failed = self._fan_out_nodes(SHARD_STATS_ACTION,
                                              data_only=True)
        state = self.state

        def is_primary(idx: str, sid: int, nid: str) -> bool:
            tbl = state.routing_table.index(idx)
            if tbl is None or not 0 <= sid < len(tbl.shards):
                return False
            return any(c.node_id == nid and c.primary
                       for c in tbl.shard(sid).copies)

        indices: dict[str, dict] = {}
        zero = lambda: {"docs": {"count": 0},  # noqa: E731
                        "segments": {"count": 0, "memory_in_bytes": 0}}
        all_primaries, all_total = zero(), zero()
        n_shards = 0
        for nid, resp in results.items():
            for key, st in resp["shards"].items():
                idx, sid = key.rsplit(":", 1)
                if index is not None and idx != index:
                    continue
                n_shards += 1
                entry = indices.setdefault(
                    idx, {"primaries": zero(), "total": zero()})
                for scope in ([entry["total"], all_total]
                              + ([entry["primaries"], all_primaries]
                                 if is_primary(idx, int(sid), nid)
                                 else [])):
                    scope["docs"]["count"] += st["docs"]
                    scope["segments"]["count"] += st["segments_count"]
                    scope["segments"]["memory_in_bytes"] += \
                        st["memory_in_bytes"]
        # _shards.total comes from the routing table, like the sibling
        # cluster_segments/cluster_cache_clear broadcasts: copies on
        # unreachable nodes count as FAILED, so a caller comparing
        # successful to total detects partial results; the node-failure
        # list rides separately
        total = self._assigned_copies(index)
        return {
            "_shards": {"total": total, "successful": n_shards,
                        "failed": max(total - n_shards, 0),
                        **({"failures": failed} if failed else {})},
            "_all": {"primaries": all_primaries, "total": all_total},
            "indices": indices,
        }

    def cluster_nodes_stats(self) -> dict:
        results, failed = self._fan_out_nodes(NODE_STATS_ACTION)
        return {"cluster_name": getattr(self.discovery, "cluster_name",
                                        "elasticsearch"),
                "nodes": results,
                **({"failures": failed} if failed else {})}

    def cluster_hot_threads(self, threads: int = 3,
                            interval_ms: int = 100) -> str:
        results, _failed = self._fan_out_nodes(
            HOT_THREADS_ACTION,
            {"threads": threads, "interval_ms": interval_ms})
        parts = []
        for nid in sorted(results):
            parts.append(f"::: {{{nid}}}\n{results[nid]['text']}")
        return "\n".join(parts)

    def cluster_snapshot(self, location: str, snap_name: str,
                         indices: str | None = None) -> dict:
        """Coordinate a snapshot of every (selected) index across the
        cluster into a shared fs repository. Runs on any node."""
        import time as _time
        from ..snapshots import (FsRepository, assert_snapshot_absent,
                                 finalize_snapshot)
        repo = FsRepository(location)
        assert_snapshot_absent(repo, snap_name)
        state = self.state
        wanted = None if indices in (None, "", "_all", "*") else {
            i.strip() for i in str(indices).split(",")}
        if wanted is not None:
            missing = wanted - set(state.metadata.indices)
            if missing:
                raise IndexNotFoundError(",".join(sorted(missing)))
        manifest: dict = {"snapshot": snap_name, "state": "SUCCESS",
                          "start_time_ms": int(_time.time() * 1000),
                          "indices": {}}
        # mark the shards under snapshot in cluster state so the
        # SnapshotInProgressDecider pins their primaries for the
        # duration (ref: SnapshotsInProgress custom +
        # SnapshotInProgressAllocationDecider)
        snap_keys = sorted(
            f"{name}:{sid}"
            for name, imd in state.metadata.indices.items()
            if wanted is None or name in wanted
            for sid in range(imd.number_of_shards))
        self._update_snapshot_marker(add=snap_keys)
        try:
            return self._cluster_snapshot_inner(
                repo, snap_name, state, wanted, manifest, location)
        finally:
            self._update_snapshot_marker(remove=snap_keys)

    def _update_snapshot_marker(self, add: list[str] = (),
                                remove: list[str] = ()) -> None:
        """Merge-update the in-progress shard pins: concurrent snapshots
        UNION their keys and each removes only its own, so one snapshot
        finishing never unpins another's streaming primaries.

        Each pin carries this coordinator's node id
        ("index:shard@node") so master failover / node-leave can prune
        pins whose owner died mid-snapshot
        (allocation.prune_stale_snapshot_pins) — the reference's
        SnapshotsInProgress is master-owned and cleaned up the same
        way. A FAILED pin update on the add path ABORTS the snapshot
        (raises) instead of proceeding unpinned: streaming primaries
        that the allocator is free to move defeat the whole guard."""
        from dataclasses import replace as _replace
        from .allocation import SNAPSHOT_IN_PROGRESS_SETTING
        owner = self.node.node_id
        add_keys = {f"{k}@{owner}" for k in add}
        remove_keys = {f"{k}@{owner}" for k in remove}

        def task(cur: ClusterState) -> ClusterState:
            tr = dict(cur.metadata.transient_settings)
            keys = {k for k in str(
                tr.get(SNAPSHOT_IN_PROGRESS_SETTING, "")).split(",") if k}
            keys |= add_keys
            keys -= remove_keys
            if keys:
                tr[SNAPSHOT_IN_PROGRESS_SETTING] = ",".join(sorted(keys))
            else:
                tr.pop(SNAPSHOT_IN_PROGRESS_SETTING, None)
            md = _replace(cur.metadata, transient_settings=tr,
                          version=cur.metadata.version + 1)
            return cur.bump(metadata=md)
        try:
            self.cluster.submit_state_update_task(
                "snapshot-marker", task).result(10)
        except Exception as e:
            if add:
                err = ElasticsearchTpuError(
                    "failed to pin shards for snapshot (cluster state "
                    "update rejected); aborting instead of snapshotting "
                    "unpinned")
                err.status = 503
                raise err from e
            # removal best-effort: the pins name this (live) owner, so
            # they are re-pruned on the next membership change at worst
            logger.warning("[%s] snapshot marker removal failed",
                           self.node.node_id, exc_info=True)

    def _cluster_snapshot_inner(self, repo, snap_name: str,
                                state: ClusterState, wanted,
                                manifest: dict, location: str) -> dict:
        import time as _time
        from ..snapshots import finalize_snapshot
        n_uploaded = n_reused = 0
        for name, imd in sorted(state.metadata.indices.items()):
            if wanted is not None and name not in wanted:
                continue
            # the FULL index settings ride the manifest (analysis,
            # similarity, cache, merge, ...) — a restored index whose
            # mappings reference a custom analyzer must get it back
            # (ref: RestoreService restores whole IndexMetaData)
            entry = {"settings": {
                **dict(imd.settings or {}),
                "index.number_of_shards": imd.number_of_shards,
                "index.number_of_replicas": imd.number_of_replicas},
                "mappings": dict(imd.mappings or {}),
                "shards": {}}
            tbl = state.routing_table.index(name)
            for sid in range(imd.number_of_shards):
                primary = tbl.shard(sid).primary if tbl else None
                if primary is None or not primary.active \
                        or primary.node_id is None:
                    raise ShardNotFoundError(name, sid)
                req = {"index": name, "shard": sid, "location": location}
                if primary.node_id == self.node.node_id:
                    r = self._on_snapshot_shard(self.node.node_id, req)
                else:
                    r = self.transport.send_request(
                        primary.node_id, SNAPSHOT_SHARD_ACTION, req,
                        timeout=60.0)
                entry["shards"][str(sid)] = r["digest"]
                if r.get("uploaded"):
                    n_uploaded += 1
                else:
                    n_reused += 1
            manifest["indices"][name] = entry
        manifest["end_time_ms"] = int(_time.time() * 1000)
        finalize_snapshot(repo, snap_name, manifest)
        return {"snapshot": {"snapshot": snap_name, "state": "SUCCESS",
                             "indices": sorted(manifest["indices"]),
                             "shards_uploaded": n_uploaded,
                             "shards_reused": n_reused}}

    def cluster_restore(self, location: str, snap_name: str,
                        indices: str | None = None,
                        wait_seconds: float = 15.0) -> dict:
        """Restore snapshot indices across the cluster: recreate each
        index through the master metadata path, then replay the doc
        stream through the replicated write path (so every copy —
        replicas included — rebuilds consistently; ref:
        RestoreService.restoreSnapshot)."""
        import json as _json
        from ..snapshots import (FsRepository, SnapshotMissingError,
                                 _deserialize_shard)
        from ..utils.errors import IndexAlreadyExistsError
        repo = FsRepository(location)
        if snap_name not in repo.list_snapshots():
            raise SnapshotMissingError(f"[{snap_name}] missing")
        manifest = _json.loads(
            repo.read_blob(f"snap-{snap_name}.json").decode())
        wanted = None if indices in (None, "", "_all", "*") else {
            i.strip() for i in str(indices).split(",")}
        if wanted is not None:
            missing = wanted - set(manifest["indices"])
            if missing:
                raise SnapshotMissingError(
                    f"indices [{','.join(sorted(missing))}] not in "
                    f"snapshot [{snap_name}]")
        restored = []
        for name, entry in sorted(manifest["indices"].items()):
            if wanted is not None and name not in wanted:
                continue
            if self.state.metadata.index(name) is not None:
                raise IndexAlreadyExistsError(name)
            extra = {k: v for k, v in entry["settings"].items()
                     if k not in ("index.number_of_shards",
                                  "index.number_of_replicas")}
            self.create_index(
                name,
                number_of_shards=int(
                    entry["settings"]["index.number_of_shards"]),
                number_of_replicas=int(
                    entry["settings"]["index.number_of_replicas"]),
                settings=extra or None,
                mappings=entry.get("mappings") or None)
            if not self._wait_index_green(name, timeout=wait_seconds):
                raise TransportError(
                    f"restore of [{name}] timed out waiting for "
                    f"shards to allocate")
            # replay each shard blob through the replicated BULK path,
            # ONE BLOB AT A TIME (peak memory stays one shard, not the
            # whole index); versions survive via external_gte (same ids
            # + same shard count means the router sends every doc back
            # to its original shard)
            for _sid, digest in sorted(entry["shards"].items()):
                docs = _deserialize_shard(
                    repo.read_blob(f"data/{digest}"))
                for start in range(0, len(docs), 500):
                    ops = [("index", {
                        "_index": name, "_id": doc_id, "doc": source,
                        "version": version,
                        "version_type": "external_gte"})
                        for doc_id, version, source
                        in docs[start: start + 500]]
                    r = self.bulk(ops)
                    if r.get("errors"):
                        bad = next(it for it in r["items"]
                                   if "error" in next(iter(it.values())))
                        raise TransportError(
                            f"restore of [{name}] failed: {bad}")
                del docs
            self.refresh_index(name)
            restored.append(name)
        return {"snapshot": {"snapshot": snap_name,
                             "indices": restored},
                "accepted": True}

    # ------------------------------------------------------------------
    # engines
    # ------------------------------------------------------------------

    def _recover_persisted_state(self) -> None:
        """Gateway recovery: the elected master restores persisted index
        metadata + fresh routing tables BEFORE the not-recovered block
        lifts (ref: gateway/GatewayService.java:94-95)."""
        meta = self._gateway_meta
        if self.gateway is None or not meta:
            return
        from .gateway import GatewayMetaState
        from .state import IndexRoutingTable
        from .service import HIGH

        def restore(cur):
            md = cur.metadata
            rt = cur.routing_table
            changed = False
            for imd in GatewayMetaState.to_index_metadata(meta):
                if md.index(imd.index) is None:
                    md = md.with_index(imd)
                    rt = rt.with_index(IndexRoutingTable.new(
                        imd.index, imd.number_of_shards,
                        imd.number_of_replicas))
                    changed = True
            templates = meta.get("templates") or {}
            if templates and templates != dict(md.templates):
                import dataclasses
                md = dataclasses.replace(
                    md, templates={**templates, **dict(md.templates)},
                    version=md.version + 1)
                changed = True
            if not changed:
                return cur
            return self.allocation.reroute(
                cur.bump(metadata=md, routing_table=rt))
        self.cluster.submit_state_update_task(
            "gateway-recovery", restore, HIGH).result(10)

    def _engine(self, index: str, sid: int) -> Engine:
        with self._engines_lock:
            eng = self.engines.get((index, sid))
        if eng is None:
            raise ShardNotFoundError(index, sid)
        return eng

    def wait_for_green(self, timeout: float = 10.0) -> bool:
        import time
        deadline = time.time() + timeout
        while time.time() < deadline:
            h = self.health()
            if h["status"] == "green":
                return True
            time.sleep(0.03)
        return False

    def _wait_index_green(self, index: str, timeout: float = 10.0) -> bool:
        """Green wait scoped to ONE index (ref: cluster health with an
        index target) — an unrelated yellow index elsewhere in the
        cluster must not fail operations on this one."""
        import time
        deadline = time.time() + timeout
        while time.time() < deadline:
            tbl = self.state.routing_table.index(index)
            if tbl is not None and all(
                    c.active for g in tbl.shards for c in g.copies):
                return True
            time.sleep(0.03)
        return False

    # ------------------------------------------------------------------
    # write path (replication template)
    # ------------------------------------------------------------------

    def index_doc(self, index: str, doc_id: str | None, body,
                  routing: str | None = None, refresh: bool = False) -> dict:
        if doc_id is None:
            import uuid
            doc_id = uuid.uuid4().hex[:20]
        return self._replicated_write(index, doc_id, {
            "op": "index", "id": doc_id, "source": body,
            "routing": routing, "refresh": refresh})

    def delete_doc(self, index: str, doc_id: str,
                   routing: str | None = None, refresh: bool = False) -> dict:
        return self._replicated_write(index, doc_id, {
            "op": "delete", "id": doc_id, "routing": routing,
            "refresh": refresh})

    def bulk(self, operations: list[tuple[str, dict]],
             refresh: bool = False) -> dict:
        """Group ops by (index, shard), send one primary request per shard.
        Ref: TransportBulkAction.executeBulk:123-157."""
        import time
        started = time.monotonic()
        groups: dict[tuple[str, int], list[tuple[int, dict]]] = {}
        items: list[dict | None] = [None] * len(operations)
        errors = False
        for i, (action, payload) in enumerate(operations):
            index = payload["_index"]
            doc_id = payload.get("_id")
            if doc_id is None:
                import uuid
                doc_id = uuid.uuid4().hex[:20]
            imd = self._index_meta(index, auto_create=True)
            sid = route_shard(doc_id, imd.number_of_shards,
                              payload.get("routing"))
            op = {"op": "delete" if action == "delete" else "index",
                  "id": doc_id, "source": payload.get("doc"),
                  "routing": payload.get("routing"), "_slot": i,
                  "_action": action}
            if payload.get("version") is not None:
                op["version"] = int(payload["version"])
                # same default as the REST layer and node.py: internal
                # CAS semantics unless the caller says otherwise
                op["version_type"] = payload.get("version_type",
                                                 "internal")
            groups.setdefault((index, sid), []).append((i, op))
        for (index, sid), ops in groups.items():
            try:
                resps = self._send_to_primary(index, sid, {
                    "index": index, "shard": sid, "refresh": refresh,
                    "ops": [o for _, o in ops]})["results"]
                for (i, op), r in zip(ops, resps):
                    action = op["_action"]
                    if "error" in r:
                        errors = True
                        items[i] = {action: {**r, "status": 400}}
                    else:
                        status = (200 if action in ("update", "delete")
                                  else (201 if r.get("created") else 200))
                        items[i] = {action: {**r, "_index": index,
                                             "status": status}}
            except ElasticsearchTpuError as e:
                errors = True
                for i, op in ops:
                    items[i] = {op["_action"]: {"error": e.to_dict(),
                                                "status": e.status}}
        return {"took": int((time.monotonic() - started) * 1000),
                "errors": errors, "items": items}

    def _index_meta(self, index: str, auto_create: bool = False) -> IndexMetadata:
        imd = self.state.metadata.index(index)
        if imd is None:
            if not auto_create:
                raise IndexNotFoundError(index)
            try:
                self.create_index(index)
            except ElasticsearchTpuError:
                pass  # concurrent create
            import time
            for _ in range(100):
                imd = self.state.metadata.index(index)
                if imd is not None:
                    return imd
                time.sleep(0.02)
            raise IndexNotFoundError(index)
        return imd

    def _replicated_write(self, index: str, doc_id: str, op: dict) -> dict:
        imd = self._index_meta(index, auto_create=op["op"] == "index")
        sid = route_shard(doc_id, imd.number_of_shards, op.get("routing"))
        resp = self._send_to_primary(index, sid, {
            "index": index, "shard": sid, "ops": [op],
            "refresh": op.get("refresh", False)})
        r = resp["results"][0]
        if "error" in r:
            err = ElasticsearchTpuError(r["error"].get("reason", "write failed"))
            err.status = r.get("status", 400)
            raise err
        return {**r, "_index": index}

    def _send_to_primary(self, index: str, sid: int, request: dict,
                         retries: int = 8) -> dict:
        """Route to the primary copy; retry on cluster-state movement
        (ref: TransportShardReplicationOperationAction:329-401)."""
        import time
        last: Exception | None = None
        for attempt in range(retries):
            tbl = self.state.routing_table.index(index)
            primary = tbl.shard(sid).primary if tbl and sid < len(tbl.shards) \
                else None
            if primary is None or not primary.active or primary.node_id is None:
                time.sleep(0.1)
                last = ShardNotFoundError(index, sid)
                continue
            try:
                if primary.node_id == self.node.node_id:
                    return self._on_write_primary(self.node.node_id, request)
                return self.transport.send_request(
                    primary.node_id, WRITE_PRIMARY_ACTION, request,
                    timeout=15.0)
            except (TransportError, ShardNotFoundError) as e:
                last = e
                time.sleep(0.1)
        raise last if last is not None else ShardNotFoundError(index, sid)

    def _write_consistency_ok(self, index: str, sid: int) -> bool:
        """Quorum write-consistency (ref: :124 — enforced when the shard
        group has more than one replica, like the reference's default)."""
        imd = self.state.metadata.index(index)
        tbl = self.state.routing_table.index(index)
        if imd is None or tbl is None:
            return False
        if imd.number_of_replicas <= 1:
            return True
        group = tbl.shard(sid)
        required = (1 + imd.number_of_replicas) // 2 + 1
        return len(group.active_copies) >= required

    def _check_block(self, level: str, index: str | None = None) -> None:
        """Ref: the action layer's checkGlobalBlock/checkRequestBlock."""
        from ..utils.errors import ClusterBlockError
        b = self.state.blocks.blocked(level, index)
        if b is not None:
            raise ClusterBlockError(b.description)

    def _on_write_primary(self, src: str, req: dict) -> dict:
        index, sid = req["index"], req["shard"]
        self._check_block("write", index)
        eng = self._engine(index, sid)
        if not self._write_consistency_ok(index, sid):
            raise WriteConsistencyError(
                f"not enough active shard copies for [{index}][{sid}]")
        n_fields_before = len(self.mappers[index].mapper.fields) \
            if index in self.mappers else 0
        results = []
        replica_ops = []
        for op in req["ops"]:
            try:
                if op["op"] == "delete":
                    r = eng.delete(op["id"],
                                   version=op.get("version"),
                                   version_type=op.get("version_type",
                                                       "internal"))
                else:
                    r = eng.index(op["id"], op["source"],
                                  version=op.get("version"),
                                  version_type=op.get("version_type",
                                                      "internal"))
                results.append(r)
                if "_version" not in r:
                    # delete of a missing doc: found=false, nothing to
                    # replicate (ref: TransportDeleteAction not-found)
                    continue
                replica_ops.append({"op": op["op"], "id": op["id"],
                                    "source": op.get("source"),
                                    "version": r["_version"]})
            except ElasticsearchTpuError as e:
                results.append({"_id": op["id"], "error": e.to_dict(),
                                "status": e.status})
        if req.get("refresh"):
            eng.refresh()
        # dynamic-mapping side channel to master (ref: MappingUpdatedAction)
        mapper = self.mappers.get(index)
        if mapper is not None and len(mapper.mapper.fields) > n_fields_before:
            try:
                self.put_mapping(index, mapper.mapping_dict())
            except TransportError:
                logger.warning("[%s] dynamic mapping update for [%s] failed",
                               self.node.node_id, index)
        # fan out to replicas (sync, ref :118-120) — INITIALIZING copies
        # receive in-flight writes too, closing the recovery lost-write
        # window (ref: RecoverySourceHandler phase2/3: ops that race the
        # doc stream must still reach the new copy)
        tbl = self.state.routing_table.index(index)
        if tbl is not None:
            futures = []
            for copy in tbl.shard(sid).replicas:
                if copy.node_id and copy.node_id != self.node.node_id \
                        and copy.state in (ShardState.STARTED,
                                           ShardState.INITIALIZING,
                                           ShardState.RELOCATING):
                    futures.append((copy, self.transport.submit_request(
                        copy.node_id, WRITE_REPLICA_ACTION,
                        {"index": index, "shard": sid, "ops": replica_ops,
                         "refresh": req.get("refresh", False)})))
            if futures:
                wait([f for _, f in futures], timeout=15.0)
                for copy, f in futures:
                    exc = f.exception() if f.done() else \
                        TimeoutError("replica write timed out")
                    if exc is None:
                        continue
                    logger.warning("[%s] replica write failed on %s: %s",
                                   self.node.node_id, copy.node_id, exc)
                    if copy.state == ShardState.INITIALIZING \
                            and isinstance(exc, ShardNotFoundError):
                        # the recovering node has not registered its
                        # engine yet, so its recovery SNAPSHOT (taken
                        # strictly after registration) will contain
                        # this op — the only safely skippable failure
                        continue
                    # any other failed copy is stale from now on:
                    # report SHARD_FAILED so the master unassigns and
                    # rebuilds it under a fresh allocation id (ref:
                    # ShardStateAction.java:56; a mid-recovery copy
                    # that MISSED a post-snapshot op must restart too)
                    try:
                        self.discovery.report_shard_failed(copy)
                    except TransportError:
                        logger.warning(
                            "[%s] could not report shard failure for "
                            "[%s][%d] on %s", self.node.node_id, index,
                            sid, copy.node_id)
        return {"results": results}

    def _on_write_replica(self, src: str, req: dict) -> dict:
        eng = self._engine(req["index"], req["shard"])
        for op in req["ops"]:
            eng.apply_replicated(op["id"], op.get("source"), op["version"],
                                 delete=op["op"] == "delete")
        if req.get("refresh"):
            eng.refresh()
        return {"ok": True}

    # ------------------------------------------------------------------
    # read path
    # ------------------------------------------------------------------

    def get_doc(self, index: str, doc_id: str,
                routing: str | None = None) -> dict:
        imd = self._index_meta(index)
        sid = route_shard(doc_id, imd.number_of_shards, routing)
        tbl = self.state.routing_table.index(index)
        group = tbl.shard(sid)
        # try copies in preference order: local first, then actives
        copies = sorted(group.active_copies,
                        key=lambda c: c.node_id != self.node.node_id)
        last: Exception | None = None
        for copy in copies:
            try:
                if copy.node_id == self.node.node_id:
                    return self._on_get(self.node.node_id,
                                        {"index": index, "shard": sid,
                                         "id": doc_id})
                return self.transport.send_request(
                    copy.node_id, GET_ACTION,
                    {"index": index, "shard": sid, "id": doc_id})
            except DocumentMissingError:
                raise
            except TransportError as e:
                last = e
        raise last if last is not None else ShardNotFoundError(index, sid)

    def _on_get(self, src: str, req: dict) -> dict:
        eng = self._engine(req["index"], req["shard"])
        r = eng.get(req["id"])
        import json
        return {"_index": req["index"], "_id": r["_id"],
                "_version": r["_version"], "found": True,
                "_source": json.loads(r["_source"])}

    @staticmethod
    def _parse_preference(preference: str | None
                          ) -> tuple[str | None, str | None, set | None]:
        """-> (kind, arg, shard_filter). Ref: Preference.java:31-61 —
        `_shards:1,3;_primary` combines a shard-group restriction with a
        copy preference via ';'."""
        if not preference:
            return None, None, None
        shard_filter = None
        rest = preference
        if rest.startswith("_shards:"):
            spec, _, tail = rest.partition(";")
            try:
                shard_filter = {int(x) for x in
                                spec[len("_shards:"):].split(",") if x}
            except ValueError:
                from ..utils.errors import IllegalArgumentError
                raise IllegalArgumentError(
                    f"invalid _shards preference [{preference}]") from None
            rest = tail
        if not rest:
            return None, None, shard_filter
        if rest.startswith("_only_node:"):
            return "_only_node", rest.split(":", 1)[1], shard_filter
        if rest.startswith("_prefer_node:"):
            return "_prefer_node", rest.split(":", 1)[1], shard_filter
        if rest in ("_local", "_primary", "_primary_first", "_replica",
                    "_replica_first"):
            return rest, None, shard_filter
        return "_custom", rest, shard_filter

    def _select_copy(self, group, rr: int, kind: str | None,
                     arg: str | None):
        """One copy of a shard group per the preference (ref:
        OperationRouting.java:144-163 preferenceActiveShardIterator)."""
        actives = [c for c in group.active_copies if c.node_id]
        if not actives:
            return None
        my_id = self.node.node_id
        if kind is None or kind == "_local":
            local = [c for c in actives if c.node_id == my_id]
            return local[0] if local else actives[rr % len(actives)]
        if kind == "_primary":
            return next((c for c in actives if c.primary), None)
        if kind == "_primary_first":
            return next((c for c in actives if c.primary),
                        actives[rr % len(actives)])
        if kind == "_replica":
            reps = [c for c in actives if not c.primary]
            return reps[rr % len(reps)] if reps else None
        if kind == "_replica_first":
            reps = [c for c in actives if not c.primary]
            return (reps[rr % len(reps)] if reps
                    else actives[rr % len(actives)])
        if kind == "_only_node":
            return next((c for c in actives if c.node_id == arg), None)
        if kind == "_prefer_node":
            return next((c for c in actives if c.node_id == arg),
                        actives[rr % len(actives)])
        # custom string: deterministic rotation (same string -> same
        # copy, the session-affinity use case)
        from .routing import djb_hash
        return actives[djb_hash(str(arg)) % len(actives)]

    def search(self, index: str | None, body: dict | None = None,
               preference: str | None = None,
               scroll: str | None = None) -> dict:
        """Scatter to one active copy per shard group, gather, reduce.
        Ref: TransportSearchTypeAction.BaseAsyncAction:126-153."""
        body = body or {}
        names = self._resolve_index_names(index)
        agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations"))
        from ..search.suggest import parse_suggest, merge_suggests
        suggest_specs = parse_suggest(body.get("suggest"))
        frm = int(body.get("from", 0))
        size = int(body.get("size", 10))
        shard_body = dict(body)
        shard_body["from"] = 0
        shard_body["size"] = frm + size

        responses, partials, suggest_parts, n_shards = \
            self._scatter_search(names, shard_body, preference)
        result = _reduce_search(responses, partials, suggest_parts,
                                n_shards, body, agg_specs, suggest_specs,
                                frm, size)
        return self._maybe_attach_scroll(result, index, body,
                                          preference, scroll, frm + size)

    def _scatter_search(self, names: list[str], shard_body: dict,
                        preference: str | None = None
                        ) -> tuple[list, list, list, int]:
        """The QUERY-phase scatter: one request per owning node covering
        its selected shard copies; returns (shard responses, keyed agg
        partials, suggest parts, shard count) for the caller's reduce —
        shared by single-cluster search and the tribe node's
        cross-cluster merge (partials are keyed by term/numeric value,
        so they meet across clusters exactly)."""
        pref_kind, pref_arg, shard_filter = self._parse_preference(
            preference)
        by_node: dict[str, list[tuple[str, int]]] = {}
        n_shards = 0
        rr = next(self._rr)
        for name in names:
            tbl = self.state.routing_table.index(name)
            if tbl is None:
                continue
            for g in tbl.shards:
                if shard_filter is not None and g.shard not in shard_filter:
                    continue
                n_shards += 1
                copy = self._select_copy(g, rr, pref_kind, pref_arg)
                if copy is None:
                    continue
                by_node.setdefault(copy.node_id, []).append((name, g.shard))
        if n_shards == 0:
            return [], [], [], 0
        futures = []
        for node_id, shards in by_node.items():
            req = {"shards": shards, "body": shard_body}
            if node_id == self.node.node_id:
                from concurrent.futures import Future
                f: Future = Future()
                try:
                    f.set_result(self._on_search_query(node_id, req))
                except Exception as e:  # noqa: BLE001
                    f.set_exception(e)
                futures.append(f)
            else:
                futures.append(self.transport.submit_request(
                    node_id, SEARCH_QUERY_ACTION, req))
        wait(futures, timeout=30.0)
        responses, partials, suggest_parts = [], [], []
        for f in futures:
            if f.done() and f.exception() is None:
                for shard_resp in f.result()["shards"]:
                    partials.append(shard_resp.pop("_agg_partials", {}))
                    if "suggest" in shard_resp:
                        suggest_parts.append(shard_resp.pop("suggest"))
                    responses.append(shard_resp)
        return responses, partials, suggest_parts, n_shards

    # (reduce lives at module level — _reduce_search — so the tribe
    # node's cross-cluster merge shares it verbatim)

    def _maybe_attach_scroll(self, result: dict, index, body: dict,
                             preference, scroll, pos: int) -> dict:
        if scroll is None:
            return result
        import time as _time
        import uuid as _uuid
        from ..utils.settings import parse_time_value
        sid = _uuid.uuid4().hex
        keep = parse_time_value(scroll, 60_000)
        self._reap_scrolls()
        self._scrolls[sid] = {
            "index": index, "body": dict(body),
            "preference": preference,
            "pos": pos, "keepalive_ms": keep,
            "expires_at": _time.time() + keep / 1000.0}
        result["_scroll_id"] = sid
        return result

    def scroll(self, scroll_id: str, scroll: str | None = None) -> dict:
        """Next scroll page on the DISTRIBUTED path. Deviation from the
        reference's pinned per-shard contexts: pages re-execute the
        fan-out with an advanced window, so each page costs
        O(pos + size) per shard and the TOTAL export is bounded by
        index.max_result_window (10000) — beyond that, per-shard
        cursors (pinned contexts / search_after) are the right tool and
        the Node-local scroll provides them. Pages are stable between
        refreshes (shard readers are refresh-point snapshots)."""
        import time as _time
        from ..utils.settings import parse_time_value
        from ..utils.errors import IllegalArgumentError
        self._reap_scrolls()
        ctx = self._scrolls.get(scroll_id)
        if ctx is None:
            err = ElasticsearchTpuError(
                f"No search context found for id [{scroll_id}]")
            err.status = 404
            raise err
        body = dict(ctx["body"])
        size = int(body.get("size", 10))
        if ctx["pos"] + size > 10_000:
            raise IllegalArgumentError(
                "distributed scroll window exceeds max_result_window "
                "(10000); use the node-local scroll for deep exports")
        body["from"] = ctx["pos"]
        if scroll is not None:
            ctx["keepalive_ms"] = parse_time_value(scroll, 60_000)
        ctx["expires_at"] = _time.time() + ctx["keepalive_ms"] / 1000.0
        result = self.search(ctx["index"], body,
                             preference=ctx.get("preference"))
        # advance ONLY after a successful page: a failed/retried page
        # must re-serve the same window, never silently skip it
        ctx["pos"] += size
        result["_scroll_id"] = scroll_id
        return result

    def clear_scroll(self, scroll_ids: list[str] | None = None) -> dict:
        if scroll_ids is None or scroll_ids == ["_all"]:
            n = len(self._scrolls)
            self._scrolls.clear()
        else:
            n = sum(1 for sid in scroll_ids
                    if self._scrolls.pop(sid, None) is not None)
        return {"succeeded": True, "num_freed": n}

    def _reap_scrolls(self) -> None:
        import time as _time
        now = _time.time()
        for sid in [s for s, c in self._scrolls.items()
                    if c["expires_at"] < now]:
            del self._scrolls[sid]

    def _on_search_query(self, src: str, req: dict) -> dict:
        out = []
        for index, sid in req["shards"]:
            try:
                eng = self._engine(index, sid)
                reader = eng.acquire_searcher()
                r = reader.msearch([req["body"]], with_partials=True)[0]
            except (ShardFailedError, ShardNotFoundError) as e:
                # contained (corrupt-failed) or just-removed copy: this
                # shard reduces as a structured failure, the rest of
                # the node's shards still answer
                out.append({"_failed": True, "index": index,
                            "shard": sid,
                            "status": getattr(e, "status", 503),
                            "error": {"type": type(e).__name__,
                                      "reason": str(e)}})
                continue
            out.append(r)
        return {"shards": out}

    def count(self, index: str | None, body: dict | None = None) -> dict:
        r = self.search(index, {"query": (body or {}).get("query"), "size": 0})
        return {"count": r["hits"]["total"], "_shards": r["_shards"]}

    def refresh_index(self, index: str | None = None) -> dict:
        """Fan a refresh to every active copy (broadcast template —
        ref: TransportBroadcastOperationAction)."""
        names = self._resolve_index_names(index)
        futures = []
        n = 0
        for name in names:
            tbl = self.state.routing_table.index(name)
            if tbl is None:
                continue
            for g in tbl.shards:
                for copy in g.active_copies:
                    n += 1
                    if copy.node_id == self.node.node_id:
                        self._on_refresh_shard(self.node.node_id,
                                               {"index": name,
                                                "shard": g.shard})
                    else:
                        futures.append(self.transport.submit_request(
                            copy.node_id, REFRESH_ACTION,
                            {"index": name, "shard": g.shard}))
        if futures:
            wait(futures, timeout=10.0)
        return {"_shards": {"total": n, "successful": n, "failed": 0}}

    def _on_refresh_shard(self, src: str, req: dict) -> dict:
        self._engine(req["index"], req["shard"]).refresh()
        return {"ok": True}

    def _resolve_index_names(self, index: str | None) -> list[str]:
        md = self.state.metadata
        if index in (None, "_all", "*", ""):
            return sorted(md.indices)
        out = []
        for n in str(index).split(","):
            n = n.strip()
            if "*" in n:
                import fnmatch
                out.extend(k for k in sorted(md.indices)
                           if fnmatch.fnmatch(k, n))
            elif md.index(n) is not None:
                out.append(n)
            else:
                raise IndexNotFoundError(n)
        return out

    # ------------------------------------------------------------------

    def close(self) -> None:
        self._applier.shutdown(wait=False, cancel_futures=True)
        with self._engines_lock:
            for eng in self.engines.values():
                eng.close()
            self.engines.clear()
        super().close()


class DataCluster:
    """N DataNodes over one LocalHub — the InternalTestCluster analog
    with real shards (ref: test/ElasticsearchIntegrationTest.java)."""

    def __init__(self, n_nodes: int = 3, *, min_master_nodes: int | None = None,
                 data_path: str | None = None,
                 cluster_name: str = "test-cluster"):
        self.hub = LocalHub()
        if min_master_nodes is None:
            min_master_nodes = n_nodes // 2 + 1
        self.nodes: dict[str, DataNode] = {}
        for i in range(n_nodes):
            nid = f"node-{i}"
            path = f"{data_path}/{nid}" if data_path else None
            self.nodes[nid] = DataNode(
                nid, self.hub, data_path=path,
                min_master_nodes=min_master_nodes,
                cluster_name=cluster_name)
        for nid in sorted(self.nodes):
            self.nodes[nid].join()

    @property
    def master(self) -> DataNode | None:
        for n in self.nodes.values():
            if n.is_master:
                return n
        return None

    def client(self) -> DataNode:
        """Any node can coordinate (every node is a coordinating node)."""
        return next(iter(self.nodes.values()))

    def wait_for_green(self, timeout: float = 15.0) -> bool:
        import time
        deadline = time.time() + timeout
        while time.time() < deadline:
            m = self.master
            if m is not None and m.health()["status"] == "green":
                return True
            time.sleep(0.05)
        return False

    def tick_all(self, rounds: int = 1) -> None:
        for _ in range(rounds):
            for n in list(self.nodes.values()):
                n.discovery.fd_tick()

    def stop_node(self, node_id: str) -> None:
        self.nodes.pop(node_id).close()

    def close(self) -> None:
        for n in self.nodes.values():
            n.close()
        self.nodes.clear()


def _reduce_search(responses, partials, suggest_parts, n_shards: int,
                   body: dict, agg_specs, suggest_specs,
                   frm: int, size: int) -> dict:
    """The QUERY-phase reduce shared by single-cluster search and the
    tribe node's cross-cluster merge (SearchPhaseController.merge)."""
    from ..search.suggest import merge_suggests
    if n_shards == 0:
        return merge_shard_results([], agg_specs, [], frm, size)
    # shard-level `_failed` placeholders (a contained corrupt shard, a
    # just-removed engine) become STRUCTURED failures entries — they
    # must count as failed, not ride in `responses` where the header
    # arithmetic below would count them successful
    failures = []
    clean = []
    for resp in responses:
        if resp.get("_failed"):
            failures.append({
                "shard": resp.get("shard"), "index": resp.get("index"),
                "status": resp.get("status", 503),
                "reason": resp.get("error")
                or {"type": "ShardFailure",
                    "reason": "shard did not respond"}})
        else:
            clean.append(resp)
    result = merge_shard_results(
        clean, agg_specs, partials, frm=frm, size=size,
        descending=_sort_descending(body),
        score_sort=_is_score_sort(body),
        total_shards=n_shards, failures=failures)
    # shards whose NODE never answered (transport failure) produced no
    # placeholder at all: failed is everything that isn't successful
    result["_shards"]["failed"] = (n_shards
                                   - result["_shards"]["successful"])
    if suggest_specs:
        result["suggest"] = merge_suggests(suggest_parts, suggest_specs)
    return result


def _is_score_sort(body: dict) -> bool:
    sort = body.get("sort")
    return sort in (None, [], "_score") or (
        isinstance(sort, list) and bool(sort) and sort[0] == "_score")


def _sort_descending(body: dict) -> bool:
    if _is_score_sort(body):
        return True
    sort = body.get("sort")
    entry = sort[0] if isinstance(sort, list) else sort
    if isinstance(entry, dict):
        spec = next(iter(entry.values()))
        order = (spec.get("order", "asc") if isinstance(spec, dict)
                 else str(spec))
        return order.lower() == "desc"
    return False
