"""Per-segment query execution: bind -> device program -> top-k + aggs.

Reference analog: search/query/QueryPhase.java:92-168 — the per-shard
Lucene execution (BulkScorer loop, TopScoreDocCollector, then
AggregationPhase collectors). Here the whole phase is ONE jitted device
program per (query structure, segment shape) pair:

    eval query AST  -> dense per-doc scores [B, cap] + match mask
    top-k           -> lax.top_k with Lucene-compatible tie-breaking
    aggregations    -> masked scatter-add bucket kernels

Two-step execution:
  * bind (host): resolve terms against the segment dictionary to block
    ranges / ordinals / bounds; produces a hashable static `desc` tree
    (compiled into the program) + dynamic param arrays (traced), so
    different terms with the same query SHAPE reuse the compiled program.
    Queries binding to the same desc can be batched (leading dim B).
  * eval (device): recursive desc interpreter building the XLA program.

Static shapes everywhere: posting-gather budgets and bucket counts are
padded to power-of-two buckets, so XLA compile count stays logarithmic.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..index.mapping import (MapperService, parse_date_millis, parse_ip,
                             MapperParsingError, DATE, BOOLEAN, IP)
from ..index.segment import (Segment, BLOCK, next_pow2, bm25_idf,
                             BM25_K1, BM25_B, POS_MAX_ENC)
from ..ops.scoring import (score_term, score_terms_fused,
                           score_topk_bundle_fused, bundle_tile_bounds,
                           match_mask_bundle_fused, bundle_primary_field,
                           BOUND_SLACK, positional_prefix, clause_fields,
                           bundle_text_fields, bundle_pos_fields,
                           positional_tile_scores, phrase_kind, span_kind,
                           bm25f_kind, parse_positional_kind)
from ..ops.knn import knn_score_column, SIMILARITIES as _KNN_SIMILARITIES
from ..ops.pallas_scoring import (pallas_enabled, interpret_mode,
                                  score_term_pallas,
                                  score_terms_fused_pallas,
                                  score_terms_dense_pallas,
                                  fused_topk_bundle_pallas,
                                  match_mask_bundle_pallas,
                                  resident_step_ok)
from ..ops.topk import top_k_hits, top_k_by_field
from ..ops import aggs as agg_ops
from ..utils.errors import (QueryParsingError, SearchParseError,
                            SearchTimeoutError)
from ..utils.profiler import annotate as _prof_annotate
from ..utils import trace_guard as _trace_guard
from . import resident as _resident
from .query_dsl import (
    Query, MatchAllQuery, MatchNoneQuery, TermQuery, RangeQuery, ExistsQuery,
    IdsQuery, PrefixQuery, WildcardQuery, FuzzyQuery, BoolQuery,
    ConstantScoreQuery, BoostingQuery, FunctionScoreQuery, ScoreFunction,
    ScriptQuery, GeoDistanceQuery, GeoBoundingBoxQuery, GeoPolygonQuery,
    GeoShapeQuery, ShapeTokensQuery, KnnQuery,
)

_F32_MIN_WEIGHT = 1e-30  # keeps score>0 as the match signal even at boost~0
_DENSE_GROUP_MAX = 8     # should-groups up to this many terms take the
                         # forward-index gather path instead of scatter
# fused positional clause caps: n is compiled into the clause kind
# string (phrase_pos:{n}:..., bm25f:{nf}:{nt}), so these bound the
# distinct-kind explosion the same way _FUSED_MAX_CLAUSES bounds the
# per-tile unroll; wider shapes take the host phrase/span/BM25F path
_POS_CLAUSE_TERMS_MAX = 8
_POS_FIELDS_MAX = 4


# ---------------------------------------------------------------------------
# Device view of a segment
# ---------------------------------------------------------------------------


def device_arrays(segment: Segment) -> dict:
    """Upload (once) and return the segment's device-resident columns.

    The upload is accounted against the fielddata breaker (columns are
    the HBM-resident fielddata analog) and released when the segment is
    garbage collected — ref: RamAccountingTermsEnum + the fielddata
    breaker of HierarchyCircuitBreakerService."""
    dev = getattr(segment, "_device", None)
    if dev is None:
        import weakref
        from ..utils.breaker import breaker_service
        # tiered residency (index/tiering.py): a pack over the HBM
        # budget pages its forward-index columns per SCORE_TILE tile
        # instead of uploading them here — only the tiny tile_max
        # summaries (the paging/pruning oracle) stay permanently
        # resident. The decision is sticky per segment.
        from ..index import tiering as _tiering_mod
        paged = _tiering_mod.activate(segment)
        fielddata = breaker_service().breaker("fielddata")
        nbytes = segment.nbytes()
        hold = fielddata.hold(nbytes)
        weakref.finalize(segment, hold.release)
        dev = {
            "text": {
                name: {
                    "block_docs": jnp.asarray(pf.block_docs),
                    "block_imps": jnp.asarray(pf.block_imps),
                    "doc_len": jnp.asarray(pf.doc_len),
                    **({"fwd_tids": jnp.asarray(pf.fwd_tids),
                        "fwd_imps": jnp.asarray(pf.fwd_imps)}
                       if pf.fwd_tids is not None and name not in paged
                       else {}),
                    # positions column family: the big [cap, L*P] delta
                    # pack pages with the forward columns; the tiny
                    # per-doc norm columns stay permanently resident
                    # (the tiered chunk walk gathers them like tile_max)
                    **({"fwd_pos": jnp.asarray(pf.fwd_pos)}
                       if getattr(pf, "fwd_pos", None) is not None
                       and name not in paged
                       else {}),
                    **({"k1ln": jnp.asarray(pf.k1ln),
                        "lnorm": jnp.asarray(pf.lnorm)}
                       if getattr(pf, "fwd_pos", None) is not None
                       else {}),
                    **({"tile_max": jnp.asarray(pf.tile_max)}
                       if pf.fwd_tids is not None
                       and getattr(pf, "tile_max", None) is not None
                       else {}),
                }
                for name, pf in segment.text.items()
            },
            "kw": {name: jnp.asarray(kc.ords) for name, kc in segment.keywords.items()},
            "kw_mv": {name: jnp.asarray(kc.mv_ords)
                      for name, kc in segment.keywords.items()
                      if kc.mv_ords is not None},
            "num": {
                name: {"values": jnp.asarray(nc.values),
                       "exists": jnp.asarray(nc.exists),
                       **({"mv_values": jnp.asarray(nc.mv_values),
                           "mv_exists": jnp.asarray(nc.mv_exists)}
                          if nc.mv_values is not None else {})}
                for name, nc in segment.numerics.items()
            },
            "vec": {
                # bf16 HBM residency: the MXU consumes bf16 anyway
                # (knn_topk casts), so f32 storage would double both
                # the footprint and the matmul's HBM read; norms stay
                # f32 for the similarity denominators
                name: {"values": jnp.asarray(vc.values,
                                             dtype=jnp.bfloat16),
                       "exists": jnp.asarray(vc.exists),
                       "norms": jnp.asarray(vc.norms)}
                for name, vc in segment.vectors.items()
            },
            "geo": {
                name: {"lat": jnp.asarray(gc.lat),
                       "lon": jnp.asarray(gc.lon),
                       "exists": jnp.asarray(gc.exists)}
                for name, gc in segment.geos.items()
            },
        }
        if segment.has_nested:
            # block-join projection: child row -> parent row (self for
            # primary rows so scatter indices stay in-bounds)
            target = np.where(segment.parent_of >= 0, segment.parent_of,
                              np.arange(segment.capacity, dtype=np.int32))
            dev["nested"] = {
                "target": jnp.asarray(target.astype(np.int32)),
                "is_child": jnp.asarray(segment.parent_of >= 0),
            }
        segment._device = dev  # type: ignore[attr-defined]
    return dev


def ensure_kw_sorted(segment: Segment, field: str) -> None:
    """Lazily upload the ordinal-sort permutation + group boundaries for
    a keyword column — the static layout behind scatter-free terms
    aggregation (ops/aggs.sorted_group_reduce). The local->global remap
    stays a (small, G-sized) runtime scatter because global ordinals are
    a READER property while this layout is a SEGMENT property."""
    dev = device_arrays(segment)
    if field in dev.get("kw_sorted", {}):
        return
    kc = segment.keywords.get(field)
    if kc is None:
        return
    perm = np.argsort(kc.ords, kind="stable").astype(np.int32)
    sorted_ords = kc.ords[perm]
    starts = np.searchsorted(
        sorted_ords, np.arange(kc.cardinality + 1)).astype(np.int32)
    _host_perms(segment)[("kw", field)] = perm
    dev.setdefault("kw_sorted", {})[field] = {
        "perm": jnp.asarray(perm), "starts": jnp.asarray(starts)}


def ensure_num_sorted(segment: Segment, field: str) -> None:
    """Lazily upload the value-sort permutation for a single-valued
    numeric column (scatter-free histograms; missing docs sort last via
    the dtype max sentinel and are excluded by the exists mask)."""
    dev = device_arrays(segment)
    if field in dev.get("num_sorted", {}):
        return
    nc = segment.numerics.get(field)
    if nc is None or nc.mv_values is not None:
        return
    vals = nc.values.copy()
    sentinel = (np.iinfo(np.int32).max if vals.dtype == np.int32
                else np.float32(np.inf))
    vals[~nc.exists] = sentinel
    perm = np.argsort(vals, kind="stable").astype(np.int32)
    _host_perms(segment)[("num", field)] = perm
    dev.setdefault("num_sorted", {})[field] = {
        "perm": jnp.asarray(perm),
        "vals": jnp.asarray(vals[perm]),
        "sexists": jnp.asarray(nc.exists[perm])}


def ensure_num_tiles(segment: Segment, field: str) -> bool:
    """Lazily build + upload the per-tile [lo, hi] extrema of a
    single-valued numeric column (index/segment.build_tile_minmax) —
    the mask-density prune input for fused range filter clauses. The
    changed dev-tree structure keys fresh compiled programs, exactly
    like the other ensure_* lazy uploads. Returns False when the column
    cannot carry extrema (absent, multi-valued, degenerate tile grid)."""
    nc = segment.numerics.get(field)
    if nc is None or nc.mv_values is not None:
        return False
    dev = device_arrays(segment)
    entry = dev["num"].get(field)
    if entry is None:
        return False
    if "tile_lo" in entry:
        return True
    # shared per-segment host cache (index/tiering.host_extrema): the
    # tiered survivor oracle reads the SAME arrays, so a paged pack's
    # range clause computes the extrema once, not once per consumer
    mm = _tiering.host_extrema(segment, field)
    if mm is None:
        return False
    entry["tile_lo"] = jnp.asarray(mm[0])
    entry["tile_hi"] = jnp.asarray(mm[1])
    return True


def ensure_script_vals(segment: Segment, fields) -> None:
    """Lazily upload the natural-unit float32 view ("script_vals":
    dates in epoch millis, ip unbiased) for the numeric columns a
    script references — scripts are rare, so this HBM copy must not tax
    script-free workloads. Mutates the cached device dict; the changed
    pytree structure keys a separate compiled program, which a scripted
    query needs anyway."""
    dev = device_arrays(segment)
    for f in fields:
        nc = segment.numerics.get(f)
        if nc is not None and "script_vals" not in dev["num"][f]:
            dev["num"][f]["script_vals"] = \
                jnp.asarray(nc.raw.astype(np.float32))


# ---------------------------------------------------------------------------
# Sorted-space query views
#
# At HBM-resident corpus scale the per-query permutation gather that
# carries a doc-space match mask into an agg layout's sort order costs
# ~17ms per 20M-row query on this TPU (a flat 1-D gather), while
# evaluating the SAME filter directly against sorted copies of the
# referenced columns costs ~0.2ms. So for view-compatible queries
# (elementwise column predicates: range/term/terms/exists/bool —
# i.e. the filter context of every analytics workload) the engine keeps
# lazily-projected sorted copies of the filter columns per agg layout
# and re-evaluates the query desc in sorted space; the per-doc gather
# never happens. Text scoring descs keep the doc-space path.
# ---------------------------------------------------------------------------

_VIEW_KW_KINDS = ("term_kw", "ord_set", "range_kw", "exists_kw")
_VIEW_NUM_KINDS = ("term_num", "range_int", "range_f32", "exists_num")


def _host_perms(segment: Segment) -> dict:
    hp = getattr(segment, "_host_perms", None)
    if hp is None:
        hp = {}
        segment._host_perms = hp  # type: ignore[attr-defined]
    return hp


def _bound_view_fields(bound: "Bound", kw: set, num: set) -> bool:
    """Walk a bound tree: True if every node is view-compatible,
    collecting the kw/num fields its mask evaluation reads."""
    k = bound.kind
    if k in ("none", "match_all"):
        return True
    if k in _VIEW_KW_KINDS:
        kw.add(bound.field)
        return True
    if k in _VIEW_NUM_KINDS:
        num.add(bound.field)
        return True
    if k == "bool":
        return all(_bound_view_fields(c, kw, num)
                   for grp in ("must", "should", "must_not", "filter")
                   for c in bound.children[grp])
    if k == "const":
        return _bound_view_fields(bound.children["q"][0], kw, num)
    return False


def ensure_agg_views(segment: Segment, bound: "Bound", agg_desc: tuple,
                     ) -> None:
    """Project the filter columns `bound` references into the sort order
    of every agg layout `agg_desc` uses on this segment (plus the
    sub-metric source columns). One-time numpy work per
    (layout, column) pair; no-op for non-view-compatible queries."""
    kw_f: set = set()
    num_f: set = set()
    if not _bound_view_fields(bound, kw_f, num_f):
        return
    dev = device_arrays(segment)
    perms = _host_perms(segment)
    for name, node in agg_desc:
        kind = node[0]
        if kind == "terms_kw":
            layouts = [("kw", node[1], node[3])]
        elif kind in ("hist_fixed", "hist_edges"):
            layouts = [("num", node[1], node[3])]
        elif kind == "pctl":
            layouts = [("num", node[1], ())]
        else:
            continue
        for lkind, lfield, subs in layouts:
            store_name = "kw_sorted" if lkind == "kw" else "num_sorted"
            store = dev.get(store_name, {}).get(lfield)
            perm = perms.get((lkind, lfield))
            if store is None or perm is None:
                continue
            need_num = num_f | {f for _n, f, mk in subs
                                if mk in ("avg", "sum", "value_count")}
            vw_num = store.setdefault("vw_num", {})
            for f in need_num:
                nc = segment.numerics.get(f)
                if nc is None or f in vw_num:
                    continue
                col = {"values": jnp.asarray(nc.values[perm]),
                       "exists": jnp.asarray(nc.exists[perm])}
                if nc.mv_values is not None:
                    col["mv_values"] = jnp.asarray(nc.mv_values[perm])
                    col["mv_exists"] = jnp.asarray(nc.mv_exists[perm])
                vw_num[f] = col
            vw_kw = store.setdefault("vw_kw", {})
            vw_kw_mv = store.setdefault("vw_kw_mv", {})
            for f in kw_f:
                kc = segment.keywords.get(f)
                if kc is None or f in vw_kw:
                    continue
                vw_kw[f] = jnp.asarray(kc.ords[perm])
                if kc.mv_ords is not None:
                    vw_kw_mv[f] = jnp.asarray(kc.mv_ords[perm])


def _desc_view_ok(desc: tuple, store: dict, seg: dict) -> bool:
    """Trace-time check: can `desc`'s match mask be evaluated against the
    projections present in `store`? (Multi-valued sidecar presence must
    mirror the doc-space column so eval_node takes the same branch.)"""
    kind = desc[0]
    if kind in ("none", "match_all"):
        return True
    if kind in _VIEW_KW_KINDS:
        f = desc[1]
        return (f in store.get("vw_kw", {})
                and ((f in seg.get("kw_mv", {}))
                     == (f in store.get("vw_kw_mv", {}))))
    if kind in _VIEW_NUM_KINDS:
        f = desc[1]
        col = store.get("vw_num", {}).get(f)
        if col is None:
            return False
        return ("mv_values" in seg["num"].get(f, {})) == ("mv_values" in col)
    if kind == "bool":
        _, must, should, must_not, filt = desc
        return all(_desc_view_ok(d, store, seg)
                   for grp in (must, should, must_not, filt) for d in grp)
    if kind == "const":
        return _desc_view_ok(desc[1], store, seg)
    return False


def _sub_view_ok(store: dict, seg: dict, mfield: str, mkind: str) -> bool:
    if mfield not in seg["num"]:
        return True  # column absent from segment: empty metric either way
    if mkind not in ("avg", "sum", "value_count"):
        return False  # min/max/stats keep the doc-space path
    col = store.get("vw_num", {}).get(mfield)
    return col is not None and "mv_values" not in col \
        and "mv_values" not in seg["num"][mfield]


def _agg_view_plan(desc: tuple, agg_desc: tuple, agg_params: tuple,
                   seg: dict, live_views: dict) -> tuple:
    """Per-agg-node static decision: evaluate in sorted view space?"""
    plan = []
    for (name, node), params in zip(agg_desc, agg_params):
        kind = node[0]
        ok = False
        if kind == "terms_kw":
            _, field, n_global, subs, top_s = node
            store = seg.get("kw_sorted", {}).get(field)
            if (store is not None and ("kw", field) in live_views
                    and field in seg["kw"]
                    and field not in seg.get("kw_mv", {})
                    and store["starts"].shape[0] - 1 == params[0].shape[0]
                    and _desc_view_ok(desc, store, seg)):
                ok = all(_sub_view_ok(store, seg, f, mk)
                         for _n, f, mk in subs)
        elif kind in ("hist_fixed", "hist_edges", "pctl"):
            field = node[1]
            subs = node[3] if kind != "pctl" else ()
            store = seg.get("num_sorted", {}).get(field)
            col = seg["num"].get(field)
            if (store is not None and ("num", field) in live_views
                    and col is not None and "mv_values" not in col
                    and "sexists" in store
                    and _desc_view_ok(desc, store, seg)):
                ok = all(_sub_view_ok(store, seg, f, mk)
                         for _n, f, mk in subs)
        plan.append(ok)
    return tuple(plan)


class _ViewMasks:
    """Lazily evaluates (and caches) the query's valid mask in each agg
    layout's sorted space: eval_node against projected columns, ANDed
    with the layout-permuted live mask."""

    def __init__(self, desc, params, seg, live_views, cap, B):
        self.desc = desc
        self.params = params
        self.seg = seg
        self.live_views = live_views
        self.cap = cap
        self.B = B
        self._cache: dict = {}

    def mask(self, key: tuple) -> jax.Array:
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        lkind, lfield = key
        store_name = "kw_sorted" if lkind == "kw" else "num_sorted"
        store = self.seg[store_name][lfield]
        view_seg = {**self.seg,
                    "kw": store.get("vw_kw", {}),
                    "kw_mv": store.get("vw_kw_mv", {}),
                    "num": store.get("vw_num", {}),
                    "text": {}, "geo": {}, "vec": {}}
        _, match = eval_node(self.desc, self.params, view_seg,
                             self.cap, self.B)
        vm = match & self.live_views[key][None, :]
        self._cache[key] = vm
        return vm


# ---------------------------------------------------------------------------
# Bound query tree (host-side intermediate; finalize() -> desc + params)
# ---------------------------------------------------------------------------


@dataclass
class Bound:
    kind: str
    field: str | None = None
    scalars: dict[str, float | int] = dc_field(default_factory=dict)
    arrays: dict[str, np.ndarray] = dc_field(default_factory=dict)
    children: dict[str, list["Bound"]] = dc_field(default_factory=dict)

    def signature(self) -> tuple:
        return (
            self.kind, self.field,
            tuple(sorted(self.arrays)),
            tuple((g, tuple(c.signature() for c in cs))
                  for g, cs in sorted(self.children.items())),
        )


class QueryBinder:
    """Resolves a query AST against ONE segment. Ref analog: Lucene query
    rewrite + Weight creation (createWeight) per IndexReader."""

    def __init__(self, segment: Segment, mapper: MapperService,
                 live: np.ndarray | None = None,
                 dfs: dict | None = None):
        self.seg = segment
        self.mappers = mapper
        self.live = live   # primary live mask (parents_match liveness)
        self.dfs = dfs     # {"field\x00term": [global_df, global_N]} from
                           # the DFS pre-phase (aggregateDfs)

    def _dfs_ratio(self, field: str, term: str, df_local: float,
                   n_local: float) -> float:
        """Scale factor turning a locally-idf'd eager impact into the
        globally-idf'd score, delegated to the field's Similarity
        (idf_global/idf_local for BM25, squared for classic TF/IDF, 1.0
        where df isn't a separable factor — index/similarity.py)."""
        if not self.dfs:
            return 1.0
        entry = self.dfs.get(f"{field}\x00{term}")
        if not entry or entry[1] <= 0:
            return 1.0
        sim = self.mappers.similarity_for(field)
        return sim.df_scale(df_local, n_local,
                            float(entry[0]), float(entry[1]))

    def bind(self, q: Query) -> Bound:
        m = getattr(self, f"_bind_{type(q).__name__}", None)
        if m is None:
            raise QueryParsingError(f"unsupported query node [{type(q).__name__}]")
        return m(q)

    # -- leaves ------------------------------------------------------------

    def _no_match(self) -> Bound:
        return Bound("none")

    def _bind_MatchAllQuery(self, q: MatchAllQuery) -> Bound:
        return Bound("match_all", scalars={"boost": q.boost})

    def _bind_MatchNoneQuery(self, q: MatchNoneQuery) -> Bound:
        return self._no_match()

    def _term_text(self, field: str, term: str, boost: float) -> Bound:
        pf = self.seg.text.get(field)
        if pf is None:
            return self._no_match()
        t = pf.lookup(term)
        if t < 0:
            lo, nb = 0, 0
        else:
            lo = int(pf.block_start[t])
            nb = int(pf.block_start[t + 1]) - lo
            if self.dfs:
                boost = boost * self._dfs_ratio(
                    field, term, float(pf.df[t]), float(pf.doc_count))
        kind = "term_text" if pf.fwd_tids is not None else "term_text_sc"
        return Bound(kind, field,
                     scalars={"block_lo": lo, "nb": nb, "tid": t,
                              "weight": max(boost, _F32_MIN_WEIGHT)})

    def _terms_text_expanded(self, field: str, term_ids: Sequence[int],
                             boost: float) -> Bound:
        """Multi-term expansion (prefix/wildcard/fuzzy/terms) as one fused
        gather: absolute block indices of all expanded terms."""
        pf = self.seg.text[field]
        blocks: list[int] = []
        for t in term_ids:
            blocks.extend(range(int(pf.block_start[t]), int(pf.block_start[t + 1])))
        return Bound("terms_fused", field,
                     scalars={"weight": max(boost, _F32_MIN_WEIGHT)},
                     arrays={"blocks": np.asarray(blocks, dtype=np.int32)})

    def _bind_TermQuery(self, q: TermQuery) -> Bound:
        kind = self.seg.field_kind(q.field)
        if kind == "text":
            # term queries are NOT analyzed (ref: TermQueryParser.java) —
            # exact term lookup; `match` handles analysis at parse time
            return self._term_text(q.field, str(q.value), q.boost)
        if kind == "keyword":
            kc = self.seg.keywords[q.field]
            o = kc.lookup(str(q.value))
            score = 0.0
            if o >= 0:
                # keyword fields carry no norms: BM25 degenerates to idf
                # (tf=1, (k1+1)/(1+k1) with b=0 -> idf), ref BM25Similarity
                score = float(bm25_idf(float(kc.df[o]), self.seg.num_docs))
                if self.dfs:
                    entry = self.dfs.get(f"{q.field}\x00{q.value}")
                    if entry and entry[1] > 0:
                        score = float(bm25_idf(float(entry[0]),
                                               float(entry[1])))
            return Bound("term_kw", q.field,
                         scalars={"ord": o, "score": max(score * q.boost,
                                                         _F32_MIN_WEIGHT)})
        if kind == "numeric":
            nc = self.seg.numerics[q.field]
            try:
                if nc.kind == DATE:
                    v = parse_date_millis(q.value) // 1000
                elif nc.kind == BOOLEAN:
                    v = 1 if (q.value in (True, "true", "1", 1)) else 0
                elif nc.kind == IP:
                    v = parse_ip(q.value) - nc.bias
                else:
                    v = float(q.value) if nc.values.dtype == np.float32 else int(q.value)
            except (ValueError, TypeError, MapperParsingError):
                return self._no_match()
            return Bound("term_num", q.field,
                         scalars={"value": v, "score": max(q.boost, _F32_MIN_WEIGHT)})
        return self._no_match()

    def _bind_RangeQuery(self, q: RangeQuery) -> Bound:
        kind = self.seg.field_kind(q.field)
        if kind == "numeric":
            nc = self.seg.numerics[q.field]
            is_int = nc.values.dtype == np.int32

            def conv(v):
                if v is None:
                    return None
                try:
                    if nc.kind == DATE:
                        return parse_date_millis(v) // 1000 if not isinstance(v, bool) else None
                    if nc.kind == IP:
                        return parse_ip(v) - nc.bias
                    return float(v)
                except Exception:
                    raise QueryParsingError(
                        f"failed to parse range bound [{v}] on [{q.field}]")

            i32 = np.iinfo(np.int32)
            lo, hi = conv(q.gte), conv(q.lte)
            lo_x, hi_x = conv(q.gt), conv(q.lt)
            if is_int:
                lo_i = i32.min if lo is None and lo_x is None else int(
                    math.ceil(lo) if lo is not None else math.floor(lo_x) + 1)
                hi_i = i32.max if hi is None and hi_x is None else int(
                    math.floor(hi) if hi is not None else math.ceil(hi_x) - 1)
                lo_i = max(min(lo_i, i32.max), i32.min)
                hi_i = max(min(hi_i, i32.max), i32.min)
                return Bound("range_int", q.field,
                             scalars={"lo": lo_i, "hi": hi_i, "boost": q.boost})
            lo_f = -np.inf if lo is None and lo_x is None else (
                lo if lo is not None else np.nextafter(np.float32(lo_x), np.float32(np.inf)))
            hi_f = np.inf if hi is None and hi_x is None else (
                hi if hi is not None else np.nextafter(np.float32(hi_x), np.float32(-np.inf)))
            return Bound("range_f32", q.field,
                         scalars={"lo": float(lo_f), "hi": float(hi_f), "boost": q.boost})
        if kind == "keyword":
            kc = self.seg.keywords[q.field]
            terms = kc.terms
            lo_o = 0
            hi_o = len(terms) - 1
            if q.gte is not None:
                lo_o = int(np.searchsorted(terms, str(q.gte), side="left"))
            elif q.gt is not None:
                lo_o = int(np.searchsorted(terms, str(q.gt), side="right"))
            if q.lte is not None:
                hi_o = int(np.searchsorted(terms, str(q.lte), side="right")) - 1
            elif q.lt is not None:
                hi_o = int(np.searchsorted(terms, str(q.lt), side="left")) - 1
            return Bound("range_kw", q.field,
                         scalars={"lo": lo_o, "hi": hi_o, "boost": q.boost})
        return self._no_match()

    def _bind_ExistsQuery(self, q: ExistsQuery) -> Bound:
        kind = self.seg.field_kind(q.field)
        if kind == "text":
            return Bound("exists_text", q.field, scalars={"boost": 1.0})
        if kind == "keyword":
            return Bound("exists_kw", q.field, scalars={"boost": 1.0})
        if kind == "numeric":
            return Bound("exists_num", q.field, scalars={"boost": 1.0})
        if kind in ("geo", "vector"):
            return Bound("exists_gv", f"{kind}\x00{q.field}",
                         scalars={"boost": 1.0})
        return self._no_match()

    def _bind_KnnQuery(self, q: KnnQuery) -> Bound:
        """Vector similarity as a scoring clause: every live doc with a
        vector matches, scored by the field similarity's transform
        (ops/knn.knn_score_column) times boost. The similarity rides
        the desc (static — it compiles into the program); the query
        vector and boost are dynamic params."""
        vc = self.seg.vectors.get(q.field)
        if vc is None:
            return self._no_match()
        fm = self.mappers.field(q.field)
        sim = fm.similarity if fm is not None and fm.similarity else "cosine"
        if sim not in _KNN_SIMILARITIES:
            raise QueryParsingError(
                f"[knn] unsupported similarity [{sim}] on [{q.field}]")
        qv = np.asarray(q.vector, dtype=np.float32)
        if qv.shape[0] != vc.dims:
            raise QueryParsingError(
                f"[knn] query_vector has {qv.shape[0]} dims, field "
                f"[{q.field}] has {vc.dims}")
        return Bound("knn_vec", q.field,
                     scalars={"boost": max(float(q.boost),
                                           _F32_MIN_WEIGHT),
                              "sim": sim},
                     arrays={"qv": qv})

    def _bind_IdsQuery(self, q: IdsQuery) -> Bound:
        mask = np.zeros(self.seg.capacity, dtype=bool)
        for v in q.values:
            d = self.seg.id_map.get(v)
            if d is not None:
                mask[d] = True
        return Bound("ids", arrays={"mask": mask})

    def _expand_terms(self, field: str, pred, boost: float,
                      max_expansions: int) -> Bound:
        kind = self.seg.field_kind(field)
        if kind == "text":
            pf = self.seg.text[field]
            tids = [i for i, t in enumerate(pf.terms) if pred(t)][:max_expansions]
            if not tids:
                return self._no_match()
            return self._terms_text_expanded(field, tids, boost)
        if kind == "keyword":
            kc = self.seg.keywords[field]
            ords = np.asarray([i for i, t in enumerate(kc.terms) if pred(t)][:max_expansions],
                              dtype=np.int32)
            if ords.size == 0:
                return self._no_match()
            return Bound("ord_set", field,
                         scalars={"boost": max(boost, _F32_MIN_WEIGHT),
                                  "card_total": kc.cardinality},
                         arrays={"ords": ords})
        return self._no_match()

    def _bind_PrefixQuery(self, q: PrefixQuery) -> Bound:
        # sorted dictionary: prefix = contiguous term range (Lucene TermsEnum seek)
        return self._expand_terms(q.field, lambda t: t.startswith(q.value),
                                  q.boost, q.max_expansions)

    def _bind_WildcardQuery(self, q: WildcardQuery) -> Bound:
        import fnmatch
        import re as _re
        rx = _re.compile(fnmatch.translate(q.value))
        return self._expand_terms(q.field, lambda t: rx.match(t) is not None,
                                  q.boost, q.max_expansions)

    def _bind_FuzzyQuery(self, q: FuzzyQuery) -> Bound:
        target = q.value

        def within_edit(t: str) -> bool:
            if abs(len(t) - len(target)) > q.fuzziness:
                return False
            return _edit_distance_le(t, target, q.fuzziness)

        return self._expand_terms(q.field, within_edit, q.boost, q.max_expansions)

    def _bind_RegexpQuery(self, q: RegexpQuery) -> Bound:
        import re as _re
        try:
            rx = _re.compile(q.value)
        except _re.error as e:
            raise QueryParsingError(f"invalid regexp [{q.value}]: {e}")
        return self._expand_terms(q.field, lambda t: rx.fullmatch(t) is not None,
                                  q.boost, q.max_expansions)

    # -- positional (phrase / span) — host match -> device scatter ---------

    def _docs_w(self, docs: np.ndarray, imps: np.ndarray) -> Bound:
        if docs.size == 0:
            return self._no_match()
        return Bound("docs_w",
                     arrays={"docs": docs.astype(np.int32),
                             "imps": imps.astype(np.float32)})

    # -- fused positional admission (device phrase/span/BM25F) -------------

    def _positional_fallback(self, why: str) -> None:
        """Count one positional query taking the host path, by reason —
        nodes_stats()["fused_scoring"].admission.positional_fallbacks."""
        _fused_stats.record_positional(why)

    def _default_bm25(self, field: str) -> bool:
        """Positional clause kinds evaluate the packed k1ln/lnorm
        columns, which bake the DEFAULT BM25 parameters — any other
        configured Similarity keeps the host oracle path."""
        from ..index.similarity import BM25Similarity
        sim = self.mappers.similarity_for(field)
        return sim is None or (isinstance(sim, BM25Similarity)
                               and sim.k1 == BM25_K1 and sim.b == BM25_B)

    def _positional_field_ok(self, pf) -> bool:
        return (getattr(pf, "fwd_pos", None) is not None
                and getattr(pf, "tile_max", None) is not None
                and pf.fwd_tids is not None)

    def _phrase_fused(self, q, pf, tid_groups) -> Bound | None:
        """Fused-engine Bound for an eligible match_phrase, or None to
        take the host phrase_match -> docs_w path (reason counted).
        Eligibility mirrors the device algorithm's assumptions; the
        host path stays the byte-identity oracle for everything else."""
        from .phrase import terms_idf_sum
        if not _positional_enabled():
            return None                        # A/B lever: exact either way
        if q.prefix_last:
            self._positional_fallback("phrase_prefix")
            return None
        if not self._positional_field_ok(pf):
            self._positional_fallback("missing_positions_pack")
            return None
        if not self._default_bm25(q.field):
            self._positional_fallback("similarity")
            return None
        n = len(tid_groups)
        if n > _POS_CLAUSE_TERMS_MAX:
            self._positional_fallback("too_many_terms")
            return None
        if q.slop > POS_MAX_ENC:
            self._positional_fallback("slop_cap")
            return None
        if not q.boost > 0.0:
            # host docs_w at boost <= 0 yields score 0 => no match; the
            # fused leaf's match is freq > 0 — semantics diverge, and
            # boost <= 0 breaks the monotone tile bound anyway
            self._positional_fallback("nonpositive_boost")
            return None
        tids = [g[0] for g in tid_groups]
        idf_sum = terms_idf_sum(pf, tid_groups)
        wb = [idf_sum / float(bm25_idf(float(pf.df[t]), pf.doc_count))
              for t in tids]
        return Bound(phrase_kind(n, q.slop > 0), q.field,
                     scalars={"idf_sum": float(idf_sum),
                              "slop": int(q.slop),
                              "boost": float(q.boost)},
                     arrays={"qt": np.asarray(tids, np.int32),
                             "wb": np.asarray(wb, np.float32)})

    def _span_fused(self, q) -> Bound | None:
        """Fused-engine Bound for an eligible span tree — a bare
        span_term or a depth-1 span_near of same-field span_terms — or
        None for the host Spans path. span_or / span_first / span_not
        and nested span_near trees stay host-side, counted. Child
        boosts are ignored exactly as the host Spans algebra ignores
        them. Declines (returns None) on a positions-less field so the
        host path raises the identical QueryParsingError."""
        from .query_dsl import SpanTermQuery, SpanNearQuery
        if not _positional_enabled():
            return None
        if isinstance(q, SpanTermQuery):
            field, terms = q.field, [str(q.value)]
            in_order, slop = False, 0
        elif isinstance(q, SpanNearQuery) and q.clauses and all(
                isinstance(c, SpanTermQuery) for c in q.clauses):
            if len({c.field for c in q.clauses}) > 1:
                return None          # host raises the same-field error
            field = q.clauses[0].field
            terms = [str(c.value) for c in q.clauses]
            in_order, slop = q.in_order, q.slop
        else:
            self._positional_fallback(f"span_{type(q).__name__}")
            return None
        pf = self.seg.text.get(field)
        if pf is None or pf.pos_data is None:
            return None      # host: no_match / positions-less error
        if not self._positional_field_ok(pf):
            self._positional_fallback("missing_positions_pack")
            return None
        if not self._default_bm25(field):
            self._positional_fallback("similarity")
            return None
        n = len(terms)
        if n > _POS_CLAUSE_TERMS_MAX:
            self._positional_fallback("too_many_terms")
            return None
        if slop > POS_MAX_ENC:
            self._positional_fallback("slop_cap")
            return None
        if not q.boost > 0.0:
            self._positional_fallback("nonpositive_boost")
            return None
        tids = [pf.lookup(t) for t in terms]
        if any(t < 0 for t in tids):
            return self._no_match()  # host: empty spans -> no_match
        idf = [float(bm25_idf(float(pf.df[t]), pf.doc_count))
               for t in tids]
        idf_sum = sum(idf)
        # n == 1 degenerates to plain occurrence counting either way;
        # the unordered kind keeps the tight per-term bound
        kind = span_kind(n, in_order if n > 1 else False)
        return Bound(kind, field,
                     scalars={"idf_sum": float(idf_sum), "slop": int(slop),
                              "boost": float(q.boost)},
                     arrays={"qt": np.asarray(tids, np.int32),
                             "wb": np.asarray([idf_sum / v for v in idf],
                                              np.float32)})

    def _bind_BM25FQuery(self, q) -> Bound:
        """multi_match type=cross_fields as true BM25F: shared max-df
        IDF per term, per-field weighted tf and length norms, ONE
        saturation across fields. Binder computes the statistics once
        and feeds the SAME numbers to whichever path serves the query:
        the fused bm25f clause kind, or the host oracle
        (search/phrase.bm25f_scores) scattered through docs_w."""
        from .phrase import bm25f_scores
        pairs = [(f, w) for f, w in q.fields
                 if self.seg.text.get(f) is not None]
        if not pairs or not q.terms:
            return self._no_match()
        pfs = [self.seg.text[f] for f, _w in pairs]
        nf, nt = len(pairs), len(q.terms)
        tids = np.full((nf, nt), -1, np.int32)
        for fi, pf in enumerate(pfs):
            for ti, term in enumerate(q.terms):
                tids[fi, ti] = pf.lookup(term)
        if (tids < 0).all():
            return self._no_match()
        # shared IDF: rarest interpretation is per-term max df across
        # the fields (the BM25F "one virtual document" view); N is the
        # widest field's doc count so idf stays well-defined
        n_docs = max(pf.doc_count for pf in pfs)
        idf = [float(bm25_idf(float(max(
                   (pf.df[t] for pf, t in zip(pfs, tids[:, ti]) if t >= 0),
                   default=0.0)), n_docs)) for ti in range(nt)]
        weights = np.asarray([max(w, _F32_MIN_WEIGHT) for _f, w in pairs],
                             np.float32)
        fused_ok = (_positional_enabled() and q.boost > 0.0
                    and nf <= _POS_FIELDS_MAX
                    and nt <= _POS_CLAUSE_TERMS_MAX
                    and all(self._positional_field_ok(pf)
                            and self._default_bm25(f)
                            for (f, _w), pf in zip(pairs, pfs)))
        if fused_ok:
            return Bound(bm25f_kind(nf, nt), tuple(f for f, _w in pairs),
                         scalars={"boost": float(q.boost)},
                         arrays={"qt": tids,
                                 "idf": np.asarray(idf, np.float32),
                                 "wf": weights})
        if _positional_enabled():
            self._positional_fallback(
                "bm25f_boost" if not q.boost > 0.0 else
                "bm25f_shape" if (nf > _POS_FIELDS_MAX
                                  or nt > _POS_CLAUSE_TERMS_MAX) else
                "missing_positions_pack")
        col = bm25f_scores(pfs, tids, idf, weights, self.seg.capacity)
        docs = np.nonzero(col > 0.0)[0].astype(np.int32)
        return self._docs_w(docs, col[docs] * np.float32(q.boost))

    def _bind_PhraseQuery(self, q) -> Bound:
        from .phrase import phrase_match, phrase_impacts, terms_idf_sum
        pf = self.seg.text.get(q.field)
        if pf is None:
            return self._no_match()
        if pf.pos_data is None:
            # legacy segment persisted without the positional sidecar:
            # degrade to the conjunctive approximation (all terms must
            # match) rather than silently returning nothing
            from .query_dsl import BoolQuery, TermQuery
            return self.bind(BoolQuery(
                must=tuple(TermQuery(q.field, t) for t in q.terms),
                boost=q.boost))
        tid_groups: list[list[int]] = []
        for i, term in enumerate(q.terms):
            if q.prefix_last and i == len(q.terms) - 1:
                tids = [j for j, t in enumerate(pf.terms)
                        if t.startswith(term)][: q.max_expansions]
                tid_groups.append(tids)
            else:
                t = pf.lookup(term)
                if t < 0:
                    return self._no_match()
                tid_groups.append([t])
        fused = self._phrase_fused(q, pf, tid_groups)
        if fused is not None:
            return fused
        docs, freqs = phrase_match(pf, tid_groups, q.slop)
        imps = phrase_impacts(
            pf, docs, freqs, terms_idf_sum(pf, tid_groups),
            sim=self.mappers.similarity_for(q.field),
            tids=[t for g in tid_groups for t in g]) * q.boost
        return self._docs_w(docs, imps)

    def _span_tree(self, q):
        """Query AST -> (phrase.Spans, field, [tids]) for span evaluation."""
        from . import phrase as ph
        from .query_dsl import (SpanTermQuery, SpanNearQuery, SpanOrQuery,
                                SpanFirstQuery, SpanNotQuery)
        if isinstance(q, SpanTermQuery):
            pf = self.seg.text.get(q.field)
            if pf is not None and pf.pos_data is None:
                # ref: Lucene errors when positions were not indexed
                raise QueryParsingError(
                    f"field [{q.field}] was indexed without position data; "
                    f"cannot run span queries")
            if pf is None:
                return ph.Spans.empty(), q.field, []
            tid = pf.lookup(str(q.value))
            return ph.span_term(pf, tid), q.field, [tid] if tid >= 0 else []
        if isinstance(q, SpanNearQuery):
            parts = [self._span_tree(c) for c in q.clauses]
            field = self._span_same_field(parts, "span_near")
            tids = [t for _, _, ts in parts for t in ts]
            return (ph.span_near([p for p, _, _ in parts], q.slop,
                                 q.in_order), field, tids)
        if isinstance(q, SpanOrQuery):
            parts = [self._span_tree(c) for c in q.clauses]
            field = self._span_same_field(parts, "span_or")
            tids = [t for _, _, ts in parts for t in ts]
            return ph.span_or([p for p, _, _ in parts]), field, tids
        if isinstance(q, SpanFirstQuery):
            spans, field, tids = self._span_tree(q.match)
            return ph.span_first(spans, q.end), field, tids
        if isinstance(q, SpanNotQuery):
            inc, field, tids = self._span_tree(q.include)
            exc, _, _ = self._span_tree(q.exclude)
            return ph.span_not(inc, exc, q.pre, q.post), field, tids
        raise QueryParsingError(
            f"unsupported span clause [{type(q).__name__}]")

    @staticmethod
    def _span_same_field(parts, ctx: str) -> str:
        # Lucene SpanNearQuery/SpanOrQuery require all clauses on one
        # field ("Clauses must have same field")
        fields = {f for _, f, _ in parts}
        if len(fields) > 1:
            raise QueryParsingError(
                f"[{ctx}] clauses must have same field, got {sorted(fields)}")
        return parts[0][1]

    def _bind_span(self, q) -> Bound:
        from .phrase import phrase_impacts
        from ..index.segment import bm25_idf
        fused = self._span_fused(q)
        if fused is not None:
            return fused
        spans, field, tids = self._span_tree(q)
        pf = self.seg.text.get(field)
        if pf is None or spans.size == 0:
            return self._no_match()
        docs, freqs = spans.doc_freqs()
        idf_sum = sum(float(bm25_idf(float(pf.df[t]), pf.doc_count))
                      for t in tids)
        imps = phrase_impacts(
            pf, docs, freqs, idf_sum,
            sim=self.mappers.similarity_for(field), tids=tids) * q.boost
        return self._docs_w(docs, imps)

    _bind_SpanTermQuery = _bind_span
    _bind_SpanNearQuery = _bind_span
    _bind_SpanOrQuery = _bind_span
    _bind_SpanFirstQuery = _bind_span
    _bind_SpanNotQuery = _bind_span

    # -- block join (nested) ------------------------------------------------

    _NESTED_SCORE_MODES = ("none", "sum", "avg", "max", "min")

    def _bind_NestedQuery(self, q) -> Bound:
        """ToParentBlockJoinQuery analog: evaluate the child query over
        hidden nested rows, project match/score onto parent rows with a
        device scatter. Ref: index/query/NestedQueryParser.java."""
        if not self.seg.has_nested:
            return self._no_match()
        kc = self.seg.keywords.get("_nested_path")
        if kc is None:
            return self._no_match()
        o = kc.lookup(q.path)
        if o < 0:
            return self._no_match()
        path_mask = kc.ords == o
        mode = q.score_mode if q.score_mode in self._NESTED_SCORE_MODES \
            else "avg"
        return Bound("nested", field=mode,
                     scalars={"boost": max(q.boost, _F32_MIN_WEIGHT)},
                     arrays={"path_mask": path_mask},
                     children={"q": [self.bind(q.query)]})

    def _bind_ParentsMatchQuery(self, q) -> Bound:
        """Matches nested child rows whose PARENT matches the inner query
        (the nested-aggregation scope filter; ref: the parentDocs bitset
        in search/aggregations/bucket/nested/NestedAggregator.java)."""
        if not self.seg.has_nested:
            return self._no_match()
        plive = (self.live if self.live is not None
                 else self.seg.primary_mask())
        return Bound("parents_match",
                     arrays={"plive": np.asarray(plive, dtype=bool)},
                     children={"q": [self.bind(q.query)]})

    def _bind_MoreLikeThisQuery(self, q) -> Bound:
        """Lucene MoreLikeThis term selection against THIS segment's
        statistics: tokens of the like-texts ranked by tf*idf, top
        max_query_terms become a bool-should of term queries."""
        from .query_dsl import (BoolQuery, TermQuery, IdsQuery, resolve_msm)
        tf_by_field: dict[str, dict[str, int]] = {}
        for fld in q.fields:
            analyzer = self.mappers.search_analyzer_for(fld)
            counts = tf_by_field.setdefault(fld, {})
            for text in q.like_texts:
                for tok in analyzer.analyze(text):
                    counts[tok] = counts.get(tok, 0) + 1
            # ignore_like/unlike: terms of the unliked docs never make
            # the query (ref: MoreLikeThisQueryParser "unlike" handling)
            for text in getattr(q, "unlike_texts", ()) or ():
                for tok in analyzer.analyze(text):
                    counts.pop(tok, None)
        scored: list[tuple[float, str, str]] = []
        for fld, counts in tf_by_field.items():
            pf = self.seg.text.get(fld)
            if pf is None:
                continue
            for term, tf in counts.items():
                if tf < q.min_term_freq:
                    continue
                t = pf.lookup(term)
                if t < 0:
                    continue
                df = int(pf.df[t])
                if df < min(q.min_doc_freq, pf.doc_count):
                    continue
                idf = float(bm25_idf(float(df), pf.doc_count))
                scored.append((tf * idf, fld, term))
        scored.sort(reverse=True)
        selected = scored[: q.max_query_terms]
        if not selected:
            return self._no_match()
        shoulds = tuple(TermQuery(fld, term, q.boost)
                        for _, fld, term in selected)
        msm = resolve_msm(q.minimum_should_match, len(shoulds)) or 1
        must_not = (IdsQuery(q.exclude_ids),) if q.exclude_ids else ()
        return self.bind(BoolQuery(should=shoulds,
                                   minimum_should_match=max(msm, 1),
                                   must_not=must_not))

    # -- compound ----------------------------------------------------------

    def _bind_BoolQuery(self, q: BoolQuery) -> Bound:
        children = {
            "must": [self.bind(c) for c in q.must],
            "should": [self.bind(c) for c in q.should],
            "must_not": [self.bind(c) for c in q.must_not],
            "filter": [self.bind(c) for c in q.filter],
        }
        # Lucene-style BooleanQuery simplification: splice a nested pure
        # disjunction into the parent's should list (and pure conjunction
        # into must) so e.g. a multi-term match inside `should` binds to
        # the same flat plan as bare term clauses.
        parent_msm = q.minimum_should_match
        if parent_msm is None:
            parent_msm = 1 if (q.should and not q.must and not q.filter) else 0
        if parent_msm <= 1:
            # only valid when the parent needs at most one should vote:
            # then "child bool matched" == "any spliced term matched" and
            # scores are identical (sum of matching terms)
            spliced = []
            for c in children["should"]:
                if (c.kind == "bool" and c.scalars.get("boost") == 1.0
                        and c.scalars.get("msm", 0) == 1
                        and not c.children.get("must")
                        and not c.children.get("must_not")
                        and not c.children.get("filter")):
                    spliced.extend(c.children.get("should", []))
                else:
                    spliced.append(c)
            children["should"] = spliced
        spliced_m = []
        for c in children["must"]:
            if (c.kind == "bool" and c.scalars.get("boost") == 1.0
                    and not c.children.get("should")
                    and not c.children.get("must_not")):
                spliced_m.extend(c.children.get("must", []))
                # child FILTER clauses stay non-scoring: route to parent filter
                children["filter"] = children["filter"] + c.children.get("filter", [])
            else:
                spliced_m.append(c)
        children["must"] = spliced_m
        # fuse same-field text-term should clauses into one scatter
        # (the match-query fast path; only valid when msm <= 1)
        msm = q.minimum_should_match
        if msm is None:
            msm = 1 if (q.should and not q.must and not q.filter) else 0
        if msm <= 1:
            fused: dict[str, list[Bound]] = {}
            rest: list[Bound] = []
            for c in children["should"]:
                if c.kind in ("term_text", "term_text_sc"):
                    fused.setdefault((c.field, c.kind), []).append(c)
                else:
                    rest.append(c)
            for (fld, ckind), group in fused.items():
                # fuse even a single term so a match query binds to the
                # same plan whatever its term count. Few-term groups take
                # the forward-index GATHER path (VPU compare+FMA, no
                # scatter); many-term groups (prefix expansions etc.) and
                # fields without a forward index stay on posting-scatter.
                if ckind == "term_text" and len(group) <= _DENSE_GROUP_MAX:
                    tids: list[int] = []
                    weights: list[float] = []
                    for c in group:
                        tids.append(c.scalars.get("tid", -1))
                        weights.append(c.scalars["weight"])
                    rest.append(Bound(
                        "terms_dense", fld,
                        arrays={"tids": np.asarray(tids, dtype=np.int32),
                                "weights": np.asarray(weights, dtype=np.float32)}))
                else:
                    blocks: list[int] = []
                    weights = []
                    for c in group:
                        for b in range(c.scalars["nb"]):
                            blocks.append(c.scalars["block_lo"] + b)
                            weights.append(c.scalars["weight"])
                    rest.append(Bound(
                        "terms_fused_w", fld,
                        arrays={"blocks": np.asarray(blocks, dtype=np.int32),
                                "weights": np.asarray(weights, dtype=np.float32)}))
            children["should"] = rest
        return Bound("bool", scalars={"msm": msm, "boost": q.boost},
                     children=children)

    def _bind_GeoDistanceQuery(self, q: GeoDistanceQuery) -> Bound:
        if q.field not in self.seg.geos:
            return self._no_match()
        return Bound("geo_distance", q.field,
                     scalars={"lat": q.lat, "lon": q.lon,
                              "to_m": q.distance_m, "from_m": q.from_m,
                              "boost": q.boost})

    def _bind_GeoBoundingBoxQuery(self, q: GeoBoundingBoxQuery) -> Bound:
        if q.field not in self.seg.geos:
            return self._no_match()
        return Bound("geo_bbox", q.field,
                     scalars={"top": q.top, "left": q.left,
                              "bottom": q.bottom, "right": q.right,
                              "boost": q.boost})

    def _bind_GeoPolygonQuery(self, q: GeoPolygonQuery) -> Bound:
        if q.field not in self.seg.geos:
            return self._no_match()
        lats = np.asarray([p[0] for p in q.points], dtype=np.float32)
        lons = np.asarray([p[1] for p in q.points], dtype=np.float32)
        return Bound("geo_polygon", q.field,
                     scalars={"boost": q.boost, "n": len(q.points)},
                     arrays={"lats": lats, "lons": lons})

    def _bind_GeoShapeQuery(self, q: GeoShapeQuery) -> Bound:
        """Decompose a shape relation into cell-token disjunctions over
        the field's prefix tree (ops/geo_shape.py; ref:
        GeoShapeQueryParser + RecursivePrefixTreeStrategy):
        intersects -> one ShapeTokensQuery; within -> intersects AND NOT
        complement-covering; disjoint -> exists AND NOT intersects."""
        from ..index.mapping import GEO_SHAPE, shape_tree_config
        from ..ops.geo_shape import (shape_intersect_tokens,
                                     shape_complement_tokens)
        from .query_dsl import BoolQuery, ExistsQuery, ShapeTokensQuery
        fm = self.mappers.field(q.field)
        if fm is None:
            return self._no_match()
        if fm.type != GEO_SHAPE:
            raise QueryParsingError(
                f"Field [{q.field}] is not a geo_shape")
        tree, tree_levels, err_pct = shape_tree_config(fm)
        tokens = shape_intersect_tokens(q.shape_json, tree.name,
                                        tree_levels, err_pct)
        if q.relation == "intersects":
            return self.bind(ShapeTokensQuery(q.field, tokens, q.boost))
        if q.relation == "disjoint":
            return self.bind(BoolQuery(
                must=(ExistsQuery(q.field),),
                must_not=(ShapeTokensQuery(q.field, tokens),),
                boost=q.boost))
        # within: the bool node applies q.boost, so inner clauses stay 1.0
        comp = shape_complement_tokens(q.shape_json, tree.name,
                                       tree_levels, err_pct)
        return self.bind(BoolQuery(
            must=(ShapeTokensQuery(q.field, tokens),),
            must_not=(ShapeTokensQuery(q.field, comp),),
            boost=q.boost))

    def _bind_ShapeTokensQuery(self, q: ShapeTokensQuery) -> Bound:
        pf = self.seg.text.get(q.field)
        if pf is None:
            return self._no_match()
        tids = [t for t in (pf.lookup(tok) for tok in q.tokens) if t >= 0]
        if not tids:
            return self._no_match()
        # constant score (Lucene ConstantScore over the prefix-tree
        # filter): the fused terms disjunction provides the match mask,
        # `const` flattens its scores to the boost
        return Bound("const", scalars={"boost": q.boost},
                     children={"q": [self._terms_text_expanded(
                         q.field, tids, 1.0)]})

    def _bind_ScriptQuery(self, q: ScriptQuery) -> Bound:
        from ..script import compile_script
        from ..script.service import numeric_param
        cs = compile_script(q.script)  # validate (raises ScriptException)
        ensure_script_vals(self.seg, cs.fields)
        pnames = ",".join(n for n, _ in q.params)
        scalars = {"boost": q.boost}
        for name, val in q.params:
            scalars[f"p_{name}"] = numeric_param(name, val)
        return Bound("script_q", f"{q.script}\x00{pnames}", scalars=scalars)

    def _bind_ConstantScoreQuery(self, q: ConstantScoreQuery) -> Bound:
        return Bound("const", scalars={"boost": q.boost},
                     children={"q": [self.bind(q.query)]})

    def _bind_BoostingQuery(self, q: BoostingQuery) -> Bound:
        return Bound("boosting", scalars={"negative_boost": q.negative_boost},
                     children={"pos": [self.bind(q.positive)],
                               "neg": [self.bind(q.negative)]})

    # -- function_score (ref: functionscore/FunctionScoreQueryParser) -------

    def _resolve_decay_value(self, field: str, v, is_span: bool) -> float:
        """origin/scale/offset -> column units (date cols: epoch seconds /
        second spans; numeric: float)."""
        nc = self.seg.numerics.get(field)
        if nc is not None and nc.kind == DATE:
            if is_span:
                from ..utils.settings import parse_time_value
                return parse_time_value(v) / 1000.0
            if v == "now" or v is None:
                import time as _t
                return float(_t.time())
            return parse_date_millis(v) / 1000.0
        try:
            return float(v)
        except (TypeError, ValueError):
            # date strings against a long column hold epoch MILLIS
            from ..utils.settings import parse_time_value
            if is_span:
                return float(parse_time_value(v))
            if v == "now" or v is None:
                import time as _t
                return _t.time() * 1000.0
            return float(parse_date_millis(v))

    def _bind_fn(self, fn: ScoreFunction) -> Bound:
        children = {"filter": [self.bind(fn.filter)]
                    if fn.filter is not None else []}
        if fn.kind == "weight":
            return Bound("fn_weight", scalars={"weight": fn.weight},
                         children=children)
        if fn.kind == "field_value_factor":
            has_col = fn.field in self.seg.numerics
            return Bound("fn_fvf", f"{fn.field}|{fn.modifier}|{int(has_col)}",
                         scalars={"factor": fn.factor, "missing": fn.missing,
                                  "weight": fn.weight}, children=children)
        if fn.kind == "random_score":
            return Bound("fn_random", scalars={"seed": fn.seed,
                                               "weight": fn.weight},
                         children=children)
        if fn.kind in ("gauss", "exp", "linear"):
            if fn.scale is None:
                raise QueryParsingError(
                    f"decay function on [{fn.field}] requires [scale]")
            has_col = fn.field in self.seg.numerics
            origin = self._resolve_decay_value(fn.field, fn.origin, False) \
                if has_col else 0.0
            scale = self._resolve_decay_value(fn.field, fn.scale, True) \
                if has_col else 1.0
            offset = self._resolve_decay_value(fn.field, fn.offset, True) \
                if has_col else 0.0
            return Bound("fn_decay", f"{fn.field}|{fn.kind}|{int(has_col)}",
                         scalars={"origin": origin, "scale": scale,
                                  "offset": offset, "decay": fn.decay,
                                  "weight": fn.weight}, children=children)
        if fn.kind == "script_score":
            from ..script import compile_script
            from ..script.service import numeric_param
            cs = compile_script(fn.script)
            ensure_script_vals(self.seg, cs.fields)
            pnames = ",".join(n for n, _ in fn.script_params)
            scalars = {"weight": fn.weight}
            for name, val in fn.script_params:
                scalars[f"p_{name}"] = numeric_param(name, val)
            return Bound("fn_script", f"{fn.script}\x00{pnames}",
                         scalars=scalars, children=children)
        raise QueryParsingError(f"unknown score function [{fn.kind}]")

    def _bind_FunctionScoreQuery(self, q: FunctionScoreQuery) -> Bound:
        mode_tag = (f"{q.score_mode}|{q.boost_mode}|"
                    f"{int(q.min_score is not None)}")
        return Bound(
            "fnscore", mode_tag,
            scalars={"max_boost": q.max_boost,
                     "min_score": (q.min_score if q.min_score is not None
                                   else 0.0),
                     "boost": q.boost},
            children={"q": [self.bind(q.query)],
                      "fns": [self._bind_fn(f) for f in q.functions]})


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Banded Levenshtein <= k (host-side fuzzy expansion)."""
    la, lb = len(a), len(b)
    if abs(la - lb) > k:
        return False
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        lo = max(1, i - k)
        hi = min(lb, i + k)
        if lo > 1:
            cur[lo - 1] = k + 1
        for j in range(lo, hi + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (a[i - 1] != b[j - 1]))
        if min(cur[max(0, lo - 1):hi + 1]) > k:
            return False
        prev = cur
    return prev[lb] <= k


# ---------------------------------------------------------------------------
# finalize: Bound trees (a batch with identical structure) -> (desc, params)
# ---------------------------------------------------------------------------


def finalize(bounds: Sequence[Bound]) -> tuple[tuple, tuple]:
    """Stack a batch of structurally-identical bound queries.

    Returns (desc, params): desc is the hashable static program structure;
    params is a pytree of stacked np arrays with leading dim B.
    """
    sig = bounds[0].signature()
    for b in bounds[1:]:
        if b.signature() != sig:
            raise ValueError("cannot batch queries with different plans")
    return _finalize_node(bounds)


def _finalize_node(bounds: Sequence[Bound]) -> tuple[tuple, tuple]:
    b0 = bounds[0]
    kind = b0.kind
    B = len(bounds)

    def stack_scalar(name, dtype):
        return np.asarray([b.scalars[name] for b in bounds], dtype=dtype)

    if kind == "none":
        return ("none",), ()
    if kind == "match_all":
        return ("match_all",), (stack_scalar("boost", np.float32),)
    if kind == "term_text":
        return (("term_text", b0.field),
                (stack_scalar("tid", np.int32),
                 stack_scalar("weight", np.float32)))
    if kind == "term_text_sc":
        nb_pad = next_pow2(max(b.scalars["nb"] for b in bounds), floor=1)
        return (("term_text_sc", b0.field, nb_pad),
                (stack_scalar("block_lo", np.int32),
                 stack_scalar("nb", np.int32),
                 stack_scalar("weight", np.float32)))
    if kind == "terms_dense":
        q_pad = next_pow2(max(b.arrays["tids"].size for b in bounds), floor=1)
        qt = np.full((B, q_pad), -1, dtype=np.int32)
        wq = np.zeros((B, q_pad), dtype=np.float32)
        for i, b in enumerate(bounds):
            t = b.arrays["tids"]
            qt[i, : t.size] = t
            wq[i, : t.size] = b.arrays["weights"]
        return ("terms_dense", b0.field, q_pad), (qt, wq)
    if kind in ("terms_fused", "terms_fused_w"):
        m_pad = next_pow2(max(b.arrays["blocks"].size for b in bounds), floor=1)
        gather = np.full((B, m_pad), -1, dtype=np.int32)
        weights = np.zeros((B, m_pad), dtype=np.float32)
        for i, b in enumerate(bounds):
            blocks = b.arrays["blocks"]
            gather[i, :blocks.size] = blocks
            if kind == "terms_fused_w":
                weights[i, :blocks.size] = b.arrays["weights"]
            else:
                weights[i, :blocks.size] = b.scalars["weight"]
        return ("terms_fused", b0.field, m_pad), (gather, weights)
    if kind == "term_kw":
        return (("term_kw", b0.field),
                (stack_scalar("ord", np.int32), stack_scalar("score", np.float32)))
    if kind == "ord_set":
        card = next_pow2(max(b.arrays["ords"].size for b in bounds), floor=1)
        card_total = int(b0.scalars["card_total"])
        ords = np.full((B, card), card_total, dtype=np.int32)  # pad -> sentinel col
        for i, b in enumerate(bounds):
            o = b.arrays["ords"]
            ords[i, :o.size] = o
        return (("ord_set", b0.field, card, card_total),
                (ords, stack_scalar("boost", np.float32)))
    if kind == "term_num":
        return (("term_num", b0.field),
                (np.asarray([b.scalars["value"] for b in bounds]),
                 stack_scalar("score", np.float32)))
    if kind == "range_int":
        return (("range_int", b0.field),
                (stack_scalar("lo", np.int32), stack_scalar("hi", np.int32),
                 stack_scalar("boost", np.float32)))
    if kind == "range_f32":
        return (("range_f32", b0.field),
                (stack_scalar("lo", np.float32), stack_scalar("hi", np.float32),
                 stack_scalar("boost", np.float32)))
    if kind == "range_kw":
        return (("range_kw", b0.field),
                (stack_scalar("lo", np.int32), stack_scalar("hi", np.int32),
                 stack_scalar("boost", np.float32)))
    if kind == "knn_vec":
        # similarity is static (compiled into the transform); the query
        # vector + boost are the dynamic params, so coalesced knn
        # searches with different vectors share one compiled program
        return (("knn_vec", b0.field, b0.scalars["sim"]),
                (np.stack([b.arrays["qv"] for b in bounds]),
                 stack_scalar("boost", np.float32)))
    if kind in ("exists_text", "exists_kw", "exists_num", "exists_gv"):
        return ((kind, b0.field), ())
    if kind == "ids":
        return ("ids",), (np.stack([b.arrays["mask"] for b in bounds]),)
    if kind == "docs_w":
        # precomputed host posting list (phrase/span matches): pad with
        # doc 0 / impact 0 — scatter-adding zero is a no-op
        n_pad = next_pow2(max(b.arrays["docs"].size for b in bounds), floor=1)
        docs = np.zeros((B, n_pad), dtype=np.int32)
        imps = np.zeros((B, n_pad), dtype=np.float32)
        for i, b in enumerate(bounds):
            d = b.arrays["docs"]
            docs[i, : d.size] = d
            imps[i, : d.size] = b.arrays["imps"]
        return ("docs_w", n_pad), (docs, imps)
    head = positional_prefix(kind) if isinstance(kind, str) else None
    if head in ("phrase_pos", "span_pos"):
        # n rides in the kind string (a static), so every bound in the
        # batch shares qt/wb width; slop is DYNAMIC — sloppiness only
        # (slop > 0) is compiled in, the slop value is a traced param
        return ((kind, b0.field),
                (np.stack([b.arrays["qt"] for b in bounds]),
                 np.stack([b.arrays["wb"] for b in bounds]),
                 stack_scalar("idf_sum", np.float32),
                 stack_scalar("slop", np.int32),
                 stack_scalar("boost", np.float32)))
    if head == "bm25f":
        return ((kind, b0.field),
                (np.stack([b.arrays["qt"] for b in bounds]),
                 np.stack([b.arrays["idf"] for b in bounds]),
                 np.stack([b.arrays["wf"] for b in bounds]),
                 stack_scalar("boost", np.float32)))
    if kind == "bool":
        descs = {}
        params = {}
        for group in ("must", "should", "must_not", "filter"):
            pairs = [_finalize_node([b.children[group][i] for b in bounds])
                     for i in range(len(b0.children[group]))]
            descs[group] = tuple(d for d, _ in pairs)
            params[group] = tuple(p for _, p in pairs)
        return (("bool", descs["must"], descs["should"], descs["must_not"],
                 descs["filter"]),
                (params["must"], params["should"], params["must_not"],
                 params["filter"],
                 stack_scalar("msm", np.int32), stack_scalar("boost", np.float32)))
    if kind == "const":
        d, p = _finalize_node([b.children["q"][0] for b in bounds])
        return ("const", d), (p, stack_scalar("boost", np.float32))
    if kind == "nested":
        d, p = _finalize_node([b.children["q"][0] for b in bounds])
        return (("nested", d, b0.field),        # field = score_mode (static)
                (p, np.stack([b.arrays["path_mask"] for b in bounds]),
                 stack_scalar("boost", np.float32)))
    if kind == "parents_match":
        d, p = _finalize_node([b.children["q"][0] for b in bounds])
        return (("parents_match", d),
                (p, np.stack([b.arrays["plive"] for b in bounds])))
    if kind == "boosting":
        dp, pp = _finalize_node([b.children["pos"][0] for b in bounds])
        dn, pn = _finalize_node([b.children["neg"][0] for b in bounds])
        return (("boosting", dp, dn),
                (pp, pn, stack_scalar("negative_boost", np.float32)))
    if kind == "fnscore":
        qd, qp = _finalize_node([b.children["q"][0] for b in bounds])
        fn_descs = []
        fn_params = []
        for i in range(len(b0.children["fns"])):
            fd, fp = _finalize_node([b.children["fns"][i] for b in bounds])
            fn_descs.append(fd)
            fn_params.append(fp)
        return (("fnscore", qd, tuple(fn_descs), b0.field),
                (qp, tuple(fn_params),
                 stack_scalar("max_boost", np.float32),
                 stack_scalar("min_score", np.float32),
                 stack_scalar("boost", np.float32)))
    if kind == "geo_distance":
        return (("geo_distance", b0.field),
                (stack_scalar("lat", np.float32),
                 stack_scalar("lon", np.float32),
                 stack_scalar("to_m", np.float32),
                 stack_scalar("from_m", np.float32),
                 stack_scalar("boost", np.float32)))
    if kind == "geo_bbox":
        return (("geo_bbox", b0.field),
                (stack_scalar("top", np.float32),
                 stack_scalar("left", np.float32),
                 stack_scalar("bottom", np.float32),
                 stack_scalar("right", np.float32),
                 stack_scalar("boost", np.float32)))
    if kind == "geo_polygon":
        # pad to pow2 vertices +1 closing vertex; padding repeats the
        # last vertex so padded edges are degenerate (no ray crossings)
        p_pad = next_pow2(max(b.scalars["n"] for b in bounds) + 1, floor=4)
        lats = np.zeros((B, p_pad), dtype=np.float32)
        lons = np.zeros((B, p_pad), dtype=np.float32)
        for i, b in enumerate(bounds):
            la, lo = b.arrays["lats"], b.arrays["lons"]
            n = la.size
            lats[i, :n] = la
            lons[i, :n] = lo
            lats[i, n:] = la[0]  # close the ring, then repeat
            lons[i, n:] = lo[0]
        return (("geo_polygon", b0.field, p_pad),
                (lats, lons, stack_scalar("boost", np.float32)))
    if kind == "script_q":
        pnames = [n for n in b0.field.split("\x00", 1)[1].split(",") if n]
        own = tuple(stack_scalar(f"p_{n}", np.float32) for n in pnames) + \
            (stack_scalar("boost", np.float32),)
        return (("script_q", b0.field), own)
    if kind == "fn_script":
        flt = b0.children.get("filter", [])
        fdesc, fparams = (None, ())
        if flt:
            fdesc, fparams = _finalize_node([b.children["filter"][0]
                                             for b in bounds])
        pnames = [n for n in b0.field.split("\x00", 1)[1].split(",") if n]
        own = tuple(stack_scalar(f"p_{n}", np.float32) for n in pnames) + \
            (stack_scalar("weight", np.float32),)
        return (("fn_script", b0.field, fdesc), (own, fparams))
    if kind in ("fn_weight", "fn_fvf", "fn_random", "fn_decay"):
        flt = b0.children.get("filter", [])
        fdesc, fparams = (None, ())
        if flt:
            fdesc, fparams = _finalize_node([b.children["filter"][0]
                                             for b in bounds])
        if kind == "fn_weight":
            own = (stack_scalar("weight", np.float32),)
        elif kind == "fn_fvf":
            own = (stack_scalar("factor", np.float32),
                   stack_scalar("missing", np.float32),
                   stack_scalar("weight", np.float32))
        elif kind == "fn_random":
            own = (stack_scalar("seed", np.uint32),
                   stack_scalar("weight", np.float32))
        else:
            own = (stack_scalar("origin", np.float32),
                   stack_scalar("scale", np.float32),
                   stack_scalar("offset", np.float32),
                   stack_scalar("decay", np.float32),
                   stack_scalar("weight", np.float32))
        return ((kind, b0.field, fdesc), (own, fparams))
    raise QueryParsingError(f"unknown bound node [{kind}]")


# ---------------------------------------------------------------------------
# Device evaluation (desc interpreter — runs under jit)
# ---------------------------------------------------------------------------


def eval_node(desc: tuple, params: tuple, seg: dict, cap: int, B: int
              ) -> tuple[jax.Array, jax.Array]:
    """Returns (score [B, cap] f32, match [B, cap] bool)."""
    kind = desc[0]
    if kind == "none":
        z = jnp.zeros((B, cap), jnp.float32)
        return z, jnp.zeros((B, cap), bool)
    if kind == "match_all":
        (boost,) = params
        ones = jnp.ones((B, cap), bool)
        return jnp.broadcast_to(boost[:, None], (B, cap)).astype(jnp.float32), ones
    if kind == "term_text":
        # forward-index gather (see terms_dense); tid -1 = absent term,
        # which only matches zero-impact padding slots -> no match
        _, field = desc
        tid, weight = params
        t = seg["text"][field]
        tids, imps = t["fwd_tids"], t["fwd_imps"]
        if pallas_enabled():
            score = score_terms_dense_pallas(tids, imps, tid[:, None],
                                             weight[:, None],
                                             interpret=interpret_mode())
        else:
            contrib = jnp.sum(jnp.where(tids[None] == tid[:, None, None],
                                        imps[None], 0.0), axis=-1)
            score = contrib * weight[:, None]
        return score, score > 0
    if kind == "term_text_sc":
        # posting-scatter path (fields whose forward index exceeded the
        # width cap)
        _, field, nb_pad = desc
        block_lo, nb, weight = params
        t = seg["text"][field]
        if pallas_enabled():
            score = score_term_pallas(t["block_docs"], t["block_imps"],
                                      block_lo, nb, weight, nb_pad, cap,
                                      interpret=interpret_mode())
        else:
            score = score_term(t["block_docs"], t["block_imps"],
                               block_lo, nb, weight, nb_pad, cap)
        return score, score > 0
    if kind == "terms_fused":
        _, field, _m = desc
        gather, weights = params
        t = seg["text"][field]
        if pallas_enabled():
            score = score_terms_fused_pallas(
                t["block_docs"], t["block_imps"], gather, weights, cap,
                interpret=interpret_mode())
        else:
            score = score_terms_fused(t["block_docs"], t["block_imps"],
                                      gather, weights, cap)
        return score, score > 0
    if kind == "terms_dense":
        # forward-index gather path: per doc slot, compare its term id to
        # each query term and FMA the eager impact — no scatter, pure VPU
        _, field, q_pad = desc
        qt, wq = params                           # [B, Qp]
        t = seg["text"][field]
        tids, imps = t["fwd_tids"], t["fwd_imps"]  # [cap, L]
        if pallas_enabled():
            score = score_terms_dense_pallas(tids, imps, qt, wq,
                                             interpret=interpret_mode())
            return score, score > 0
        score = jnp.zeros((B, cap), jnp.float32)
        for qi in range(q_pad):
            tq = qt[:, qi][:, None, None]          # [B,1,1]
            contrib = jnp.sum(
                jnp.where(tids[None] == tq, imps[None], 0.0), axis=-1)
            score = score + contrib * wq[:, qi][:, None]
        return score, score > 0
    if kind == "docs_w":
        docs, imps = params                         # [B, n] each
        score = jnp.zeros((B, cap), jnp.float32).at[
            jnp.arange(B)[:, None], docs].add(imps)
        return score, score > 0
    if isinstance(kind, str) and positional_prefix(kind):
        # positional clause (phrase/span/BM25F), unfused reference: the
        # SAME per-doc leaf evaluator the fused tile walk runs, applied
        # to the whole capacity as one "tile" — elementwise over docs,
        # so full-cap == tile-by-tile bit-identically
        _, field = desc
        ones_i = jnp.ones((B,), jnp.int32)
        ones_f = jnp.ones((B,), jnp.float32)
        inp = tuple(params) + (ones_i, ones_f)
        text_tiles = {}
        pos_tiles = {}
        for f in clause_fields(field):
            t = seg["text"][f]
            text_tiles[f] = (t["fwd_tids"], t["fwd_imps"])
            pos_tiles[f] = (t["fwd_pos"], t["k1ln"], t["lnorm"])
        s_leaf, m_leaf = positional_tile_scores(kind, field, inp,
                                                text_tiles, pos_tiles)
        return jnp.where(m_leaf, s_leaf, 0.0), m_leaf
    if kind == "knn_vec":
        # vector similarity clause: one whole-capacity MXU matmul —
        # the SAME column the fused bundle engine slices per tile
        # (_vec_clause_inputs), so fused and unfused hybrid scores are
        # bit-identical
        _, field, sim = desc
        qv, boost = params                          # [B, D], [B]
        v = seg["vec"][field]
        col = knn_score_column(v["values"], v["norms"], v["exists"], qv,
                               similarity=sim)
        match = jnp.broadcast_to(v["exists"][None, :], (B, cap))
        return col * boost[:, None], match
    if kind == "nested":
        # block-join to-parent projection (ToParentBlockJoinQuery)
        _, inner_desc, score_mode = desc
        inner_params, path_mask, boost = params
        c_score, c_match = eval_node(inner_desc, inner_params, seg, cap, B)
        ok = c_match & path_mask & seg["nested"]["is_child"][None, :]
        target = seg["nested"]["target"]
        cs = jnp.where(ok, c_score, 0.0)
        cnt = jnp.zeros((B, cap), jnp.float32).at[:, target].add(
            ok.astype(jnp.float32))
        match = cnt > 0
        if score_mode == "none":
            score = jnp.where(match, boost[:, None], 0.0)
        elif score_mode == "max":
            mx = jnp.full((B, cap), -jnp.inf).at[:, target].max(
                jnp.where(ok, cs, -jnp.inf))
            score = jnp.where(match, mx, 0.0) * boost[:, None]
        elif score_mode == "min":
            mn = jnp.full((B, cap), jnp.inf).at[:, target].min(
                jnp.where(ok, cs, jnp.inf))
            score = jnp.where(match, mn, 0.0) * boost[:, None]
        else:
            total = jnp.zeros((B, cap), jnp.float32).at[:, target].add(cs)
            if score_mode == "avg":
                total = total / jnp.maximum(cnt, 1.0)
            score = jnp.where(match, total, 0.0) * boost[:, None]
        return score, match
    if kind == "parents_match":
        (inner_desc,) = desc[1:]
        inner_params, plive = params
        p_score, p_match = eval_node(inner_desc, inner_params, seg, cap, B)
        pm = p_match & plive
        target = seg["nested"]["target"]
        match = jnp.take_along_axis(
            pm, jnp.broadcast_to(target[None, :], (B, cap)), axis=1) \
            & seg["nested"]["is_child"][None, :]
        return match.astype(jnp.float32), match
    if kind == "term_kw":
        _, field = desc
        ordv, scorev = params
        if field in seg.get("kw_mv", {}):
            mv = seg["kw_mv"][field]          # [cap, M]
            match = jnp.any(mv[None] == ordv[:, None, None], axis=-1) \
                & (ordv[:, None] >= 0)
        else:
            ords = seg["kw"][field]
            match = (ords[None, :] == ordv[:, None]) & (ordv[:, None] >= 0)
        return jnp.where(match, scorev[:, None], 0.0), match
    if kind == "ord_set":
        # membership via a [B, card_total+1] table instead of a
        # [B, cap, set] broadcast compare (which would blow HBM)
        _, field, _card, card_total = desc
        ord_sets, boost = params           # [B, card] (pad = card_total), [B]
        tbl = jnp.zeros((B, card_total + 1), bool).at[
            jnp.arange(B)[:, None], ord_sets].set(True)
        if field in seg.get("kw_mv", {}):
            mv = seg["kw_mv"][field]        # [cap, M]
            safe = jnp.clip(mv, 0, None)
            hit = jax.vmap(lambda t: t[safe])(tbl) & (mv >= 0)[None]
            match = jnp.any(hit, axis=-1)
        else:
            ords = seg["kw"][field]
            safe = jnp.clip(ords, 0, None)
            match = jax.vmap(lambda t: t[safe])(tbl) & (ords >= 0)[None, :]
        return jnp.where(match, boost[:, None], 0.0), match
    if kind == "term_num":
        _, field = desc
        value, scorev = params
        col = seg["num"][field]
        if "mv_values" in col:
            match = jnp.any((col["mv_values"][None] == value[:, None, None])
                            & col["mv_exists"][None], axis=-1)
        else:
            match = (col["values"][None, :] == value[:, None]) \
                & col["exists"][None, :]
        return jnp.where(match, scorev[:, None], 0.0), match
    if kind in ("range_int", "range_f32"):
        _, field = desc
        lo, hi, boost = params
        col = seg["num"][field]
        if "mv_values" in col:
            v = col["mv_values"][None]      # [1, cap, M]
            match = jnp.any((v >= lo[:, None, None])
                            & (v <= hi[:, None, None])
                            & col["mv_exists"][None], axis=-1)
        else:
            v = col["values"][None, :]
            match = (v >= lo[:, None]) & (v <= hi[:, None]) \
                & col["exists"][None, :]
        return jnp.where(match, boost[:, None], 0.0), match
    if kind == "range_kw":
        _, field = desc
        lo, hi, boost = params
        if field in seg.get("kw_mv", {}):
            mv = seg["kw_mv"][field][None]  # [1, cap, M]
            match = jnp.any((mv >= lo[:, None, None])
                            & (mv <= hi[:, None, None]), axis=-1)
        else:
            ords = seg["kw"][field][None, :]
            match = (ords >= lo[:, None]) & (ords <= hi[:, None]) \
                & (ords >= 0)
        return jnp.where(match, boost[:, None], 0.0), match
    if kind == "exists_text":
        _, field = desc
        m = (seg["text"][field]["doc_len"] > 0)[None, :]
        m = jnp.broadcast_to(m, (B, cap))
        return m.astype(jnp.float32), m
    if kind == "exists_kw":
        _, field = desc
        m = (seg["kw"][field] >= 0)[None, :]
        m = jnp.broadcast_to(m, (B, cap))
        return m.astype(jnp.float32), m
    if kind == "exists_num":
        _, field = desc
        m = seg["num"][field]["exists"][None, :]
        m = jnp.broadcast_to(m, (B, cap))
        return m.astype(jnp.float32), m
    if kind == "exists_gv":
        _, tag = desc
        col_kind, field = tag.split("\x00", 1)
        group = "geo" if col_kind == "geo" else "vec"
        m = seg[group][field]["exists"][None, :]
        m = jnp.broadcast_to(m, (B, cap))
        return m.astype(jnp.float32), m
    if kind == "ids":
        (mask,) = params
        return mask.astype(jnp.float32), mask
    if kind == "bool":
        _, d_must, d_should, d_not, d_filter = desc
        p_must, p_should, p_not, p_filter, msm, boost = params
        score = jnp.zeros((B, cap), jnp.float32)
        must_ok = jnp.ones((B, cap), bool)
        for d, p in zip(d_must, p_must):
            s, m = eval_node(d, p, seg, cap, B)
            score = score + jnp.where(m, s, 0.0)
            must_ok = must_ok & m
        for d, p in zip(d_filter, p_filter):
            _, m = eval_node(d, p, seg, cap, B)
            must_ok = must_ok & m
        not_any = jnp.zeros((B, cap), bool)
        for d, p in zip(d_not, p_not):
            _, m = eval_node(d, p, seg, cap, B)
            not_any = not_any | m
        should_cnt = jnp.zeros((B, cap), jnp.int32)
        for d, p in zip(d_should, p_should):
            s, m = eval_node(d, p, seg, cap, B)
            score = score + jnp.where(m, s, 0.0)
            should_cnt = should_cnt + m.astype(jnp.int32)
        match = must_ok & (~not_any) & (should_cnt >= msm[:, None])
        return score * boost[:, None], match
    if kind == "const":
        _, d_child = desc
        p_child, boost = params
        _, m = eval_node(d_child, p_child, seg, cap, B)
        return jnp.where(m, boost[:, None], 0.0), m
    if kind == "boosting":
        _, d_pos, d_neg = desc
        p_pos, p_neg, nboost = params
        s, m = eval_node(d_pos, p_pos, seg, cap, B)
        _, mn = eval_node(d_neg, p_neg, seg, cap, B)
        s = jnp.where(mn, s * nboost[:, None], s)
        return s, m
    if kind == "fnscore":
        # ref: common/lucene/search/function/FunctionScoreQuery.java —
        # combine the child score with per-doc function factors
        _, qdesc, fn_descs, mode_tag = desc
        qparams, fn_params, max_boost, min_score, boost = params
        score_mode, boost_mode, has_min = mode_tag.split("|")
        s, m = eval_node(qdesc, qparams, seg, cap, B)
        factors: list[jax.Array] = []
        applies: list[jax.Array] = []
        seg_fn = dict(seg)
        seg_fn["_score_ctx"] = s  # script_score's _score binding
        for fd, fp in zip(fn_descs, fn_params):
            f, a = _eval_score_fn(fd, fp, seg_fn, cap, B)
            factors.append(f)
            applies.append(a)
        if not factors:
            combined = jnp.ones((B, cap), jnp.float32)
        elif score_mode == "sum":
            combined = sum(jnp.where(a, f, 0.0)
                           for f, a in zip(factors, applies))
        elif score_mode == "avg":
            tot = sum(jnp.where(a, f, 0.0) for f, a in zip(factors, applies))
            cnt = sum(a.astype(jnp.float32) for a in applies)
            combined = jnp.where(cnt > 0, tot / jnp.maximum(cnt, 1.0), 1.0)
        elif score_mode == "max":
            stk = jnp.stack([jnp.where(a, f, -jnp.inf)
                             for f, a in zip(factors, applies)])
            mx = jnp.max(stk, axis=0)
            combined = jnp.where(jnp.isfinite(mx), mx, 1.0)
        elif score_mode == "min":
            stk = jnp.stack([jnp.where(a, f, jnp.inf)
                             for f, a in zip(factors, applies)])
            mn_ = jnp.min(stk, axis=0)
            combined = jnp.where(jnp.isfinite(mn_), mn_, 1.0)
        elif score_mode == "first":
            combined = jnp.ones((B, cap), jnp.float32)
            for f, a in zip(reversed(factors), reversed(applies)):
                combined = jnp.where(a, f, combined)
        else:  # multiply (default)
            combined = jnp.ones((B, cap), jnp.float32)
            for f, a in zip(factors, applies):
                combined = combined * jnp.where(a, f, 1.0)
        combined = jnp.minimum(combined, max_boost[:, None])
        if boost_mode == "replace":
            new = combined
        elif boost_mode == "sum":
            new = s + combined
        elif boost_mode == "avg":
            new = (s + combined) / 2.0
        elif boost_mode == "max":
            new = jnp.maximum(s, combined)
        elif boost_mode == "min":
            new = jnp.minimum(s, combined)
        else:  # multiply
            new = s * combined
        new = new * boost[:, None]
        if has_min == "1":
            m = m & (new >= min_score[:, None])
        # keep the positive-score match invariant of the scoring paths
        new = jnp.where(m, jnp.maximum(new, _F32_MIN_WEIGHT), 0.0)
        return new, m
    if kind == "script_q":
        _, tag = desc
        boost = params[-1]
        val = _eval_device_script(tag, params[:-1], seg, cap, B)
        m = val != 0 if val.dtype != bool else val
        score = jnp.where(m, jnp.maximum(boost[:, None], _F32_MIN_WEIGHT), 0.0)
        return score, m
    if kind == "geo_distance":
        from ..ops.geo import haversine_m
        _, field = desc
        lat_q, lon_q, to_m, from_m, boost = params
        g = seg["geo"][field]
        d = haversine_m(g["lat"][None, :], g["lon"][None, :],
                        lat_q[:, None], lon_q[:, None])
        m = g["exists"][None, :] & (d <= to_m[:, None]) & \
            (d >= from_m[:, None])
        return jnp.where(m, jnp.maximum(boost[:, None], _F32_MIN_WEIGHT),
                         0.0), m
    if kind == "geo_bbox":
        _, field = desc
        top, left, bottom, right, boost = params
        g = seg["geo"][field]
        lat = g["lat"][None, :]
        lon = g["lon"][None, :]
        lat_ok = (lat <= top[:, None]) & (lat >= bottom[:, None])
        # date-line crossing: left > right means the box wraps
        wraps = (left > right)[:, None]
        in_plain = (lon >= left[:, None]) & (lon <= right[:, None])
        in_wrap = (lon >= left[:, None]) | (lon <= right[:, None])
        m = g["exists"][None, :] & lat_ok & \
            jnp.where(wraps, in_wrap, in_plain)
        return jnp.where(m, jnp.maximum(boost[:, None], _F32_MIN_WEIGHT),
                         0.0), m
    if kind == "geo_polygon":
        _, field, p_pad = desc
        lats, lons, boost = params                  # [B, P], [B, P], [B]
        g = seg["geo"][field]
        y = g["lat"][None, :]                       # [1, cap]
        x = g["lon"][None, :]
        inside = jnp.zeros((B, cap), bool)
        # ray cast edge-by-edge (static unroll over padded vertex count;
        # arrays stay [B, cap] so HBM use is independent of P)
        for i in range(p_pad - 1):
            yi = lats[:, i][:, None]
            yj = lats[:, i + 1][:, None]
            xi = lons[:, i][:, None]
            xj = lons[:, i + 1][:, None]
            straddles = (yi > y) != (yj > y)
            denom = jnp.where(yj - yi == 0.0, 1e-12, yj - yi)
            x_cross = (xj - xi) * (y - yi) / denom + xi
            inside = inside ^ (straddles & (x < x_cross))
        m = g["exists"][None, :] & inside
        return jnp.where(m, jnp.maximum(boost[:, None], _F32_MIN_WEIGHT),
                         0.0), m
    raise QueryParsingError(f"unknown desc node [{kind}]")


def _eval_device_script(tag: str, own: tuple, seg: dict, cap: int, B: int,
                        score: jax.Array | None = None) -> jax.Array:
    """Run a compiled expression inside the device program.

    `tag` = "source\\x00p1,p2" (static, part of the jit cache key); `own`
    = stacked [B] param arrays in tag order (+ trailing weight/boost the
    caller consumes). Columns broadcast [cap] x params [B,1] -> [B,cap].
    """
    from ..script import compile_script, ColumnDocAccessor
    src, pname_str = tag.split("\x00", 1)
    pnames = [n for n in pname_str.split(",") if n]
    cs = compile_script(src)
    params = {n: own[i][:, None] for i, n in enumerate(pnames)}
    bindings = {}
    if score is not None:
        bindings["_score"] = score
    val = cs.run(doc=ColumnDocAccessor(seg, jnp), params=params,
                 bindings=bindings, xp=jnp)
    val = jnp.asarray(val)
    return jnp.broadcast_to(val, (B, cap))


def _eval_agg_script(tag: str, seg: dict, cap: int, B: int) -> jax.Array:
    """Aggregation-script variant of _eval_device_script: params are
    static floats encoded in the tag ("src\\x00k=v,...")."""
    from ..script import compile_script, ColumnDocAccessor
    src, ptag = tag.split("\x00", 1)
    params = {}
    for pair in ptag.split(","):
        if pair:
            k, v = pair.split("=", 1)
            params[k] = float(v)
    cs = compile_script(src)
    val = cs.run(doc=ColumnDocAccessor(seg, jnp), params=params,
                 bindings={}, xp=jnp)
    return jnp.broadcast_to(jnp.asarray(val), (B, cap))


def _eval_score_fn(desc: tuple, params: tuple, seg: dict, cap: int, B: int
                   ) -> tuple[jax.Array, jax.Array]:
    """One score function -> (factor [B,cap], applicable [B,cap])."""
    kind, tag, fdesc = desc
    own, fparams = params
    if fdesc is not None:
        _, applicable = eval_node(fdesc, fparams, seg, cap, B)
    else:
        applicable = jnp.ones((B, cap), bool)
    if kind == "fn_weight":
        (weight,) = own
        return jnp.broadcast_to(weight[:, None], (B, cap)), applicable
    if kind == "fn_script":
        weight = own[-1]
        # _score binding: scripts in function_score see the inner query
        # score — passed via seg["_score_ctx"] set by the fnscore branch
        val = _eval_device_script(tag, own[:-1], seg, cap, B,
                                  score=seg.get("_score_ctx"))
        return val.astype(jnp.float32) * weight[:, None], applicable
    if kind == "fn_random":
        seed, weight = own
        idx = jnp.arange(cap, dtype=jnp.uint32)[None, :]
        h = idx * jnp.uint32(2654435761) + seed[:, None] * jnp.uint32(40503)
        h = h ^ (h >> 15)
        h = h * jnp.uint32(2246822519)
        h = h ^ (h >> 13)
        u = h.astype(jnp.float32) / jnp.float32(2 ** 32)
        return u * weight[:, None], applicable
    field, shape_or_mod, has_col = tag.split("|")
    if has_col == "0":
        # column absent in this segment: fvf -> missing value; decay -> 1
        if kind == "fn_fvf":
            factor, missing, weight = own
            val = jnp.broadcast_to(missing[:, None], (B, cap))
            return _apply_fvf_modifier(val, shape_or_mod) * weight[:, None], \
                applicable
        weight = own[-1]
        return jnp.ones((B, cap), jnp.float32) * weight[:, None], applicable
    col = seg["num"][field]
    vals = col["values"].astype(jnp.float32)[None, :]
    exists = col["exists"][None, :]
    if kind == "fn_fvf":
        factor, missing, weight = own
        val = jnp.where(exists, vals * factor[:, None], missing[:, None])
        return _apply_fvf_modifier(val, shape_or_mod) * weight[:, None], \
            applicable
    # decay functions (ref: functionscore/DecayFunctionBuilder.java)
    origin, scale, offset, decay, weight = own
    d = jnp.maximum(jnp.abs(vals - origin[:, None]) - offset[:, None], 0.0)
    ln_decay = jnp.log(decay[:, None])
    if shape_or_mod == "gauss":
        sigma2 = -(scale[:, None] ** 2) / (2.0 * ln_decay)
        f = jnp.exp(-(d ** 2) / (2.0 * sigma2))
    elif shape_or_mod == "exp":
        lam = ln_decay / scale[:, None]
        f = jnp.exp(lam * d)
    else:  # linear
        s_ = scale[:, None] / (1.0 - decay[:, None])
        f = jnp.maximum((s_ - d) / s_, 0.0)
    f = jnp.where(exists, f, 1.0)
    return f * weight[:, None], applicable


def _apply_fvf_modifier(val: jax.Array, modifier: str) -> jax.Array:
    """Ref: common/lucene/search/function/FieldValueFactorFunction.Modifier."""
    if modifier == "none":
        return val
    if modifier == "log":
        return jnp.log10(jnp.maximum(val, 1e-9))
    if modifier == "log1p":
        return jnp.log10(jnp.maximum(val, 0.0) + 1.0)
    if modifier == "log2p":
        return jnp.log10(jnp.maximum(val, 0.0) + 2.0)
    if modifier == "ln":
        return jnp.log(jnp.maximum(val, 1e-9))
    if modifier == "ln1p":
        return jnp.log1p(jnp.maximum(val, 0.0))
    if modifier == "ln2p":
        return jnp.log(jnp.maximum(val, 0.0) + 2.0)
    if modifier == "square":
        return val * val
    if modifier == "sqrt":
        return jnp.sqrt(jnp.maximum(val, 0.0))
    if modifier == "reciprocal":
        return 1.0 / jnp.maximum(val, 1e-9)
    raise SearchParseError(f"unknown field_value_factor modifier [{modifier}]")


# ---------------------------------------------------------------------------
# Fused block-max score + top-k: plan classifier, backend autotuner, stats
#
# The unfused program materializes a full [B, cap] score matrix, then
# lax.top_k's it. Plans the classifier below can express as a CLAUSE
# BUNDLE (ops/scoring.py: dense-text must/should scoring clauses incl.
# boosted single-should wrappers, dense or numeric-range filter /
# must_not masks, dynamic msm/boost) instead route through the fused
# block-max-WAND ops (ops/scoring.score_topk_bundle_fused /
# ops/pallas_scoring.fused_topk_bundle_pallas): SCORE_TILE-doc tiles
# with a running top-k and block-max pruning off the pack-time tile_max
# summaries. Both engines take the same calling convention and cover
# the same matrix — multi-field bundles, range masks, emit-match (k>0
# plans that ALSO carry aggregations have the tile loop write the exact
# match mask, which feeds the ordinary aggregation pass — still never
# materializing the [B, cap] score matrix), and the mask-only k == 0
# pass. Which backend wins is shape- and data-dependent (the round-5
# bench had Pallas LOSING to XLA on http_logs), so the first execution
# of each (pack, shape-bucket) key warms both backends and takes the
# best-of-N wall clock of each; choices AND both timings persist
# across restarts under the node data path, keyed by the pack
# fingerprint (a refreshed pack re-tunes under its new fingerprint),
# and shapes where an admitted pallas candidate lost by >10% surface
# in nodes_stats()["fused_scoring"].loss_audit.
# ---------------------------------------------------------------------------

import json as _json
import os as _os
import threading as _threading
import time as _time

# the clause-kind partition is owned by ops/scoring.py — importing it
# keeps the admission classifier and the bundle engine from drifting
from ..ops.scoring import (DENSE_CLAUSE_KINDS as _FUSED_DENSE_KINDS,
                           RANGE_CLAUSE_KINDS as _FUSED_RANGE_KINDS,
                           VEC_CLAUSE_KINDS as _FUSED_VEC_KINDS)
# tiered tile residency (index/tiering.py): HBM as a cache over
# host-RAM forward-index tiles, paged by the block-max bound oracle
from ..index import tiering as _tiering

# compile-time unroll budget of the per-tile clause loop; plans beyond
# it fall back rather than minting pathological programs
_FUSED_MAX_CLAUSES = 8


def _fused_leaf_inputs(desc: tuple, params: tuple
                       ) -> tuple[jax.Array, jax.Array]:
    if desc[0] == "terms_dense":
        qt, wq = params
        return qt, wq
    tid, weight = params                     # term_text: single-term Q=1
    return tid[:, None], weight[:, None]


def fused_enabled() -> bool:
    return _os.environ.get("ES_TPU_FUSED", "auto").lower() not in (
        "0", "false", "off")


def _positional_enabled() -> bool:
    """Gate for the fused positional clause kinds (phrase/span/BM25F on
    device). Off forces the host phrase.py path — responses are
    byte-identical either way; this is the bench A/B lever."""
    return _os.environ.get("ES_TPU_POSITIONAL", "1").lower() not in (
        "0", "false", "off")


def _leaf_scoring_kind(d0) -> bool:
    return d0 in _FUSED_DENSE_KINDS or (isinstance(d0, str)
                                        and positional_prefix(d0))


def _classify_fused_leaf(desc: tuple):
    """(kind, field, wrapped) of a scoring clause the bundle engine
    evaluates per tile — a bare terms_dense/term_text or positional
    (phrase/span/BM25F) leaf, or one wrapped in a single-should bool
    that carries its own dynamic (msm, boost), e.g. a boosted match
    inside an explicit bool (bool-in-bool). None for anything else."""
    if _leaf_scoring_kind(desc[0]):
        return (desc[0], desc[1], False)
    if desc[0] == "bool":
        _, must, should, must_not, filt = desc
        if not must and not must_not and not filt and len(should) == 1 \
                and _leaf_scoring_kind(should[0][0]):
            return (should[0][0], should[0][1], True)
    return None


def _fused_plan_bundle(desc: tuple, k: int, agg_desc, sort_spec: tuple,
                       allow_aggs: bool = True, allow_k0: bool = False):
    """SHARED plan-level admission (single-chip executor AND the mesh
    searcher route through this — keep the predicates from drifting).

    Returns (bundle, reject_reason): a static clause-bundle tuple in
    eval_node order (must, filter, must_not, should — see
    ops/scoring.py) when the fused score+top-k path may serve the plan,
    else (None, reason) for the rejection counters. Requires a pure
    score sort; aggregations are fine where the caller can run the
    emit-match engine (allow_aggs). k == 0 plans (size-0 counts /
    filtered aggs) are admitted only where the caller runs the
    match-mask-only engine (allow_k0) — there is no k-th slot for the
    running top-k, so the score matrix is skipped entirely. Callers
    still check the pack carries the tile summaries and that every bool
    boost is positive."""
    if not fused_enabled():
        return None, "disabled"
    if k <= 0 and not allow_k0:
        return None, "k_zero"
    if tuple(sort_spec) != ("_score",):
        return None, "sort"
    if agg_desc and not allow_aggs:
        return None, "aggs_unsupported"
    if _leaf_scoring_kind(desc[0]):
        return (("should", desc[0], desc[1], False),), None
    if desc[0] != "bool":
        return None, f"clause:{desc[0]}"
    _, d_must, d_should, d_not, d_filter = desc
    clauses = []
    for role, group in (("must", d_must), ("filter", d_filter),
                        ("must_not", d_not), ("should", d_should)):
        for c in group:
            leaf = _classify_fused_leaf(c)
            if leaf is not None:
                clauses.append((role,) + leaf)
            elif role in ("filter", "must_not") \
                    and c[0] in _FUSED_RANGE_KINDS:
                clauses.append((role, c[0], c[1], False))
            elif role in ("must", "should") \
                    and c[0] in _FUSED_VEC_KINDS:
                # vector similarity clause (hybrid BM25+knn): scored
                # per tile from the in-program similarity column
                clauses.append((role, c[0], c[1], False))
            else:
                return None, f"clause:{c[0]}"
    if not any(_leaf_scoring_kind(kd) for _r, kd, _f, _w in clauses):
        return None, "no_scoring_clause"
    if len(clauses) > _FUSED_MAX_CLAUSES:
        return None, "too_many_clauses"
    return tuple(clauses), None


def _bundle_inputs(desc: tuple, params: tuple, bundle: tuple):
    """Per-clause dynamic inputs for a classified plan (runs under jit
    on the traced params): (cl_inputs, msm [B] i32, boost [B] f32|None)
    in the ops/scoring.py bundle contract. Walks desc/params in the
    exact group order the classifier emitted the bundle in."""
    B = _batch_size(params)
    ones_i = jnp.ones((B,), jnp.int32)
    ones_f = jnp.ones((B,), jnp.float32)
    if desc[0] != "bool":
        if isinstance(desc[0], str) and positional_prefix(desc[0]):
            return (tuple(params) + (ones_i, ones_f),), ones_i, None
        qt, wq = _fused_leaf_inputs(desc, params)
        return ((qt, wq, ones_i, ones_f),), ones_i, None
    _, d_must, d_should, d_not, d_filter = desc
    p_must, p_should, p_not, p_filter, msm, boost = params
    groups = {"must": (d_must, p_must), "should": (d_should, p_should),
              "must_not": (d_not, p_not), "filter": (d_filter, p_filter)}
    nxt = {r: 0 for r in groups}
    out = []
    for role, kind, _field, wrapped in bundle:
        dg, pg = groups[role]
        d, p = dg[nxt[role]], pg[nxt[role]]
        nxt[role] += 1
        if kind in _FUSED_RANGE_KINDS:
            lo, hi, _boost_r = p
            out.append((lo, hi))
        elif kind in _FUSED_VEC_KINDS:
            # (qv [B, D], boost [B], similarity) — the raw clause
            # inputs; eval_fused_topk/match substitute the computed
            # (col, exists, ub) before the scoring ops see them
            qv, boost_c = p
            out.append((qv, boost_c, d[2]))
        elif wrapped:
            _, _cm, c_should, _cn, _cf = d
            _pm, pc_should, _pn, _pf, msm_c, boost_c = p
            if positional_prefix(kind):
                # positional finalize params ride whole (the 5/4-tuple
                # contract of ops/scoring.positional_tile_scores), the
                # wrapper's (msm, boost) appended last
                out.append(tuple(pc_should[0]) + (msm_c, boost_c))
            else:
                qt, wq = _fused_leaf_inputs(c_should[0], pc_should[0])
                out.append((qt, wq, msm_c, boost_c))
        elif positional_prefix(kind):
            out.append(tuple(p) + (ones_i, ones_f))
        else:
            qt, wq = _fused_leaf_inputs(d, p)
            out.append((qt, wq, ones_i, ones_f))
    return tuple(out), msm, boost


def _fused_pack_ok(segment: Segment, bundle: tuple) -> str | None:
    """Pack-level admission: every dense clause field needs a forward
    index + tile_max block-max summary; every range clause field needs
    (lazily built) per-tile extrema. Returns a reject reason or None."""
    for _role, kind, field, _w in bundle:
        if kind in _FUSED_DENSE_KINDS:
            pf = segment.text.get(field)
            if pf is None or pf.fwd_tids is None \
                    or getattr(pf, "tile_max", None) is None:
                return "missing_tile_max"
        elif positional_prefix(kind):
            # binder admission already checked the BINDING segment; this
            # re-check covers the cross-segment callers (pack pairs,
            # mesh) where another segment may lack the positions pack
            for f in clause_fields(field):
                pf = segment.text.get(f)
                if pf is None or pf.fwd_tids is None \
                        or getattr(pf, "fwd_pos", None) is None \
                        or getattr(pf, "tile_max", None) is None:
                    return "missing_positions_pack"
        elif kind in _FUSED_VEC_KINDS:
            if segment.vectors.get(field) is None:
                return "missing_vector_column"
        elif not ensure_num_tiles(segment, field):
            return "missing_tile_minmax"
    return None


def _fused_params_ok(desc: tuple, params: tuple, bundle: tuple) -> bool:
    """Positive-boost admission, host-side on the numpy params: the
    outer bool boost and every wrapped clause's boost must be > 0 —
    scores are applied pre-selection in eval_node's op order (exact
    doc-id/tie parity for any positive boost), but boost <= 0 breaks
    the monotone-bound argument the pruning relies on."""
    if desc[0] != "bool":
        return True
    if not bool((np.asarray(params[5]) > 0).all()):
        return False
    p_groups = {"must": params[0], "should": params[1],
                "must_not": params[2], "filter": params[3]}
    nxt = {r: 0 for r in p_groups}
    for role, kind, _field, wrapped in bundle:
        p = p_groups[role][nxt[role]]
        nxt[role] += 1
        if wrapped and not bool((np.asarray(p[5]) > 0).all()):
            return False
        # knn clause boost must be positive too: its tile bound is the
        # max of the boost-folded column — monotone only for boost > 0
        if kind in _FUSED_VEC_KINDS \
                and not bool((np.asarray(p[1]) > 0).all()):
            return False
    return True


def _fused_row_elems(cap: int, n_tiles: int, k: int,
                     emit_match: bool = False,
                     vec_clauses: int = 0,
                     pos_width: int = 0) -> int:
    """Per-row transient of a fused dispatch in elements — one [*, tile]
    scoring slab plus the [*, n_tiles*ck] candidate strip, plus the
    [*, cap] bool match mask in emit-match (fused+aggs) mode, plus one
    [*, cap] similarity column per knn clause (the in-program vector
    preamble), plus the decoded [*, tile, n*P] i32 position slab of the
    widest positional clause (pos_width = its n * P; the per-clause
    decodes are sequential, so the widest bounds the live transient).
    The breaker estimate (execute_segment_async) and the chunking
    decision (_segment_body) MUST size from this one definition."""
    tile = cap // n_tiles
    return tile + n_tiles * min(k, tile) + (cap if emit_match else 0) \
        + vec_clauses * cap + pos_width * tile


def _bundle_pos_width(bundle: tuple, text_cols) -> int:
    """Widest positional clause's decoded position slab in elements per
    doc (n_terms * P for phrase/span; P for bm25f, whose per-(field,
    term) decodes are sequential). text_cols is either Segment.text
    (host PostingsField objects) or a device seg["text"] dict."""
    w = 0
    for _r, kd, fld, _w2 in bundle:
        if not (isinstance(kd, str) and positional_prefix(kd)):
            continue
        head, n, _v = parse_positional_kind(kd)
        for f in clause_fields(fld):
            c = text_cols[f]
            if isinstance(c, dict):
                fwd_pos, fwd_tids = c.get("fwd_pos"), c.get("fwd_tids")
            else:
                fwd_pos, fwd_tids = c.fwd_pos, c.fwd_tids
            if fwd_pos is None or fwd_tids is None:
                continue
            # trailing axis: works for host [cap, L] / mesh [S, cap, L]
            p = fwd_pos.shape[-1] // fwd_tids.shape[-1]
            w = max(w, (1 if head == "bm25f" else n) * p)
    return w


def _bundle_positional(bundle: tuple) -> bool:
    return any(isinstance(kd, str) and positional_prefix(kd)
               for _r, kd, _f, _w in bundle)


class _FusedScoringStats:
    """Autotuner choices, block-prune counters, and per-reason admission
    rejections for the fused score+top-k path; surfaced via the node
    stats API (node.nodes_stats()["fused_scoring"])."""

    def __init__(self):
        self._lock = _threading.Lock()
        self._choices: dict[str, dict] = {}
        self._hard = 0.0
        self._thresholded = 0.0
        self._examined = 0.0
        self._dispatches = 0
        self._admitted = 0
        self._rejected: dict[str, int] = {}
        # positional (phrase/span/BM25F) observability: queries whose
        # positional clause fell back to the host path, by reason;
        # fused-admitted plans CARRYING positional clauses; and the
        # tile-prune counters of exactly those dispatches (the
        # position-aware prune signal the bench leg gates on)
        self._positional: dict[str, int] = {}
        self._positional_admitted = 0
        self._pos_hard = 0.0
        self._pos_thresholded = 0.0
        self._pos_examined = 0.0
        self._pos_dispatches = 0
        # fused-ADMITTED plans where the Pallas kernel was not even a
        # candidate, by reason tag — the remaining kernel-coverage gaps
        # made observable instead of inferred from bench diffs
        self._pallas_rejected: dict[str, int] = {}
        # top-level `knn` section admission, by reason (record_knn)
        self._knn: dict[str, int] = {}
        # IVF cluster-prune counters (record_ann_prune)
        self._ann_probed = 0
        self._ann_pruned = 0
        self._ann_scored = 0

    def record_choice(self, key: tuple, backend: str, reason: str,
                      timings: dict | None = None,
                      keep_existing: bool = False) -> None:
        """keep_existing: record only when the key has no entry yet —
        the forced-env resolve path runs per dispatch and must not
        clobber a tuned entry's timings (which would silently drop the
        shape from the loss audit)."""
        entry = {"backend": backend, "reason": reason}
        if timings:
            entry["timings_ms"] = {b: round(t * 1e3, 3)
                                   for b, t in timings.items()}
        with self._lock:
            if keep_existing and repr(key) in self._choices:
                return
            # keys embed pack fingerprints, which refreshes/merges mint
            # forever: bounded so the stats payload cannot grow
            # monotonically
            _bounded_put(self._choices, repr(key), entry)

    def record_admit(self, positional: bool = False) -> None:
        with self._lock:
            self._admitted += 1
            if positional:
                self._positional_admitted += 1

    def record_reject(self, reason: str) -> None:
        with self._lock:
            self._rejected[reason] = self._rejected.get(reason, 0) + 1

    def record_positional(self, reason: str) -> None:
        """One positional query bound to the HOST phrase/span/BM25F
        path, by reason — plan-level positional admission made
        observable (admission.positional_fallbacks)."""
        with self._lock:
            self._positional[reason] = self._positional.get(reason, 0) + 1

    def record_pallas_reject(self, reason: str) -> None:
        with self._lock:
            self._pallas_rejected[reason] = \
                self._pallas_rejected.get(reason, 0) + 1

    def record_knn(self, reason: str) -> None:
        """Per-reason admission of top-level `knn` search sections
        (search/shard_searcher.py): how each vector search was served
        — "query_rewrite" (bundle clause, rides the dispatch
        scheduler), "ivf" (coarse-quantized probe), "exact" (pure-knn
        scan: below the IVF crossover OR a degraded/skipped build), or
        a "host_fallback:<why>" tag for shapes the device paths cannot
        take (e.g. unsupported similarity) — so unfused vector shapes
        are visible instead of silent."""
        with self._lock:
            self._knn[reason] = self._knn.get(reason, 0) + 1

    def record_prune(self, hard: float, thresholded: float,
                     examined: float, positional: bool = False) -> None:
        with self._lock:
            self._hard += float(hard)
            self._thresholded += float(thresholded)
            self._examined += float(examined)
            self._dispatches += 1
            if positional:
                self._pos_hard += float(hard)
                self._pos_thresholded += float(thresholded)
                self._pos_examined += float(examined)
                self._pos_dispatches += 1

    def record_ann_prune(self, probed: int, pruned: int,
                         scored: int) -> None:
        """IVF probe counters (ops/ann.ivf_topk stats, per-(query,
        cluster) units): `pruned` is the cluster-prune skip count — a
        probed cluster whose bound could not beat the running k-th
        best, skipped without touching its members."""
        with self._lock:
            self._ann_probed += int(probed)
            self._ann_pruned += int(pruned)
            self._ann_scored += int(scored)

    def snapshot(self) -> dict:
        with self._lock:
            pruned = self._hard + self._thresholded
            considered = self._admitted + sum(self._rejected.values())
            # autotuner loss-audit (the ROADMAP item-3 regression
            # signal): every TIMED tune kept both backends' best-of-N;
            # any shape where the Pallas candidate lost to XLA by >10%
            # is a kernel-coverage/perf gap, reported here whichever
            # backend actually won
            audit = []
            for key, entry in self._choices.items():
                t = entry.get("timings_ms")
                if not t or "pallas" not in t or "xla" not in t:
                    continue
                if t["xla"] > 0 and t["pallas"] > 1.1 * t["xla"]:
                    audit.append({"key": key, "backend": entry["backend"],
                                  "pallas_ms": t["pallas"],
                                  "xla_ms": t["xla"],
                                  "ratio": round(t["pallas"] / t["xla"],
                                                 3)})
            return {
                "backend_choices": {k: dict(v)
                                    for k, v in self._choices.items()},
                "dispatches": self._dispatches,
                "tiles": {"examined": round(self._examined, 3),
                          "hard_skipped": round(self._hard, 3),
                          "thresholded": round(self._thresholded, 3)},
                "prune_rate": (pruned / self._examined
                               if self._examined else 0.0),
                "loss_audit": {"shapes": audit, "count": len(audit)},
                "ann": {"clusters_probed": self._ann_probed,
                        "clusters_pruned": self._ann_pruned,
                        "clusters_scored": self._ann_scored},
                # why plans fell back, by reason — so a bench run can
                # see WHY a workload missed the fused path; the
                # pallas_rejected sub-map counts fused-admitted plans
                # the KERNEL could not serve, by reason tag
                "admission": {
                    "admitted": self._admitted,
                    "rejected": dict(self._rejected),
                    "pallas_rejected": dict(self._pallas_rejected),
                    "knn": dict(self._knn),
                    "positional_fallbacks": dict(self._positional),
                    "positional_admitted": self._positional_admitted,
                    "rate": (self._admitted / considered
                             if considered else 0.0)},
                "positional": {
                    "dispatches": self._pos_dispatches,
                    "tiles": {
                        "examined": round(self._pos_examined, 3),
                        "hard_skipped": round(self._pos_hard, 3),
                        "thresholded": round(self._pos_thresholded, 3)},
                    "prune_rate": (
                        (self._pos_hard + self._pos_thresholded)
                        / self._pos_examined
                        if self._pos_examined else 0.0)},
            }

    def reset(self) -> None:
        with self._lock:
            self._choices.clear()
            self._hard = self._thresholded = self._examined = 0.0
            self._dispatches = 0
            self._admitted = 0
            self._rejected.clear()
            self._pallas_rejected.clear()
            self._knn.clear()
            self._positional.clear()
            self._positional_admitted = 0
            self._pos_hard = self._pos_thresholded = self._pos_examined = 0.0
            self._pos_dispatches = 0
            self._ann_probed = self._ann_pruned = self._ann_scored = 0


_fused_stats = _FusedScoringStats()


def fused_scoring_stats() -> dict:
    """Snapshot for the node stats API (+ the tiered-residency block:
    resident vs summary bytes, tile hit/miss/eviction counters, and
    the prune-skipped fetch count proving the I/O filter)."""
    out = _fused_stats.snapshot()
    out["tiering"] = _tiering.stats_snapshot()
    return out


# hard cap on the per-tile selection depth the kernel will attempt:
# up to ops/pallas_scoring._CK_UNROLL the selection passes unroll; past
# it a fori_loop runs the same passes (the multi-pass form that lifted
# the old 128 hard cap), and past THIS the O(ck * tile) per-tile
# selection work loses to XLA's tile-wide lax.top_k regardless
_FUSED_PALLAS_CK_MAX = 1024

_autotune_choices: dict = {}
# serializes first-execution tuning: concurrent searches timing
# different keys would dispatch onto the same (serially executing)
# device and corrupt each other's wall clocks — and the corrupted
# winner would be cached for the life of the process
_autotune_lock = _threading.Lock()
# bound on cached choices/stats entries: keys embed seg_ids, which a
# long-lived node's refresh/merge cycle mints without end — evicting
# oldest-inserted only costs a re-tune if an evicted pack comes back
_AUTOTUNE_CACHE_CAP = 512


def _bounded_put(d: dict, key, value) -> None:
    """Insert under the shared FIFO cap (caller holds the dict's lock).
    ONE policy for the tuner cache and its stats mirror, so the two
    stay in lockstep; re-recording an existing key never evicts."""
    if key not in d:
        while len(d) >= _AUTOTUNE_CACHE_CAP:
            d.pop(next(iter(d)))
    d[key] = value


def seg_cache_key(segment: Segment) -> str:
    """The key every fingerprint-keyed cache (autotune choices, the
    persisted store, resident entries) indexes a pack under. Base
    segments key on content; DELTA segments (streaming write path) key
    on their (base generation, pow2 delta-extent bucket) instead —
    Segment.cache_key — so a refresh's delta rebuild lands on the SAME
    key and performs zero re-tunes and zero evictions. Only compaction
    (which mints a new base fingerprint) re-keys."""
    return segment.cache_key()


def fused_pallas_ok(ck: int) -> bool:
    """May the Pallas fused kernel be a candidate? Real-TPU lowering
    only (interpret mode is a validation tool, not a serving backend)
    and a bounded per-tile selection depth; ck == 0 is the mask-only
    k == 0 grid (no selection at all)."""
    return (pallas_enabled() and not interpret_mode()
            and 0 <= ck <= _FUSED_PALLAS_CK_MAX)


def _pallas_coverage() -> str:
    """Kernel coverage mode: "full" (default — the kernel serves the
    whole bundle admission matrix) or "legacy" (the PR 2 single-field
    all-dense no-aggs matrix; an A/B and bisection tool — with it set,
    the per-reason pallas_rejected counters show exactly which plans the
    restriction costs)."""
    return _os.environ.get("ES_TPU_PALLAS_COVERAGE", "full").lower()


# widest positions pack (L*P int16 elements per doc row) the kernel
# will stage into VMEM next to the forward block: past this the
# [tile, L*P] position ref alone approaches the VMEM budget and the
# XLA engine (which streams the decode through HBM) wins anyway
_POS_PALLAS_WIDTH_MAX = 4096


def _bundle_pallas_reason(bundle: tuple, agg_desc, ck: int,
                          pos_width: int = 0) -> str | None:
    """Why the Pallas kernel is NOT a candidate for a fused-admitted
    bundle (None = it is): reason tags feed
    nodes_stats()["fused_scoring"].admission.pallas_rejected so the
    remaining coverage gaps are observable, not inferred from bench
    diffs. Shape reasons are computed before availability so they
    surface on every backend. pos_width is the widest positional
    field's packed L*P (0 = caller has no positional clauses or no
    shape info — the VMEM gate is then skipped)."""
    if any(kd in _FUSED_VEC_KINDS for _r, kd, _f, _w in bundle):
        # the similarity-column preamble (whole-capacity MXU matmul) has
        # no kernel form yet: hybrid BM25+vector bundles run the XLA
        # engine, visibly
        return "knn_clause"
    if ck > _FUSED_PALLAS_CK_MAX:
        return "ck_cap"
    if _bundle_positional(bundle) and pos_width > _POS_PALLAS_WIDTH_MAX:
        return "positional_vmem"
    if _pallas_coverage() == "legacy":
        if _bundle_positional(bundle):
            return "positional_clause"
        if agg_desc:
            return "agg_emit_match"
        if ck == 0:
            return "k_zero"
        fields = {f for _r, kd, f, _w in bundle
                  if kd in _FUSED_DENSE_KINDS}
        if len(fields) != 1:
            return "multi_field"
        if any(kd in _FUSED_RANGE_KINDS for _r, kd, _f, _w in bundle):
            return "range_mask"
    if not fused_pallas_ok(ck):
        return "kernel_unavailable"
    return None


def _bundle_pallas_ok(bundle: tuple, agg_desc, ck: int,
                      pos_width: int = 0) -> bool:
    """Bundle-level Pallas candidacy: the kernel now covers the full
    bundle admission matrix — multi-text-field bundles, positional
    (phrase/span/BM25F) clause kinds, dense/numeric range filter &
    must_not masks, emit-match (k>0 + aggs), and the mask-only k == 0
    grid — so candidacy reduces to availability plus the
    selection-depth and positional-VMEM caps (see _bundle_pallas_reason
    for the tags)."""
    return _bundle_pallas_reason(bundle, agg_desc, ck, pos_width) is None


# -- persisted autotuner choices (satellite: survive restarts) --------------
#
# Keys embed the pack FINGERPRINT (index/segment.Segment.fingerprint),
# which is stable across process restarts for identical content and
# changes whenever a refresh/merge rebuilds the pack — so invalidation
# is by construction: a refreshed pack re-tunes under its new key and
# stale entries age out of the FIFO cap.

_autotune_persist_path: str | None = None
# key -> {"choice": "pallas"|"xla", "timings_ms": {...}|None}: the
# loss-audit satellite keeps BOTH backends' best-of-N, not just the
# winner, so a restart can still answer "by how much did pallas lose"
_autotune_persisted: dict[str, dict] = {}
_AUTOTUNE_PERSIST_CAP = 4096


def _persist_entry(value) -> dict | None:
    """Normalize one on-disk store value: current dict entries and the
    pre-timings plain-string format both load (a legacy entry just has
    no timings to audit)."""
    if isinstance(value, str) and value in ("pallas", "xla"):
        return {"choice": value, "timings_ms": None}
    if isinstance(value, dict) and value.get("choice") in ("pallas",
                                                           "xla"):
        t = value.get("timings_ms")
        return {"choice": value["choice"],
                "timings_ms": dict(t) if isinstance(t, dict) else None}
    return None


def autotune_persistence_path() -> str | None:
    return _autotune_persist_path


def autotune_persist_key(fingerprint: str, cap: int, desc: tuple,
                         k: int, agg: bool) -> str:
    """Canonical persisted-store key shared by the single-chip executor
    and the mesh path: (pack fingerprint, cap, desc, pow2-bucketed k,
    aggs?). k is bucketed to its next power of two so the single-chip
    convention (k_eff = from+size) and the mesh convention (k already
    pow2-padded) land on the SAME key — that is what lets an SPMD mesh
    program (which cannot wall-clock itself without desyncing the
    collective) reuse the choice a single-chip execution of the
    identical pack timed and persisted. Entries persisted under the
    pre-canonical format (repr of the full tune key incl. b_pad) are
    inert: they never match, cost one re-tune per pack, and age out of
    the store's FIFO cap."""
    return repr((fingerprint, cap, desc, next_pow2(max(int(k), 1),
                                                   floor=1), bool(agg)))


def configure_autotune_persistence(path: str | None,
                                   if_owner: str | None = None,
                                   only_if_unset: bool = False) -> bool:
    """Point the autotuner at an on-disk choice store (the node passes
    <data_path>/fused_autotune.json at startup; None disables). The
    store is process-global, so with several in-process nodes the FIRST
    configured store wins (the breaker_service convention):
    only_if_unset claims the store atomically (returns False when
    another store is already configured), and if_owner tears down only
    the store you configured (a closing node must not disable
    persistence for nodes still running)."""
    global _autotune_persist_path, _autotune_persisted
    with _autotune_lock:
        if only_if_unset and _autotune_persist_path is not None:
            return False
        if if_owner is not None and _autotune_persist_path != if_owner:
            return False
        _autotune_persist_path = path
        _autotune_persisted = {}
        if path is None:
            return True
        try:
            # graftlint: ok(lock-discipline): node-startup store load —
            # must be atomic with claiming the store path, never on the
            # query path
            with open(path) as f:
                data = _json.load(f)
            _autotune_persisted = {
                str(k): e for k, v in data.items()
                if (e := _persist_entry(v)) is not None}
            # a store written before the FIFO cap existed (or by a
            # larger-capped build) must not smuggle an unbounded map
            # back in: drop oldest-inserted down to the cap on load
            while len(_autotune_persisted) > _AUTOTUNE_PERSIST_CAP:
                _autotune_persisted.pop(next(iter(_autotune_persisted)))
        except (OSError, ValueError):
            _autotune_persisted = {}
    return True


def _persisted_key_fingerprint(key_str: str) -> str | None:
    """First element (the pack fingerprint / cache key) of a persisted
    autotune store key — keys are repr() of tuples whose head is that
    string. None for unparseable (pre-canonical) keys."""
    import ast
    try:
        key = ast.literal_eval(key_str)
    except (ValueError, SyntaxError):
        return None
    if isinstance(key, tuple) and key and isinstance(key[0], str):
        return key[0]
    return None


def sweep_autotune_store(live_keys) -> int:
    """Prune persisted autotuner entries whose pack no longer exists
    (satellite: without this, every refresh/merge/compaction in a
    node's life leaves its dead fingerprints in fused_autotune.json
    forever — the FIFO cap bounds the count, but dead entries crowd
    out live ones and the file never shrinks). `live_keys` is the set
    of cache keys of every segment currently recovered on this node
    (node startup calls this after recovery); pack-pair keys
    ("fp_a+fp_b", the base+delta dispatch) survive when EVERY half is
    live, and unparseable legacy keys are swept with the dead. Returns
    the number of entries dropped and rewrites the store when any
    were."""
    live = set(live_keys)
    with _autotune_lock:
        if _autotune_persist_path is None or not _autotune_persisted:
            return 0
        dead = []
        for key_str in _autotune_persisted:
            fp = _persisted_key_fingerprint(key_str)
            if fp is None or not all(p in live for p in fp.split("+")):
                dead.append(key_str)
        if not dead:
            return 0
        for key_str in dead:
            _autotune_persisted.pop(key_str, None)
        tmp = _autotune_persist_path + ".tmp"
        try:
            # graftlint: ok(lock-discipline): node-startup sweep, never
            # on the query path — same discipline as the store load
            with open(tmp, "w") as f:
                _json.dump(_autotune_persisted, f)
            _os.replace(tmp, _autotune_persist_path)
        except OSError:
            pass
    return len(dead)


def _autotune_persist_locked(key_str: str, choice: str,
                             timings: dict | None = None) -> None:
    """Write-through one choice plus both backends' best-of-N timings
    (caller holds _autotune_lock). Atomic replace; write failures
    degrade to in-memory-only, never raise."""
    if _autotune_persist_path is None:
        return
    if key_str not in _autotune_persisted:
        while len(_autotune_persisted) >= _AUTOTUNE_PERSIST_CAP:
            _autotune_persisted.pop(next(iter(_autotune_persisted)))
    _autotune_persisted[key_str] = {
        "choice": choice,
        "timings_ms": ({b: round(t * 1e3, 3) for b, t in timings.items()}
                       if timings else None)}
    tmp = _autotune_persist_path + ".tmp"
    try:
        _os.makedirs(_os.path.dirname(_autotune_persist_path) or ".",
                     exist_ok=True)
        with open(tmp, "w") as f:
            _json.dump(_autotune_persisted, f)
        _os.replace(tmp, _autotune_persist_path)
    except OSError:
        pass


def resolve_fused_backend(key: tuple, ck: int, run_backend=None,
                          pallas_candidate: bool = True,
                          persist_keys: tuple[str, ...] | None = None
                          ) -> str:
    """Per-(pack fingerprint, shape-bucket) backend choice.
    ES_TPU_FUSED_BACKEND forces; a choice persisted under the node data
    path is reused across restarts; otherwise the first execution of a
    key times both backends via `run_backend(name)` (dispatch + block)
    — one compile pass, one steady-state warmup pass, then best-of-N
    (ES_TPU_AUTOTUNE_REPS, default 3) so a first-execution hiccup on
    either side cannot commit the wrong backend for the life of the
    pack — and caches + persists the winner. Callers with no way to
    time (mesh programs) pass run_backend=None and get a persisted
    choice when any of their `persist_keys` (autotune_persist_key — one
    per shard for a mesh pack) has one, else the static choice. Timed
    winners are written under persist_keys[0] (defaults to repr(key))."""
    forced = _os.environ.get("ES_TPU_FUSED_BACKEND", "").lower()
    if forced in ("pallas", "xla"):
        # forced outranks even an already-cached tuned choice, and is
        # never cached itself: flipping the env mid-process switches
        # EVERY path — cold, resident (_resident_backend mirrors this
        # precedence), mesh — onto one engine, and unsetting it
        # restores the tuned choice. Cache-first here would let a
        # pre-flip tuned choice serve one engine cold while the
        # resident path pins the other. keep_existing: this branch
        # runs per dispatch and must not overwrite a tuned entry's
        # timings (that would drop the shape from the loss audit).
        _fused_stats.record_choice(key, forced, "forced", None,
                                   keep_existing=True)
        return forced
    cached = _autotune_choices.get(key)
    if cached is not None:
        return cached
    with _autotune_lock:
        cached = _autotune_choices.get(key)
        if cached is not None:
            return cached
        key_str = repr(key)
        if persist_keys is None:
            persist_keys = (key_str,)
        persisted = next((c for pk in persist_keys
                          if (c := _autotune_persisted.get(pk))
                          is not None), None)
        if not pallas_candidate or not fused_pallas_ok(ck):
            choice, reason, timings = "xla", "pallas-unavailable", None
        elif persisted is not None:
            # reloaded timings (when the store has them) re-enter the
            # stats mirror so the loss audit survives a restart
            choice, reason = persisted["choice"], "persisted"
            timings = ({b: t / 1e3 for b, t
                        in persisted["timings_ms"].items()}
                       if persisted["timings_ms"] else None)
        elif run_backend is None:
            choice, reason, timings = "pallas", "static", None
        else:
            reps = max(1, int(_os.environ.get("ES_TPU_AUTOTUNE_REPS",
                                              "3")))
            timings = {}
            for b in ("xla", "pallas"):
                run_backend(b)                   # compile
                run_backend(b)                   # steady-state warmup:
                # the first post-compile execution still pays one-time
                # costs (transfer-cache fills, lazy device init) that
                # skewed BENCH_r05's http_logs choice toward pallas
                best = None
                for _ in range(reps):
                    t0 = _time.perf_counter()
                    run_backend(b)
                    dt = _time.perf_counter() - t0
                    best = dt if best is None else min(best, dt)
                timings[b] = best
            choice = min(timings, key=timings.get)
            reason = "timed"
            # graftlint: ok(lock-discipline): write-through must commit
            # under the same hold as the in-memory choice (a racing
            # tuner could persist the loser); first-execution-only per
            # (pack, shape) — never the steady-state query path
            _autotune_persist_locked(persist_keys[0], choice, timings)
        _bounded_put(_autotune_choices, key, choice)
    _fused_stats.record_choice(key, choice, reason, timings)
    return choice


def _vec_clause_inputs(seg: dict, bundle: tuple, cl_inputs: tuple,
                       n_tiles: int) -> tuple:
    """Substitute every knn clause's raw (qv, boost, similarity) input
    with the (col, exists, ub) triple the bundle ops consume (runs
    traced, inside the ONE fused program):

      col — the whole-capacity transformed-similarity column, boost
            folded in: the same `knn_score_column(...) * boost` ops, in
            the same order, as eval_node's knn_vec leaf, so fused and
            unfused hybrid scores are bit-identical;
      ub  — per-tile max of col (+ one BOUND_SLACK, mirroring the
            dense clauses' per-clause inflation): an EXACT query-time
            tile bound — the tile walk prunes vector tiles against the
            very numbers it would have scored."""
    out = []
    for (role, kind, field, _w), inp in zip(bundle, cl_inputs):
        if kind not in _FUSED_VEC_KINDS:
            out.append(inp)
            continue
        qv, boost_c, sim = inp
        v = seg["vec"][field]
        col = knn_score_column(v["values"], v["norms"], v["exists"], qv,
                               similarity=sim) * boost_c[:, None]
        b, cap = col.shape
        tile = cap // n_tiles
        ub = col.reshape(b, n_tiles, tile).max(axis=2)
        # sign-guarded slack (the ops/ann._slacked rule): dot_product
        # on non-unit vectors can transform NEGATIVE — multiplying a
        # negative max up would LOWER the bound below the true best
        # score and wrongly prune the tile
        ub = jnp.where(ub >= 0.0, ub * jnp.float32(BOUND_SLACK),
                       ub / jnp.float32(BOUND_SLACK))
        out.append((col, v["exists"], ub))
    return tuple(out)


def eval_fused_topk(seg: dict, desc: tuple, params: tuple,
                    live: jax.Array, k: int, bundle: tuple, backend: str,
                    emit_match: bool = False, step=None,
                    init_topk=None, idx_offset: int = 0):
    """Shared fused score+top-k entry (single-chip program AND the mesh
    shard_map program route through here). Returns (top_s [B,k],
    top_i [B,k], total [B], prune_stats [3] f32) plus the exact match
    mask [B, cap] when emit_match (the fused+aggs mode), plus the
    device-side timed_out scalar when a stepped `step` (see
    ops/scoring._stepped_tile_loop) is given. Both engines take the
    SAME calling convention and share bundle_tile_bounds, so they prune
    identically and responses stay byte-identical whichever the
    autotuner picked — including through a stepped chunk boundary."""
    cl_inputs, msm, boost = _bundle_inputs(desc, params, bundle)
    if boost is None:
        boost = jnp.ones_like(msm, dtype=jnp.float32)
    text_cols = {f: seg["text"][f] for f in bundle_text_fields(bundle)}
    num_cols = {f: seg["num"][f] for _r, kd, f, _w in bundle
                if kd in _FUSED_RANGE_KINDS}
    if any(kd in _FUSED_VEC_KINDS for _r, kd, _f, _w in bundle):
        n_tiles = text_cols[bundle_primary_field(bundle)][
            "tile_max"].shape[1]
        cl_inputs = _vec_clause_inputs(seg, bundle, cl_inputs, n_tiles)
        # the kernel has no knn-clause form (the similarity-column
        # preamble is XLA-only); even a FORCED pallas choice demotes
        # here — results are identical either way, crashing is not
        backend = "xla"
    if backend == "pallas":
        out = fused_topk_bundle_pallas(
            text_cols, num_cols, bundle, cl_inputs, msm, boost, live, k,
            emit_match=emit_match, step=step, interpret=interpret_mode(),
            init_topk=init_topk, idx_offset=idx_offset)
    else:
        out = score_topk_bundle_fused(
            text_cols, num_cols, bundle, cl_inputs, msm, boost, live, k,
            emit_match=emit_match, step=step, init_topk=init_topk,
            idx_offset=idx_offset)
    tail = () if step is None else (out[-1],)
    if step is not None:
        out = out[:-1]
    if emit_match:
        top_s, top_i, total, pruned, match = out
        return (top_s, top_i, total, pruned.astype(jnp.float32),
                match) + tail
    top_s, top_i, total, pruned = out
    return (top_s, top_i, total, pruned.astype(jnp.float32)) + tail


def eval_fused_match(seg: dict, desc: tuple, params: tuple,
                     live: jax.Array, bundle: tuple, backend: str = "xla",
                     emit_match: bool = True, step=None):
    """Fused match-mask-only entry for k == 0 plans (size-0 counts /
    filtered aggs): the tile loop computes the exact match mask and
    total with block-max can_match hard-skips, never touching scores or
    top-k — on the XLA engine or the mask-only Pallas grid, per the
    autotuned choice. Returns (total [B], prune_stats [3] f32) plus the
    match mask [B, cap] when emit_match (an aggregation pass follows),
    plus the timed_out scalar when a stepped `step` is given."""
    cl_inputs, msm, boost = _bundle_inputs(desc, params, bundle)
    text_cols = {f: seg["text"][f] for f in bundle_text_fields(bundle)}
    num_cols = {f: seg["num"][f] for _r, kd, f, _w in bundle
                if kd in _FUSED_RANGE_KINDS}
    if any(kd in _FUSED_VEC_KINDS for _r, kd, _f, _w in bundle):
        n_tiles = text_cols[bundle_primary_field(bundle)][
            "tile_max"].shape[1]
        cl_inputs = _vec_clause_inputs(seg, bundle, cl_inputs, n_tiles)
        backend = "xla"    # no kernel form — see eval_fused_topk
    if backend == "pallas":
        out = match_mask_bundle_pallas(
            text_cols, num_cols, bundle, cl_inputs, msm, boost, live,
            emit_match=emit_match, step=step, interpret=interpret_mode())
    else:
        out = match_mask_bundle_fused(
            text_cols, num_cols, bundle, cl_inputs, msm, boost, live,
            emit_match=emit_match, step=step)
    tail = () if step is None else (out[-1],)
    if step is not None:
        out = out[:-1]
    if emit_match:
        total, pruned, match = out
        return (total, pruned.astype(jnp.float32), match) + tail
    total, pruned = out
    return (total, pruned.astype(jnp.float32)) + tail


# ---------------------------------------------------------------------------
# The jitted per-segment program: query eval + top-k + aggregations
# ---------------------------------------------------------------------------

# per-chunk transient budget in elements: a batch whose [B, cap] dense
# accumulators would exceed this executes as sequential lax.map chunks
# inside ONE program — one device dispatch (the tunnel charges ~65ms per
# dispatch), bounded HBM transients
_CHUNK_ELEMS = int(_os.environ.get("ES_TPU_CHUNK_ELEMS", str(1 << 27)))


def _chunk_b(B: int, cap: int) -> int:
    bc = B
    while bc > 1 and bc * cap > _CHUNK_ELEMS:
        bc //= 2
    return bc


def _segment_body(seg: dict, params: tuple, live: jax.Array,
                  live_views: dict, agg_params: tuple, sort_params: tuple,
                  *, desc: tuple, agg_desc: tuple, cap: int, k: int,
                  sort_spec: tuple, fused: tuple | None = None,
                  step=None):
    B = _batch_size(params)
    if fused is not None:
        # fused transient per row — NOT the dense [*, cap]
        f0 = bundle_primary_field(fused[0])
        n_tiles = seg["text"][f0]["tile_max"].shape[1]
        row_elems = _fused_row_elems(
            cap, n_tiles, k, emit_match=bool(agg_desc),
            vec_clauses=sum(kd in _FUSED_VEC_KINDS
                            for _r, kd, _f, _w in fused[0]),
            pos_width=_bundle_pos_width(fused[0], seg["text"]))
    else:
        row_elems = cap
    # a resident stepped body never B-chunks: the step state (deadline
    # verdict + remaining injected-delay budget) is carried through ONE
    # tile loop — lax.map chunks would each re-meter the full budget
    bc = B if step is not None else _chunk_b(B, row_elems)
    if bc >= B:
        return _segment_body_one(
            seg, params, live, live_views, agg_params, sort_params,
            desc=desc, agg_desc=agg_desc, cap=cap, k=k,
            sort_spec=sort_spec, fused=fused, step=step)
    nc = B // bc
    chunked = jax.tree_util.tree_map(
        lambda a: a.reshape((nc, bc) + a.shape[1:]), params)
    out = jax.lax.map(
        lambda p: _segment_body_one(
            seg, p, live, live_views, agg_params, sort_params,
            desc=desc, agg_desc=agg_desc, cap=cap, k=k,
            sort_spec=sort_spec, fused=fused),
        chunked)
    return jax.tree_util.tree_map(
        lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), out)


def _segment_body_one(seg: dict, params: tuple, live: jax.Array,
                      live_views: dict, agg_params: tuple,
                      sort_params: tuple, *, desc: tuple, agg_desc: tuple,
                      cap: int, k: int, sort_spec: tuple,
                      fused: tuple | None = None, step=None):
    B = _batch_size(params)
    if fused is not None:
        # fused block-max score + top-k: never materializes the [B, cap]
        # SCORE matrix. Plan admission (score sort, k>0, boost>0, tile
        # summaries present) happened host-side in execute_segment_async.
        # Plans that also carry aggregations run the XLA engine in
        # emit-match mode: the tile loop writes the exact bool match
        # mask (hard-pruned tiles keep their zeros) and the ordinary
        # aggregation pass consumes it. A resident `step` threads the
        # per-chunk deadline check through the tile loop and appends
        # the device-side timed_out verdict to the return.
        bundle, backend = fused
        step_tail = (jnp.bool_(False),) if step is not None else ()
        if k == 0:
            # match-mask-only engine: size-0 counts / filtered aggs skip
            # the score matrix AND top-k selection (the k_zero gap)
            if agg_desc:
                out = eval_fused_match(
                    seg, desc, params, live, bundle, backend,
                    emit_match=True, step=step)
                if step is not None:
                    total, pruned, match, timed = out
                    step_tail = (timed,)
                else:
                    total, pruned, match = out
                plan = _agg_view_plan(desc, agg_desc, agg_params, seg,
                                      live_views)
                views = _ViewMasks(desc, params, seg, live_views, cap, B)
                agg_out = eval_aggs(agg_desc, agg_params, seg, match,
                                    views=views, plan=plan)
            else:
                out = eval_fused_match(
                    seg, desc, params, live, bundle, backend,
                    emit_match=False, step=step)
                if step is not None:
                    total, pruned, timed = out
                    step_tail = (timed,)
                else:
                    total, pruned = out
                agg_out = {}
            empty_f = jnp.zeros((B, 0), jnp.float32)
            return ((empty_f, empty_f, jnp.zeros((B, 0), jnp.int32),
                     total, jnp.zeros((B, 0), bool)), agg_out,
                    jnp.broadcast_to(pruned[None, :] / B, (B, 3))
                    ) + step_tail
        if agg_desc:
            out = eval_fused_topk(
                seg, desc, params, live, k, bundle, backend,
                emit_match=True, step=step)
            if step is not None:
                top_score, top_idx, total, pruned, match, timed = out
                step_tail = (timed,)
            else:
                top_score, top_idx, total, pruned, match = out
            plan = _agg_view_plan(desc, agg_desc, agg_params, seg,
                                  live_views)
            views = _ViewMasks(desc, params, seg, live_views, cap, B)
            agg_out = eval_aggs(agg_desc, agg_params, seg, match,
                                views=views, plan=plan)
        else:
            out = eval_fused_topk(
                seg, desc, params, live, k, bundle, backend, step=step)
            if step is not None:
                top_score, top_idx, total, pruned, timed = out
                step_tail = (timed,)
            else:
                top_score, top_idx, total, pruned = out
            agg_out = {}
        # each row carries its chunk's prune stats / chunk size, so a
        # row-sum at collect time reconstructs (approximately, when the
        # real batch undershoots the padded one) the dispatch totals
        prune_rows = jnp.broadcast_to(pruned[None, :] / B, (B, 3))
        top_missing = jnp.zeros_like(top_idx, dtype=bool)
        return ((top_score, top_score, top_idx, total, top_missing),
                agg_out, prune_rows) + step_tail
    plan = _agg_view_plan(desc, agg_desc, agg_params, seg, live_views)
    views = _ViewMasks(desc, params, seg, live_views, cap, B)
    # aggs-only requests whose every agg node rides a sorted view skip
    # the doc-space query eval entirely (total comes from a view mask)
    skip_doc = bool(k == 0 and sort_spec == ("_score",) and agg_desc
                    and plan and all(plan))
    if skip_doc:
        valid = None
        node0 = agg_desc[0][1]
        key0 = (("kw", node0[1]) if node0[0] == "terms_kw"
                else ("num", node0[1]))
        total = views.mask(key0).sum(axis=-1, dtype=jnp.int32)
    else:
        score, match = eval_node(desc, params, seg, cap, B)
        valid = match & live[None, :]
        score = jnp.where(valid, score, 0.0)

    if k == 0:
        top_score = jnp.zeros((B, 0), jnp.float32)
        top_key = top_score
        top_idx = jnp.zeros((B, 0), jnp.int32)
        top_missing = jnp.zeros((B, 0), bool)
        if not skip_doc:
            total = valid.sum(axis=-1, dtype=jnp.int32)
        agg_out = eval_aggs(agg_desc, agg_params, seg, valid,
                            views=views, plan=plan)
        return (top_score, top_key, top_idx, total, top_missing), \
            agg_out, jnp.zeros((B, 3), jnp.float32)

    if sort_spec[0] == "_score":
        top_key, top_idx, total = top_k_hits(score, valid, k)
        top_score = top_key
        top_missing = jnp.zeros_like(top_idx, dtype=bool)
    else:
        _, field, descending, kindtag = sort_spec
        if kindtag == "kw" and field in seg["kw"]:
            # segment-local ordinals -> shard-global ords so the key is
            # comparable across segments (review: local ords mis-merge)
            (s2g,) = sort_params
            local = seg["kw"][field]
            keys = s2g[jnp.clip(local, 0, None)]
            missing = local < 0
        elif kindtag == "geo":
            # geo_distance sort: key = meters/unit from a dynamic origin
            # (sort_params, no recompile per origin)
            from ..ops.geo import haversine_m
            if field in seg["geo"]:
                lat_q, lon_q, unit_m = sort_params
                g = seg["geo"][field]
                keys = haversine_m(g["lat"], g["lon"], lat_q, lon_q) / unit_m
                missing = ~g["exists"]
            else:
                keys = jnp.zeros((cap,), jnp.float32)
                missing = jnp.ones((cap,), bool)
        elif kindtag == "script":
            from ..script import compile_script, ColumnDocAccessor
            src, ptag = field.split("\x00", 1)
            sparams = {kv.split("=", 1)[0]: float(kv.split("=", 1)[1])
                       for kv in ptag.split(",") if kv}
            cs = compile_script(src)
            val = cs.run(doc=ColumnDocAccessor(seg, jnp), params=sparams,
                         xp=jnp)
            keys = jnp.broadcast_to(jnp.asarray(val, jnp.float32), (cap,))
            missing = jnp.zeros((cap,), bool)
        elif kindtag == "num" and field in seg["num"]:
            keys = seg["num"][field]["values"]
            missing = ~seg["num"][field]["exists"]
        else:  # field absent from this whole segment
            keys = jnp.zeros((cap,), jnp.int32)
            missing = jnp.ones((cap,), bool)
        top_key, top_idx, total, top_missing = top_k_by_field(
            keys, valid, missing, k, descending)
        top_score = jnp.take_along_axis(score, top_idx, axis=1)

    agg_out = eval_aggs(agg_desc, agg_params, seg, valid,
                        views=views, plan=plan)
    return (top_score, top_key, top_idx, total, top_missing), \
        agg_out, jnp.zeros((B, 3), jnp.float32)


def _batch_size(params) -> int:
    leaves = jax.tree_util.tree_leaves(params)
    if not leaves:
        return 1
    return leaves[0].shape[0]


# ---------------------------------------------------------------------------
# Aggregations: desc interpreter (device part)
# ---------------------------------------------------------------------------
# agg desc nodes (see search/aggregations.py for parse/reduce):
#   ("terms_kw", field, n_global, sub_metrics)     params: (seg2global, g2seg)
#   ("hist_fixed", field, n_buckets, sub_metrics)  params: (origin, interval)
#   ("hist_edges", field, n_buckets, sub_metrics)  params: (edges,)
#   ("stats", field)                               params: ()
#   ("value_count_kw"|"value_count_num"|..., field) params: ()
#   ("global",) / ("filter", child_desc)           -- round 2
# sub_metrics: tuple of ("avg"|"sum"|"min"|"max"|"stats"|"value_count", field)


def _merge_metric_dicts(acc: dict, st: dict) -> dict:
    """Merge per-value-slot metric partials: min/max fold, others sum."""
    for k, v in st.items():
        if k == "min":
            acc[k] = jnp.minimum(acc[k], v)
        elif k == "max":
            acc[k] = jnp.maximum(acc[k], v)
        else:
            acc[k] = acc[k] + v
    return acc


def _empty_bucket_metric(mkind: str, B: int, n_buckets: int) -> dict:
    entry = {}
    zero = jnp.zeros((B, n_buckets), jnp.float32)
    if mkind in ("avg", "sum", "stats", "extended_stats"):
        entry["sum"] = zero
    if mkind in ("avg", "stats", "extended_stats", "value_count"):
        entry["count"] = zero
    if mkind in ("min", "stats", "extended_stats"):
        entry["min"] = jnp.full((B, n_buckets), jnp.inf, jnp.float32)
    if mkind in ("max", "stats", "extended_stats"):
        entry["max"] = jnp.full((B, n_buckets), -jnp.inf, jnp.float32)
    if mkind == "extended_stats":
        entry["sum_sq"] = zero
    return entry


def _hist_edges_for(kind, params, n_buckets, dtype):
    if kind == "hist_fixed":
        origin, interval = params
        if dtype == jnp.int32:
            # int32 columns (epoch seconds) need EXACT edges — f32 would
            # smear boundaries past 2^24. The pow2-padded tail may
            # overflow int32; clamp it to INT32_MAX (monotonicity is all
            # searchsorted needs past the data max).
            rng = jnp.arange(n_buckets + 1, dtype=jnp.int32)
            o = origin.astype(jnp.int32)
            off = interval.astype(jnp.int32) * rng
            s = o + off
            # the pow2-padded tail may overflow int32 in `off` OR in
            # `o + off`; clamp every edge whose true value could exceed
            # INT32_MAX (f32 magnitude guard catches double-wraps the
            # sign tests can't see). Monotonicity is all searchsorted
            # needs past the data max.
            lim = jnp.int32(2**31 - 1)
            approx = o.astype(jnp.float32) \
                + interval.astype(jnp.float32) * rng.astype(jnp.float32)
            bad = (off < 0) | (s < o) \
                | (approx >= jnp.float32(2**31 - 256))
            return jnp.where(bad, lim, s)
        rng = jnp.arange(n_buckets + 1, dtype=jnp.float32)
        edges = origin.astype(jnp.float32) \
            + interval.astype(jnp.float32) * rng
    else:
        (edges,) = params
    return edges.astype(dtype)


def _sorted_hist_counts(srtn, exists, valid, edges,
                        weights=None) -> jax.Array:
    """Shared sorted-histogram reduce: exists-masked (optionally value-
    weighted) counts per edge bucket — the single calling convention the
    histogram, percentile, and sub-metric paths all go through."""
    w = jnp.where(exists[None, :], valid.astype(jnp.float32), 0.0)
    if weights is not None:
        w = w * weights
    return agg_ops.sorted_hist_reduce(srtn["vals"].astype(edges.dtype)
                                      if srtn["vals"].dtype != edges.dtype
                                      else srtn["vals"],
                                      srtn["perm"], w, edges)


def _hist_sorted(seg, col, srtn, valid, subs, kind, params, n_buckets):
    """Scatter-free histogram: docs are value-sorted (static perm), so
    bucket sums are cumsum differences at searchsorted edge positions
    (ops/aggs.sorted_hist_reduce)."""
    perm, sorted_vals = srtn["perm"], srtn["vals"]
    edges = _hist_edges_for(kind, params, n_buckets, sorted_vals.dtype)
    exists = col["exists"]
    w = jnp.where(exists[None, :], valid.astype(jnp.float32), 0.0)
    entry = {"counts": _sorted_hist_counts(srtn, exists, valid, edges)}
    for mname, mfield, mkind in subs:
        mcol = seg["num"].get(mfield)
        B = valid.shape[0]
        if mcol is None:
            entry[mname] = _empty_bucket_metric(mkind, B, n_buckets)
            continue
        if "mv_values" in mcol or mkind not in ("avg", "sum",
                                                "value_count"):
            # multi-valued sources and min/max-bearing metrics keep the
            # per-doc scatter path
            if kind == "hist_fixed":
                origin, interval = params
                bids = agg_ops.fixed_histogram_bucket_ids(
                    col["values"], exists, origin, interval, n_buckets)
            else:
                bids = agg_ops.edges_bucket_ids(col["values"], exists,
                                                params[0], n_buckets)
            entry[mname] = _bucket_metrics(
                bids, valid, [(mname, mfield, mkind)], seg,
                n_buckets)[mname]
            continue
        mvals, mex = mcol["values"], mcol["exists"]
        wm = jnp.where(mex[None, :], w, 0.0)
        st: dict = {}
        if mkind == "sum":
            st["sum"] = agg_ops.sorted_hist_reduce(
                sorted_vals, perm,
                wm * mvals.astype(jnp.float32)[None, :], edges)
        if mkind == "avg":
            st["sum"] = agg_ops.sorted_hist_reduce(
                sorted_vals, perm,
                wm * mvals.astype(jnp.float32)[None, :], edges)
            st["count"] = agg_ops.sorted_hist_reduce(sorted_vals, perm,
                                                     wm, edges)
        if mkind == "value_count":
            st["count"] = agg_ops.sorted_hist_reduce(sorted_vals, perm,
                                                     wm, edges)
        entry[mname] = st
    return entry


def _to_global(seg_arr, g2seg):
    """Per-segment-group array [B, G] -> shard-global bucket space via
    the INVERSE ordinal map (a gather — global ords map injectively from
    segment ords, so no scatter is ever needed; TPU scatter costs ~65ms
    regardless of size while this gather is microseconds)."""
    safe = jnp.clip(g2seg, 0, None)
    out = jnp.take(seg_arr, safe, axis=-1)
    return jnp.where((g2seg >= 0)[None, :], out, 0.0)


def _terms_sorted(seg, field, srt, valid, subs, seg2global, g2seg,
                  n_global):
    """Scatter-free terms aggregation over the static ordinal-sort
    layout (ops/aggs.sorted_group_reduce): per-doc scatters become
    permute+cumsum+boundary-gather, and the local->global remap rides
    the inverse ordinal map (another gather)."""
    perm, starts = srt["perm"], srt["starts"]
    w = valid.astype(jnp.float32)
    entry = {"counts": _to_global(
        agg_ops.sorted_group_reduce(perm, starts, w), g2seg)}
    for mname, mfield, mkind in subs:
        col = seg["num"].get(mfield)
        B = valid.shape[0]
        if col is None:
            entry[mname] = _empty_bucket_metric(mkind, B, n_global)
            continue
        if "mv_values" in col or mkind not in ("avg", "sum",
                                               "value_count"):
            # multi-valued sources and min/max-bearing metrics keep the
            # per-doc scatter; the layout's presence on a segment does
            # not restrict which descs may run against it
            bids = agg_ops.keyword_bucket_ids(seg["kw"][field],
                                              seg2global, n_global)
            entry[mname] = _bucket_metrics(
                bids, valid, [(mname, mfield, mkind)], seg,
                n_global)[mname]
            continue
        vals, exists = col["values"], col["exists"]
        wm = jnp.where(exists[None, :], w, 0.0)
        st: dict = {}
        if mkind in ("avg", "sum"):
            st["sum"] = _to_global(
                agg_ops.sorted_group_reduce(
                    perm, starts, wm * vals.astype(jnp.float32)[None, :]),
                g2seg)
        if mkind in ("avg", "value_count"):
            st["count"] = _to_global(
                agg_ops.sorted_group_reduce(perm, starts, wm), g2seg)
        entry[mname] = st
    return entry


def _view_bucket_entry(store: dict, vm: jax.Array, subs, bounds,
                       n_out: int, post=None) -> dict:
    """Shared view-space bucket reduce: counts + avg/sum/value_count
    sub-metrics as block reduces of sorted-space weights at `bounds`.
    Repeated (weight, field) reduces are memoized (avg shares sum's
    reduce and value_count's count); counts accumulate in int32.
    `post` maps each per-layout array to the output bucket space
    (terms: segment-ordinal -> shard-global gather)."""
    if post is None:
        post = lambda a: a  # noqa: E731
    B = vm.shape[0]
    memo: dict = {}

    def counts_of(mask, key):
        if key not in memo:
            memo[key] = agg_ops.view_group_reduce(
                mask, bounds, int_weights=True).astype(jnp.float32)
        return memo[key]

    entry = {"counts": post(counts_of(vm, ("count", None)))}
    for mname, mfield, mkind in subs:
        pcol = store.get("vw_num", {}).get(mfield)
        if pcol is None:
            entry[mname] = _empty_bucket_metric(mkind, B, n_out)
            continue
        st: dict = {}
        if mkind in ("avg", "sum"):
            key = ("sum", mfield)
            if key not in memo:
                wv = jnp.where(vm & pcol["exists"][None, :],
                               pcol["values"].astype(jnp.float32)[None, :],
                               0.0)
                memo[key] = agg_ops.view_group_reduce(wv, bounds)
            st["sum"] = post(memo[key])
        if mkind in ("avg", "value_count"):
            st["count"] = post(counts_of(vm & pcol["exists"][None, :],
                                         ("count", mfield)))
        entry[mname] = st
    return entry


def _terms_view(store: dict, vm: jax.Array, subs, g2seg, n_global: int
                ) -> dict:
    """Terms aggregation fully in sorted view space: group sums are
    block reduces of the sorted-space valid mask at the static group
    boundaries — no per-query gather, int32-exact counts."""
    return _view_bucket_entry(store, vm, subs, store["starts"], n_global,
                              post=lambda a: _to_global(a, g2seg))


def _hist_view(store: dict, vm: jax.Array, subs, kind, params,
               n_buckets: int) -> dict:
    """(date_)histogram in sorted view space: bucket boundaries come
    from a log-depth searchsorted of the static sorted values; sums are
    block reduces of sorted-space weights."""
    sv = store["vals"]
    edges = _hist_edges_for(kind, params, n_buckets, sv.dtype)
    pos = jnp.searchsorted(sv, edges, side="left").astype(jnp.int32)
    return _view_bucket_entry(store, vm & store["sexists"][None, :],
                              subs, pos, n_buckets)


def _pctl_view(store: dict, vm: jax.Array, lo, width, n_bins: int) -> dict:
    inner = lo.astype(jnp.float32) + width.astype(jnp.float32) \
        * jnp.arange(1, n_bins, dtype=jnp.float32)
    edges = jnp.concatenate([
        jnp.asarray([-jnp.inf], jnp.float32), inner,
        jnp.asarray([jnp.inf], jnp.float32)])
    pos = jnp.searchsorted(store["vals"].astype(jnp.float32), edges,
                           side="left").astype(jnp.int32)
    w = vm & store["sexists"][None, :]
    return {"counts": agg_ops.view_group_reduce(
        w, pos, int_weights=True).astype(jnp.float32)}


def _compress_topk(entry: dict, top_s: int) -> dict:
    """Shrink a terms partial to its per-segment top buckets by count
    (device-side shard_size, ref: InternalTerms shard-level truncation):
    the wire ships 2*top_s+1 floats per query instead of n_global —
    the download through a remote-device tunnel dominates the agg
    otherwise. Indices ride as f32 (exact below 2^24)."""
    counts = entry["counts"]
    tv, ti = jax.lax.top_k(counts, top_s)
    out = {"top_counts": tv, "top_idx": ti.astype(jnp.float32),
           "total": counts.sum(axis=-1, keepdims=True)}
    for mname, st in entry.items():
        if mname == "counts" or not isinstance(st, dict):
            continue
        for key, arr in st.items():
            out[f"sub\x00{mname}\x00{key}"] = jnp.take_along_axis(
                arr, ti, axis=-1)
    return out


def _bucket_metrics(bucket_ids, mask, sub_metrics, seg, n_buckets):
    B = mask.shape[0]
    out = {}
    for mname, mfield, mkind in sub_metrics:
        col = seg["num"].get(mfield)
        if col is None:
            out[mname] = _empty_bucket_metric(mkind, B, n_buckets)
            continue
        # multi-valued metric source: every value of the doc lands in the
        # bucket (SortedNumeric values iteration)
        val_cols = ([(col["mv_values"][:, m], col["mv_exists"][:, m])
                     for m in range(col["mv_values"].shape[1])]
                    if "mv_values" in col
                    else [(col["values"], col["exists"])])
        entry = _empty_bucket_metric(mkind, B, n_buckets)
        for vals, exists in val_cols:
            m = mask & exists[None, :]
            if mkind in ("avg", "sum", "stats", "extended_stats"):
                entry["sum"] = entry["sum"] + agg_ops.bucket_sums(
                    bucket_ids, m, vals, n_buckets)
            if mkind in ("avg", "stats", "extended_stats", "value_count"):
                entry["count"] = entry["count"] + agg_ops.bucket_counts(
                    bucket_ids, m, n_buckets)
            if mkind in ("min", "stats", "extended_stats"):
                entry["min"] = jnp.minimum(entry["min"], agg_ops.bucket_min(
                    bucket_ids, m, vals, n_buckets))
            if mkind in ("max", "stats", "extended_stats"):
                entry["max"] = jnp.maximum(entry["max"], agg_ops.bucket_max(
                    bucket_ids, m, vals, n_buckets))
            if mkind == "extended_stats":
                entry["sum_sq"] = entry["sum_sq"] + agg_ops.bucket_sum_sq(
                    bucket_ids, m, vals, n_buckets)
        out[mname] = entry
    return out


def _empty_buckets(subs, B: int, n_buckets: int) -> dict:
    entry = {"counts": jnp.zeros((B, n_buckets), jnp.float32)}
    for mname, _f, mkind in subs:
        entry[mname] = _empty_bucket_metric(mkind, B, n_buckets)
    return entry


def eval_aggs(agg_desc: tuple, agg_params: tuple, seg: dict,
              valid: jax.Array | None, views: "_ViewMasks | None" = None,
              plan: tuple = ()) -> dict:
    """Per-segment device aggregation. A segment lacking the aggregated
    column (field introduced later / sparse mapping) contributes zero
    partials instead of crashing. `plan[i]` (static) routes node i
    through its sorted-view path; `valid` may be None when every node
    does (the doc-space mask was never materialized)."""
    out: dict[str, Any] = {}
    B = views.B if views is not None else valid.shape[0]
    for ni, ((name, node), params) in enumerate(zip(agg_desc, agg_params)):
        kind = node[0]
        use_view = bool(plan) and plan[ni]
        if kind == "terms_kw":
            _, field, n_global, subs, top_s = node
            if use_view:
                seg2global, g2seg = params
                vm = views.mask(("kw", field))
                entry = _terms_view(seg["kw_sorted"][field], vm, subs,
                                    g2seg, n_global)
                out[name] = _compress_topk(entry, top_s) if top_s \
                    else entry
                continue
            if field not in seg["kw"]:
                # every branch must agree on compressed-vs-full: the
                # shard merge reads whichever form the FIRST segment
                # produced for all of them
                entry = _empty_buckets(subs, B, n_global)
                out[name] = _compress_topk(entry, top_s) if top_s \
                    else entry
                continue
            seg2global, g2seg = params
            if field in seg.get("kw_mv", {}):
                # multi-valued: one collect per ordinal SLOT (ref:
                # GlobalOrdinalsStringTermsAggregator over SortedSet —
                # each distinct ord of a doc lands in its bucket once)
                mv = seg["kw_mv"][field]
                entry = _empty_buckets(subs, B, n_global)
                counts = entry["counts"]
                for m in range(mv.shape[1]):
                    bids = agg_ops.keyword_bucket_ids(mv[:, m], seg2global,
                                                      n_global)
                    counts = counts + agg_ops.bucket_counts(bids, valid,
                                                            n_global)
                    sub = _bucket_metrics(bids, valid, subs, seg, n_global)
                    for mname, st in sub.items():
                        _merge_metric_dicts(entry[mname], st)
                entry["counts"] = counts
                out[name] = _compress_topk(entry, top_s) if top_s \
                    else entry
                continue
            srt = seg.get("kw_sorted", {}).get(field)
            if srt is not None and srt["starts"].shape[0] - 1 \
                    == seg2global.shape[0]:
                entry = _terms_sorted(seg, field, srt, valid, subs,
                                      seg2global, g2seg, n_global)
            else:
                bids = agg_ops.keyword_bucket_ids(seg["kw"][field],
                                                  seg2global, n_global)
                entry = {"counts": agg_ops.bucket_counts(bids, valid,
                                                         n_global)}
                entry.update(_bucket_metrics(bids, valid, subs, seg,
                                             n_global))
            if top_s:
                entry = _compress_topk(entry, top_s)
            out[name] = entry
        elif kind in ("hist_fixed", "hist_edges"):
            _, field, n_buckets, subs = node
            if use_view:
                vm = views.mask(("num", field))
                out[name] = _hist_view(seg["num_sorted"][field], vm, subs,
                                       kind, params, n_buckets)
                continue
            if field not in seg["num"]:
                out[name] = _empty_buckets(subs, B, n_buckets)
                continue
            col = seg["num"][field]
            srtn = seg.get("num_sorted", {}).get(field)
            if srtn is not None and "mv_values" not in col:
                out[name] = _hist_sorted(seg, col, srtn, valid, subs,
                                         kind, params, n_buckets)
                continue
            val_cols = ([(col["mv_values"][:, m], col["mv_exists"][:, m])
                         for m in range(col["mv_values"].shape[1])]
                        if "mv_values" in col
                        else [(col["values"], col["exists"])])
            entry = _empty_buckets(subs, B, n_buckets)
            counts = entry["counts"]
            prev_bids: list = []
            for vcol, ecol in val_cols:
                if kind == "hist_fixed":
                    origin, interval = params
                    bids = agg_ops.fixed_histogram_bucket_ids(
                        vcol, ecol, origin, interval, n_buckets)
                else:
                    (edges,) = params
                    bids = agg_ops.edges_bucket_ids(vcol, ecol, edges,
                                                    n_buckets)
                # a doc lands in each DISTINCT bucket once (ref:
                # HistogramAggregator previousKey dedup for multi-values)
                v_ok = valid
                for pb in prev_bids:
                    v_ok = v_ok & (bids != pb)[None, :]
                prev_bids.append(bids)
                counts = counts + agg_ops.bucket_counts(bids, v_ok,
                                                        n_buckets)
                sub = _bucket_metrics(bids, v_ok, subs, seg, n_buckets)
                for mname, st in sub.items():
                    _merge_metric_dicts(entry[mname], st)
            entry["counts"] = counts
            out[name] = entry
        elif kind == "stats_script":
            # metric over a device-evaluated expression (script metric
            # aggs + the restricted scripted_metric; params are baked
            # into the tag as static constants)
            _, tag = node
            vals = _eval_agg_script(tag, seg, valid.shape[-1],
                                    valid.shape[0])
            m = valid
            cnt = m.sum(axis=-1, dtype=jnp.float32)
            out[name] = {
                "count": cnt,
                "sum": jnp.where(m, vals, 0.0).sum(axis=-1),
                "sum_sq": jnp.where(m, vals * vals, 0.0).sum(axis=-1),
                "min": jnp.where(m, vals, jnp.inf).min(axis=-1),
                "max": jnp.where(m, vals, -jnp.inf).max(axis=-1),
            }
        elif kind == "stats":
            _, field = node
            col = seg["num"].get(field)
            if col is not None and "mv_values" in col:
                # every value participates (SortedNumeric stats)
                mv, me = col["mv_values"], col["mv_exists"]
                acc = None
                for m in range(mv.shape[1]):
                    st = agg_ops.masked_stats(mv[:, m], me[:, m], valid)
                    if acc is None:
                        acc = dict(st)
                    else:
                        _merge_metric_dicts(acc, st)
                out[name] = acc
                continue
            if col is None:
                out[name] = {"count": jnp.zeros((B,), jnp.float32),
                             "sum": jnp.zeros((B,), jnp.float32),
                             "sum_sq": jnp.zeros((B,), jnp.float32),
                             "min": jnp.full((B,), jnp.inf, jnp.float32),
                             "max": jnp.full((B,), -jnp.inf, jnp.float32)}
                continue
            out[name] = agg_ops.masked_stats(col["values"], col["exists"], valid)
        elif kind == "value_count_num":
            _, field = node
            col = seg["num"].get(field)
            if col is None:
                out[name] = {"count": jnp.zeros((B,), jnp.float32)}
                continue
            if "mv_values" in col:
                m = valid[:, :, None] & col["mv_exists"][None]
                out[name] = {"count": m.sum(axis=(-1, -2),
                                            dtype=jnp.float32)}
            else:
                m = valid & col["exists"][None, :]
                out[name] = {"count": m.sum(axis=-1, dtype=jnp.float32)}
        elif kind == "value_count_kw":
            _, field = node
            if field not in seg["kw"]:
                out[name] = {"count": jnp.zeros((B,), jnp.float32)}
                continue
            if field in seg.get("kw_mv", {}):
                m = valid[:, :, None] & (seg["kw_mv"][field] >= 0)[None]
                out[name] = {"count": m.sum(axis=(-1, -2),
                                            dtype=jnp.float32)}
            else:
                m = valid & (seg["kw"][field] >= 0)[None, :]
                out[name] = {"count": m.sum(axis=-1, dtype=jnp.float32)}
        elif kind == "pctl":
            # fixed-resolution histogram for percentile interpolation
            # (device-side t-digest analog; host merges weighted bins)
            _, field, n_bins = node
            if use_view:
                lo, width = params
                out[name] = _pctl_view(seg["num_sorted"][field],
                                       views.mask(("num", field)),
                                       lo, width, n_bins)
                continue
            col = seg["num"].get(field)
            if col is None:
                out[name] = {"counts": jnp.zeros((B, n_bins), jnp.float32)}
                continue
            lo, width = params
            if "mv_values" in col:
                counts = jnp.zeros((B, n_bins), jnp.float32)
                mv, me = col["mv_values"], col["mv_exists"]
                for m in range(mv.shape[1]):
                    v = mv[:, m].astype(jnp.float32)
                    bids = jnp.clip((v - lo) / width, 0,
                                    n_bins - 1).astype(jnp.int32)
                    bids = jnp.where(me[:, m], bids, n_bins)
                    counts = counts + agg_ops.bucket_counts(bids, valid,
                                                            n_bins)
                out[name] = {"counts": counts}
                continue
            srtn = seg.get("num_sorted", {}).get(field)
            if srtn is not None:
                # scatter-free: value-sorted cumsum at bin edges; the
                # outer edges are +-inf to reproduce the clip-into-
                # first/last-bin semantics of the bucket-id path
                inner = lo.astype(jnp.float32) \
                    + width.astype(jnp.float32) \
                    * jnp.arange(1, n_bins, dtype=jnp.float32)
                edges = jnp.concatenate([
                    jnp.asarray([-jnp.inf], jnp.float32), inner,
                    jnp.asarray([jnp.inf], jnp.float32)])
                out[name] = {"counts": _sorted_hist_counts(
                    srtn, col["exists"], valid, edges)}
                continue
            v = col["values"].astype(jnp.float32)
            bids = jnp.clip((v - lo) / width, 0, n_bins - 1).astype(jnp.int32)
            bids = jnp.where(col["exists"], bids, n_bins)
            out[name] = {"counts": agg_ops.bucket_counts(bids, valid, n_bins)}
        elif kind == "geo_bounds":
            # masked lat/lon extrema (ref: metrics/geobounds/
            # GeoBoundsAggregator — running min/max per bucket)
            _, field = node
            g = seg.get("geo", {}).get(field)
            if g is None:
                out[name] = {"stats": {
                    "count": jnp.zeros((B,), jnp.float32),
                    "min_lat": jnp.full((B,), jnp.inf, jnp.float32),
                    "max_lat": jnp.full((B,), -jnp.inf, jnp.float32),
                    "min_lon": jnp.full((B,), jnp.inf, jnp.float32),
                    "max_lon": jnp.full((B,), -jnp.inf, jnp.float32)}}
                continue
            m = valid & g["exists"][None, :]
            lat = g["lat"][None, :]
            lon = g["lon"][None, :]
            out[name] = {"stats": {
                "count": m.sum(axis=-1, dtype=jnp.float32),
                "min_lat": jnp.where(m, lat, jnp.inf).min(axis=-1),
                "max_lat": jnp.where(m, lat, -jnp.inf).max(axis=-1),
                "min_lon": jnp.where(m, lon, jnp.inf).min(axis=-1),
                "max_lon": jnp.where(m, lon, -jnp.inf).max(axis=-1)}}
        elif kind == "geo_centroid":
            _, field = node
            g = seg.get("geo", {}).get(field)
            if g is None:
                out[name] = {"stats": {
                    "count": jnp.zeros((B,), jnp.float32),
                    "sum_lat": jnp.zeros((B,), jnp.float32),
                    "sum_lon": jnp.zeros((B,), jnp.float32)}}
                continue
            m = valid & g["exists"][None, :]
            out[name] = {"stats": {
                "count": m.sum(axis=-1, dtype=jnp.float32),
                "sum_lat": jnp.where(m, g["lat"][None, :], 0.0).sum(axis=-1),
                "sum_lon": jnp.where(m, g["lon"][None, :], 0.0).sum(axis=-1)}}
        elif kind == "matchmask":
            # packed per-doc match bitmask -> host (the escape hatch for
            # host-reduced aggs: geohash_grid, scripted_metric). 1 bit
            # per doc = cap/8 bytes per query; little-endian bit order
            # to pair with np.unpackbits(bitorder="little").
            bits = valid.reshape(B, valid.shape[1] // 8, 8).astype(jnp.float32)
            weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128],
                                  jnp.float32)
            out[name] = {"mask": (bits * weights).sum(axis=-1)}
        elif kind == "cardinality_kw":
            _, field, n_global = node
            if field not in seg["kw"]:
                out[name] = {"counts": jnp.zeros((B, n_global), jnp.float32)}
                continue
            (seg2global,) = params
            if field in seg.get("kw_mv", {}):
                mv = seg["kw_mv"][field]
                counts = jnp.zeros((B, n_global), jnp.float32)
                for m in range(mv.shape[1]):
                    bids = agg_ops.keyword_bucket_ids(mv[:, m], seg2global,
                                                      n_global)
                    counts = counts + agg_ops.bucket_counts(bids, valid,
                                                            n_global)
            else:
                bids = agg_ops.keyword_bucket_ids(seg["kw"][field],
                                                  seg2global, n_global)
                counts = agg_ops.bucket_counts(bids, valid, n_global)
            out[name] = {"counts": counts}  # host reduces then counts nonzero
        elif kind == "cardinality_hll":
            # HLL++ sketch: scatter-MAX of per-ordinal ranks into 2^p
            # registers (ref: HyperLogLogPlusPlus.collect); the "max"
            # key makes segment/shard/mesh reduction an elementwise max
            _, field, m = node
            reg_l, rank_l = params
            if field not in seg["kw"] or reg_l.shape[0] == 0:
                out[name] = {"max": jnp.zeros((B, m), jnp.float32)}
                continue

            def hll_update(ords, regs):
                safe = jnp.clip(ords, 0, None)
                r = reg_l[safe]                       # [cap]
                rk = rank_l[safe].astype(jnp.float32)
                ok = valid & (ords >= 0)[None, :]
                vals = jnp.where(ok, rk[None, :], 0.0)

                def one(v):
                    return jnp.zeros((m,), jnp.float32).at[r].max(
                        v, mode="drop")
                return jnp.maximum(regs, jax.vmap(one)(vals))

            regs = jnp.zeros((B, m), jnp.float32)
            if field in seg.get("kw_mv", {}):
                mv = seg["kw_mv"][field]
                for j in range(mv.shape[1]):
                    regs = hll_update(mv[:, j], regs)
            else:
                regs = hll_update(seg["kw"][field], regs)
            out[name] = {"max": regs}
        else:
            raise SearchParseError(f"unknown agg node [{kind}]")
    return out


# ---------------------------------------------------------------------------
# Public per-segment entry
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# Packed wire format for the device call
#
# Over a remote-device tunnel (axon) every host<->device transfer costs
# milliseconds of round trip, so the per-call dynamic data is packed into
# at most THREE upload buffers (int32 / float32 / bool) and ONE download
# buffer (float32). The pack layout is static per plan, so unpacking
# compiles away. (This is the moral analog of the reference's Streamable
# wire protocol — common/io/stream/ — applied to the host<->device hop.)
# ---------------------------------------------------------------------------

_DTYPE_TAGS = {"i": np.int32, "f": np.float32, "b": np.bool_}


def _pack_trees(*trees):
    """Flatten trees into 3 dtype-segregated buffers + a static spec."""
    leaves, treedef = jax.tree_util.tree_flatten(tuple(trees))
    bufs = {"i": [], "f": [], "b": []}
    spec = []
    for leaf in leaves:
        a = np.asarray(leaf)
        if a.dtype == np.bool_:
            tag = "b"
        elif np.issubdtype(a.dtype, np.floating):
            tag = "f"
            a = a.astype(np.float32, copy=False)
        else:
            tag = "i"
            a = a.astype(np.int32, copy=False)
        offset = sum(x.size for x in bufs[tag])
        bufs[tag].append(a.ravel())
        spec.append((tag, a.shape, offset, a.size))
    packed = {tag: (np.concatenate(parts) if parts
                    else np.zeros(0, _DTYPE_TAGS[tag]))
              for tag, parts in bufs.items()}
    # ONE wire buffer: [i32 | f32-bits | bool-as-i32] — a remote-device
    # tunnel charges a round trip per transfer op, so dtype segments are
    # bit-cast in and out of a single int32 array
    wire = np.concatenate([
        packed["i"],
        packed["f"].view(np.int32),
        packed["b"].astype(np.int32),
    ])
    sizes = (packed["i"].size, packed["f"].size, packed["b"].size)
    return wire, (treedef, tuple(spec), sizes)


def _unpack_trees(wire: jax.Array, static) -> tuple:
    treedef, spec, (ni, nf, nb) = static
    packed = {
        "i": wire[:ni],
        "f": jax.lax.bitcast_convert_type(wire[ni: ni + nf], jnp.float32),
        "b": wire[ni + nf: ni + nf + nb] != 0,
    }
    leaves = []
    for tag, shape, offset, size in spec:
        leaves.append(packed[tag][offset: offset + size].reshape(shape))
    return jax.tree_util.tree_unflatten(treedef, leaves)


@partial(jax.jit, static_argnames=("pack_static", "desc", "agg_desc", "cap",
                                   "k", "sort_spec", "fused"))
def _segment_program_packed(seg: dict, wire, live: jax.Array,
                            live_views: dict,
                            *, pack_static, desc: tuple, agg_desc: tuple,
                            cap: int, k: int, sort_spec: tuple,
                            fused: tuple | None = None):
    params, agg_params, sort_params = _unpack_trees(wire, pack_static)
    (top_score, top_key, top_idx, total, top_missing), agg_out, prune = \
        _segment_body(seg, params, live, live_views, agg_params,
                      sort_params, desc=desc,
                      agg_desc=agg_desc, cap=cap, k=k, sort_spec=sort_spec,
                      fused=fused)
    B = top_score.shape[0]
    # two download buffers: f32 (scores + prune + aggs) and i32 (exact
    # keys/ids) — int sort keys (epoch seconds) must NOT round-trip
    # through f32
    f_parts = [top_score]
    i_parts = [top_idx, total[:, None], top_missing.astype(jnp.int32)]
    if top_key.dtype == jnp.float32:
        f_parts.append(top_key)
    else:
        i_parts.append(top_key.astype(jnp.int32))
    f_parts.append(prune)
    for leaf in jax.tree_util.tree_leaves(agg_out):
        f_parts.append(leaf.reshape(B, -1).astype(jnp.float32))
    fbuf = jnp.concatenate(f_parts, axis=1)
    ibuf = jnp.concatenate(i_parts, axis=1)
    # single download op: f32 section bit-cast into the int32 buffer
    return jnp.concatenate(
        [ibuf, jax.lax.bitcast_convert_type(fbuf, jnp.int32)], axis=1)


# ---------------------------------------------------------------------------
# Base+delta pack dispatch (streaming write path, ROADMAP item 1)
#
# In delta mode the reader holds ONE immutable base segment and ONE
# small delta segment. A fused-admitted plan searches BOTH in a single
# device dispatch: the base tile walk runs first, its running top-k
# state (threshold included) carries into the delta walk via the ops
# layer's init_topk/idx_offset chaining, both walks' candidates merge
# through one selection, and the aggregation passes run per sub-segment
# inside the same program (ordinal spaces stay segment-local, so the
# partials meet in the EXACT same host reduce two dispatches would
# feed). Results are byte-identical to the per-segment path — the
# collect splits the merged top-k back into per-segment candidate
# lists — while the tunnel pays ONE round trip and the delta tiles
# prune against the base's threshold.
# ---------------------------------------------------------------------------


def _pack_body(seg_b: dict, seg_d: dict, params_b: tuple, params_d: tuple,
               live_b: jax.Array, live_d: jax.Array, live_views_b: dict,
               live_views_d: dict, agg_params_b: tuple, agg_params_d: tuple,
               *, desc: tuple, agg_desc: tuple, cap_b: int, cap_d: int,
               k: int, fused: tuple, step=None):
    """Fused base+delta evaluation — ONE selection over both packs plus
    per-sub-segment aggregation passes. Returns the _segment_body shape
    with `totals` widened to [B, 2] (per-sub-segment exact hit counts:
    the host split needs them to rebuild per-segment candidate lists)
    and the agg tree replaced by the (base, delta) PAIR of trees. With
    a `step`, the per-chunk deadline check rides the BASE walk (the
    dominant cost; the delta walk is bounded by the compaction
    threshold) and its verdict covers through the base's final check."""
    B = _batch_size(params_b)
    bundle, backend = fused
    emit = bool(agg_desc)
    step_tail = ()

    def aggs_for(seg, params, live_views, agg_params, match, cap):
        plan = _agg_view_plan(desc, agg_desc, agg_params, seg, live_views)
        views = _ViewMasks(desc, params, seg, live_views, cap, B)
        return eval_aggs(agg_desc, agg_params, seg, match,
                         views=views, plan=plan)

    if k == 0:
        out_b = eval_fused_match(seg_b, desc, params_b, live_b, bundle,
                                 backend, emit_match=emit, step=step)
        if step is not None:
            step_tail = (out_b[-1],)
            out_b = out_b[:-1]
        out_d = eval_fused_match(seg_d, desc, params_d, live_d, bundle,
                                 backend, emit_match=emit)
        if emit:
            total_b, prune_b, match_b = out_b
            total_d, prune_d, match_d = out_d
            agg_pair = (aggs_for(seg_b, params_b, live_views_b,
                                 agg_params_b, match_b, cap_b),
                        aggs_for(seg_d, params_d, live_views_d,
                                 agg_params_d, match_d, cap_d))
        else:
            total_b, prune_b = out_b
            total_d, prune_d = out_d
            agg_pair = ({}, {})
        totals = jnp.stack([total_b, total_d], axis=1)
        empty_f = jnp.zeros((B, 0), jnp.float32)
        prune = (prune_b + prune_d).astype(jnp.float32)
        return ((empty_f, empty_f, jnp.zeros((B, 0), jnp.int32), totals,
                 jnp.zeros((B, 0), bool)), agg_pair,
                jnp.broadcast_to(prune[None, :] / B, (B, 3))) + step_tail

    # the base walk opens at the PACK's k width (running_topk_init —
    # NOT min'd against the base capacity alone, so a delta bigger than
    # the base's tail still fills the window) and the delta walk chains
    # onto its state with indices offset past the base capacity
    from ..ops.topk import running_topk_init
    k_pack = min(k, cap_b + cap_d)
    out_b = eval_fused_topk(seg_b, desc, params_b, live_b, k_pack, bundle,
                            backend, emit_match=emit, step=step,
                            init_topk=running_topk_init(B, k_pack))
    if step is not None:
        step_tail = (out_b[-1],)
        out_b = out_b[:-1]
    if emit:
        top_s, top_i, total_b, prune_b, match_b = out_b
    else:
        top_s, top_i, total_b, prune_b = out_b
    out_d = eval_fused_topk(seg_d, desc, params_d, live_d, k_pack, bundle,
                            backend, emit_match=emit,
                            init_topk=(top_s, top_i), idx_offset=cap_b)
    if emit:
        top_s, top_i, total_d, prune_d, match_d = out_d
        agg_pair = (aggs_for(seg_b, params_b, live_views_b, agg_params_b,
                             match_b, cap_b),
                    aggs_for(seg_d, params_d, live_views_d, agg_params_d,
                             match_d, cap_d))
    else:
        top_s, top_i, total_d, prune_d = out_d
        agg_pair = ({}, {})
    totals = jnp.stack([total_b, total_d], axis=1)
    prune = (prune_b + prune_d).astype(jnp.float32)
    top_missing = jnp.zeros_like(top_i, dtype=bool)
    return ((top_s, top_s, top_i, totals, top_missing), agg_pair,
            jnp.broadcast_to(prune[None, :] / B, (B, 3))) + step_tail


@partial(jax.jit, static_argnames=("pack_static", "desc", "agg_desc",
                                   "cap_b", "cap_d", "k", "fused"))
def _pack_program_packed(seg_b: dict, seg_d: dict, wire,
                         live_b: jax.Array, live_d: jax.Array,
                         live_views_b: dict, live_views_d: dict,
                         *, pack_static, desc: tuple, agg_desc: tuple,
                         cap_b: int, cap_d: int, k: int, fused: tuple):
    """_segment_program_packed's base+delta twin: same one-buffer wire
    in/out discipline, totals carried as TWO i32 columns (base, delta)
    and the agg section holding both sub-segments' trees."""
    params_b, params_d, agg_params_b, agg_params_d = _unpack_trees(
        wire, pack_static)
    (top_score, _tk, top_idx, totals, top_missing), agg_pair, prune = \
        _pack_body(seg_b, seg_d, params_b, params_d, live_b, live_d,
                   live_views_b, live_views_d, agg_params_b, agg_params_d,
                   desc=desc, agg_desc=agg_desc, cap_b=cap_b, cap_d=cap_d,
                   k=k, fused=fused)
    B = top_score.shape[0]
    f_parts = [top_score, prune]
    i_parts = [top_idx, totals, top_missing.astype(jnp.int32)]
    for leaf in jax.tree_util.tree_leaves(agg_pair):
        f_parts.append(leaf.reshape(B, -1).astype(jnp.float32))
    fbuf = jnp.concatenate(f_parts, axis=1)
    ibuf = jnp.concatenate(i_parts, axis=1)
    return jnp.concatenate(
        [ibuf, jax.lax.bitcast_convert_type(fbuf, jnp.int32)], axis=1)


# ---------------------------------------------------------------------------
# Resident query loop (search/resident.py): AOT-pinned stepped programs
# ---------------------------------------------------------------------------

# tile-loop chunks per stepped program: each chunk boundary polls the
# host clock (deadline) and meters any injected straggler delay, so a
# laggard step can exit within one chunk of the cutoff instead of
# finishing its whole tile walk
_RESIDENT_CHUNKS = max(1, int(_os.environ.get("ES_TPU_RESIDENT_CHUNKS",
                                              "8")))


def _step_poll(hi, lo, delay_left, per_chunk, timed):
    """Host half of the device-side deadline check, invoked once per
    tile-loop chunk via io_callback. `hi + lo` reconstructs the f64
    absolute monotonic deadline from two f32 halves (one f32 loses ms
    precision at realistic uptimes); `delay_left`/`per_chunk` meter an
    injected shard_delay fault ACROSS chunks, so the delay burns inside
    device execution — where a real slow step would — and the first
    chunk past the cutoff flips timed_out, skipping the rest."""
    if bool(timed):
        return np.bool_(True), np.float32(delay_left)
    d = float(delay_left)
    if d > 0.0:
        s = min(d, float(per_chunk))
        _time.sleep(s / 1000.0)
        d -= s
    deadline = float(hi) + float(lo)
    late = math.isfinite(deadline) and _time.monotonic() > deadline
    return np.bool_(late), np.float32(d)


def _resident_step(step_arr, chunk_tiles: int):
    """Build the ops-layer step tuple (chunk_tiles, init_state, check)
    from the dynamic step scalars [dead_hi, dead_lo, per_chunk_ms,
    delay_total_ms]. The check chains (timed, delay_left) through the
    loop carry, which also serializes the callbacks."""
    from jax.experimental import io_callback

    def check(_c, st):
        timed, delay_left = st
        timed, delay_left = io_callback(
            _step_poll,
            (jax.ShapeDtypeStruct((), jnp.bool_),
             jax.ShapeDtypeStruct((), jnp.float32)),
            step_arr[0], step_arr[1], delay_left, step_arr[2], timed)
        return timed, (timed, delay_left)

    return (chunk_tiles, (jnp.bool_(False), step_arr[3]), check)


@partial(jax.jit, static_argnames=("pack_static", "desc", "agg_desc",
                                   "cap", "k", "sort_spec", "fused",
                                   "chunk_tiles"),
         donate_argnums=(1,))
def _resident_step_program(seg: dict, wire, live: jax.Array,
                           live_views: dict, step_arr,
                           *, pack_static, desc: tuple, agg_desc: tuple,
                           cap: int, k: int, sort_spec: tuple,
                           fused: tuple, chunk_tiles: int):
    """The stepped twin of _segment_program_packed: same wire format in,
    same wire format out PLUS one trailing i32 column carrying the
    device-side timed_out verdict. The query-param wire buffer is
    DONATED — the pinned executable reuses its memory, so a staged feed
    never allocates twice. AOT-compiled once per resident entry and
    invoked through the pinned executable (search/resident.py)."""
    params, agg_params, sort_params = _unpack_trees(wire, pack_static)
    (top_score, top_key, top_idx, total, top_missing), agg_out, prune, \
        timed = _segment_body(
            seg, params, live, live_views, agg_params, sort_params,
            desc=desc, agg_desc=agg_desc, cap=cap, k=k,
            sort_spec=sort_spec, fused=fused,
            step=_resident_step(step_arr, chunk_tiles))
    B = top_score.shape[0]
    f_parts = [top_score]
    i_parts = [top_idx, total[:, None], top_missing.astype(jnp.int32)]
    if top_key.dtype == jnp.float32:
        f_parts.append(top_key)
    else:
        i_parts.append(top_key.astype(jnp.int32))
    # timed_out rides LAST in the i32 section so collect can strip it
    # without disturbing the shared slice arithmetic
    i_parts.append(jnp.broadcast_to(timed.astype(jnp.int32)[None, None],
                                    (B, 1)))
    f_parts.append(prune)
    for leaf in jax.tree_util.tree_leaves(agg_out):
        f_parts.append(leaf.reshape(B, -1).astype(jnp.float32))
    fbuf = jnp.concatenate(f_parts, axis=1)
    ibuf = jnp.concatenate(i_parts, axis=1)
    return jnp.concatenate(
        [ibuf, jax.lax.bitcast_convert_type(fbuf, jnp.int32)], axis=1)


def _split_deadline(deadline: float | None) -> tuple[float, float]:
    """f64 monotonic deadline -> two f32 halves (hi + lo reconstructs it
    to sub-ms precision); +inf disables."""
    if deadline is None:
        return float("inf"), 0.0
    hi = float(np.float32(deadline))
    return hi, deadline - hi


@partial(jax.jit, static_argnames=("pack_static", "desc", "agg_desc",
                                   "cap_b", "cap_d", "k", "fused",
                                   "chunk_tiles"),
         donate_argnums=(2,))
def _resident_pack_program(seg_b: dict, seg_d: dict, wire,
                           live_b: jax.Array, live_d: jax.Array,
                           live_views_b: dict, live_views_d: dict,
                           step_arr, *, pack_static, desc: tuple,
                           agg_desc: tuple, cap_b: int, cap_d: int,
                           k: int, fused: tuple, chunk_tiles: int):
    """The stepped base+delta twin of _resident_step_program: the
    per-chunk deadline check rides the BASE tile walk (the delta walk
    is bounded by the compaction threshold, at most one chunk's worth
    of work past the base's final check), totals ride as two columns,
    and the timed_out verdict rides last in the i32 section. The wire
    is DONATED exactly like the single-segment entry."""
    params_b, params_d, agg_params_b, agg_params_d = _unpack_trees(
        wire, pack_static)
    (top_score, _tk, top_idx, totals, top_missing), agg_pair, prune, \
        timed = _pack_body(
            seg_b, seg_d, params_b, params_d, live_b, live_d,
            live_views_b, live_views_d, agg_params_b, agg_params_d,
            desc=desc, agg_desc=agg_desc, cap_b=cap_b, cap_d=cap_d,
            k=k, fused=fused,
            step=_resident_step(step_arr, chunk_tiles))
    B = top_score.shape[0]
    f_parts = [top_score, prune]
    i_parts = [top_idx, totals, top_missing.astype(jnp.int32),
               jnp.broadcast_to(timed.astype(jnp.int32)[None, None],
                                (B, 1))]
    for leaf in jax.tree_util.tree_leaves(agg_pair):
        f_parts.append(leaf.reshape(B, -1).astype(jnp.float32))
    fbuf = jnp.concatenate(f_parts, axis=1)
    ibuf = jnp.concatenate(i_parts, axis=1)
    return jnp.concatenate(
        [ibuf, jax.lax.bitcast_convert_type(fbuf, jnp.int32)], axis=1)


def _resident_backend(segment: Segment, bundle: tuple, desc, agg_desc,
                      k_eff: int, b_pad: int, ck: int) -> str | None:
    """Backend a resident stepped entry would pin, resolvable WITHOUT
    timing (the resident path cannot wall-clock a tune — its dispatch
    is pipelined): forced env, the tuner's cached choice, or a
    persisted store hit. None means the shape has no decision yet — the
    caller keeps the cold autotuned dispatch, whose first execution
    tunes the shape and unblocks residency on the NEXT dispatch.

    Pallas-tuned shapes pin Pallas stepped executables now
    (resident_step_ok — the chunked kernel hosts the per-chunk deadline
    check between pallas_call invocations); only when stepping is
    unavailable (kernels disabled) does a pallas-tuned shape stay on
    the cold dispatch rather than silently losing its kernel."""
    forced = _os.environ.get("ES_TPU_FUSED_BACKEND", "").lower()
    if forced in ("pallas", "xla"):
        # forced outranks candidacy AND any cached tuned choice, the
        # same precedence resolve_fused_backend applies — and it
        # reaches the stepped path unconditionally: the chunked walk
        # runs in interpret mode off-TPU exactly like the forced cold
        # path does, so the validation tool sees the real resident
        # pipeline (no resident_step_ok gate here; that gate protects
        # TUNED choices from silently losing their kernel)
        return forced
    if not _bundle_pallas_ok(bundle, agg_desc, ck,
                             _bundle_pos_width(bundle, segment.text)):
        return "xla"                     # XLA engine either way
    tune_key = (seg_cache_key(segment), segment.capacity, desc, k_eff,
                b_pad, bool(agg_desc))
    choice = _autotune_choices.get(tune_key)
    if choice is None:
        entry = _autotune_persisted.get(autotune_persist_key(
            seg_cache_key(segment), segment.capacity, desc, k_eff,
            bool(agg_desc)))
        choice = entry["choice"] if entry is not None else None
    if choice is None:
        return None                      # untuned: cold dispatch tunes
    if choice == "pallas" and not resident_step_ok():
        return None                      # keep the kernel, stay cold
    return choice


def _resident_admit(segment: Segment, bundle: tuple, desc, agg_desc,
                    k_eff: int, b_pad: int, ck: int) -> bool:
    """Residency admission on top of fused admission: a plan goes
    resident once its engine backend is decidable without timing
    (_resident_backend) — XLA-only shapes immediately, tuned shapes on
    their winner (either engine), untuned Pallas candidates after one
    cold autotuned dispatch."""
    return _resident_backend(segment, bundle, desc, agg_desc, k_eff,
                             b_pad, ck) is not None


def _dev_shape_sig(dev) -> tuple:
    """Shape/dtype signature of an uploaded pack tree. Part of the
    resident entry key: a delta segment keys by GENERATION (not
    content), so the key itself must pin the exact avals the AOT
    executable was compiled for — within a pow2 bucket the signature
    is constant across epoch bumps (that is what pad_delta_shapes
    buys); when a bucket grows the signature changes and the entry
    recompiles once, log-many times over a delta's life."""
    return tuple((tuple(leaf.shape), str(leaf.dtype))
                 for leaf in jax.tree_util.tree_leaves(dev))


def _resident_entry_key(segment: Segment, desc, agg_desc, sort_spec,
                        k_res: int, b_pad: int, pack_sig, dev_struct,
                        view_keys, bundle, backend: str,
                        shape_sig: tuple = ()):
    return (seg_cache_key(segment), segment.capacity, desc, agg_desc,
            sort_spec, k_res, b_pad, pack_sig, dev_struct, view_keys,
            bundle, backend, shape_sig)


def _gc_backstop(obj, hold):
    """Attach a GC backstop to a utils/breaker.Hold: the bytes release
    when the hold is released OR when `obj` is garbage collected,
    whichever first — GC alone is too lazy for tight query loops, which
    would accumulate estimates to a spurious trip; an un-weakref-able
    object (or None) releases immediately. Hold.release is idempotent,
    so the deterministic path and the finalizer cannot double-release."""
    if obj is None:
        hold.release()
        return hold
    import weakref
    try:
        weakref.finalize(obj, hold.release)
    except TypeError:
        hold.release()
    return hold


_out_layout_cache: dict = {}
# guards the cache STORES only (reads are racy-but-safe dict gets; the
# eval_shape compute runs outside so a slow abstract eval never convoys
# concurrent dispatches) — racing writers compute identical layouts
# and the setdefault keeps the first
_out_layout_lock = _threading.Lock()


def _output_layout(cache_key, seg, params, live, live_views, agg_params,
                   sort_params, desc, agg_desc, cap, k, sort_spec,
                   fused=None):
    """Host-side output layout (shapes + agg treedef) via eval_shape."""
    hit = _out_layout_cache.get(cache_key)
    if hit is not None:
        return hit
    shapes = jax.eval_shape(
        partial(_segment_body, desc=desc, agg_desc=agg_desc, cap=cap, k=k,
                sort_spec=sort_spec, fused=fused),
        seg, params, live, live_views, agg_params, sort_params)
    (ts, tk, ti, tt, tm), agg_shapes, _prune = shapes
    agg_leaves, agg_treedef = jax.tree_util.tree_flatten(agg_shapes)
    layout = {
        "k": k,
        "key_dtype": tk.dtype,
        "agg_treedef": agg_treedef,
        "agg_shapes": [tuple(s.shape) for s in agg_leaves],
        "fused": fused is not None,
        "fused_positional": (fused is not None
                             and _bundle_positional(fused[0])),
    }
    with _out_layout_lock:
        layout = _out_layout_cache.setdefault(cache_key, layout)
    return layout


def _sort_key_dtype(segment: Segment, sort_spec: tuple):
    if sort_spec[0] == "_score":
        return np.dtype(np.float32)
    _, field, _desc, kindtag = sort_spec[:4]
    if kindtag in ("script", "geo"):
        return np.dtype(np.float32)
    if kindtag == "num" and field in segment.numerics:
        return np.dtype(segment.numerics[field].values.dtype)
    return np.dtype(np.int32)  # kw ords / absent field path


def _device_live(segment: Segment, live: np.ndarray) -> jax.Array:
    """Cache the live-mask upload per (segment, mask identity): over a
    remote device tunnel every host->device hop costs milliseconds, and
    the mask only changes on delete/refresh."""
    if isinstance(live, jax.Array):
        return live
    cached = getattr(segment, "_live_dev", None)
    if cached is not None and cached[0] is live:
        return cached[1]
    dev = jnp.asarray(live)
    segment._live_dev = (live, dev)  # type: ignore[attr-defined]
    return dev


def _live_views_for(segment: Segment, live_dev: jax.Array,
                    agg_desc: tuple) -> dict:
    """Layout-permuted live masks for every agg layout that carries
    sorted-view projections. One device gather per (live epoch, layout),
    cached — the per-dispatch cost is a dict of cached arrays."""
    if not agg_desc:
        return {}
    dev = device_arrays(segment)
    cache = getattr(segment, "_live_view_cache", None)
    if cache is None or cache[0] is not live_dev:
        cache = (live_dev, {})
        segment._live_view_cache = cache  # type: ignore[attr-defined]
    out = {}
    for lkind, store_name in (("kw", "kw_sorted"), ("num", "num_sorted")):
        for f, store in dev.get(store_name, {}).items():
            if "vw_num" not in store and "vw_kw" not in store:
                continue
            key = (lkind, f)
            if key not in cache[1]:
                cache[1][key] = jnp.take(live_dev, store["perm"])
            out[key] = cache[1][key]
    return out


def _execute_resident(segment: Segment, live, desc: tuple, params: tuple,
                      agg_desc: tuple, agg_params: tuple,
                      sort_spec: tuple, sort_params: tuple,
                      bundle: tuple, backend: str, k_eff: int,
                      b_pad: int, deadline: float | None, step_budget,
                      shard_key: tuple | None, n_real: int):
    """Serve one dispatch through a pinned resident entry: stage the
    donated param feed asynchronously, invoke the AOT-compiled stepped
    executable, start the async result fetch — the split
    feed/execute/fetch pipeline that replaces the cold path's
    monolithic dispatch. k is bucketed to its next power of two so
    nearby request sizes share one executable; the response window is a
    prefix of the (larger) top-k, so responses stay byte-identical.
    `backend` is the engine _resident_backend resolved — "xla" runs the
    stepped fori tile loop, "pallas" the chunked pallas_call grid; both
    host the identical per-chunk deadline check."""
    cap = segment.capacity
    k_res = min(next_pow2(max(k_eff, 1), floor=1), cap) if k_eff > 0 else 0
    fused = (bundle, backend)
    f0 = bundle_primary_field(bundle)
    n_tiles = segment.text[f0].tile_max.shape[1]
    chunk_tiles = max(1, -(-n_tiles // _RESIDENT_CHUNKS))
    n_chunks = -(-n_tiles // chunk_tiles)
    row_elems = _fused_row_elems(
        cap, n_tiles, k_res, emit_match=bool(agg_desc),
        pos_width=_bundle_pos_width(bundle, segment.text))
    from ..utils.breaker import breaker_service
    req_breaker = breaker_service().breaker("request")
    # the stepped body never B-chunks (the step state rides ONE loop),
    # so the transient estimate covers the whole padded batch
    est = b_pad * row_elems * 8
    req_hold = req_breaker.hold(est)
    try:
        dev = device_arrays(segment)
        live_dev = _device_live(segment, live)
        live_views = _live_views_for(segment, live_dev, agg_desc)
        wire, pack_static = _pack_trees(params, agg_params, sort_params)
        # -- feed stage: async device_put; the transfer lands while the
        # host resolves the entry / earlier enqueued programs execute
        t_stage = _time.perf_counter()
        wire_dev = jax.device_put(wire)
        hi, lo = _split_deadline(deadline)
        delay_ms = float(step_budget.take()) if step_budget is not None \
            else 0.0
        step_arr = jax.device_put(np.asarray(
            [hi, lo, delay_ms / n_chunks, delay_ms], np.float32))
        key_dtype = _sort_key_dtype(segment, sort_spec)
        dev_struct = jax.tree_util.tree_structure(dev)
        view_keys = tuple(sorted(live_views))
        is_delta = getattr(segment, "delta_parent", None) is not None
        key = _resident_entry_key(segment, desc, agg_desc, sort_spec,
                                  k_res, b_pad, pack_static[1],
                                  dev_struct, view_keys, bundle, backend,
                                  shape_sig=(_dev_shape_sig(dev)
                                             if is_delta else ()))
        entry = _resident.cache.get(
            key, delta_epoch=(getattr(segment, "delta_epoch", 0)
                              if is_delta else None))
        if entry is None:
            # cold: AOT-compile and pin. The jit wrapper's cache would
            # re-hash the statics per call; the pinned executable skips
            # straight to the runtime.
            _resident.stats.cold_dispatches.inc()
            import warnings
            with warnings.catch_warnings():
                # the donated wire is only reusable when an output
                # happens to match its shape; "not usable" is the
                # expected steady state for small feeds, not a problem
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not")
                compiled = _resident_step_program.lower(
                    dev, wire_dev, live_dev, live_views, step_arr,
                    pack_static=pack_static, desc=desc, agg_desc=agg_desc,
                    cap=cap, k=k_res, sort_spec=sort_spec, fused=fused,
                    chunk_tiles=chunk_tiles).compile()
            entry = _resident.ResidentEntry(
                key, label=repr((desc, k_res, b_pad, bool(agg_desc),
                                 backend)),
                compiled=compiled, seg_id=segment.seg_id,
                fingerprint=segment.fingerprint(),
                # delta entries hold NO segment weakref: the epoch's
                # segment dies at every refresh while the executable
                # (which takes the pack as a runtime argument) must
                # survive it — compaction evicts via evict_generation
                seg_ref=(None if is_delta
                         else _resident.make_ref(segment)),
                backend=backend,
                generation=seg_cache_key(segment),
                delta_epoch=getattr(segment, "delta_epoch", 0))
            _resident.cache.put(entry)
        layout = _output_layout(
            (cap, key_dtype, desc, agg_desc, k_res, sort_spec,
             pack_static[1], dev_struct, view_keys, fused),
            dev, params, live_dev, live_views, agg_params, sort_params,
            desc, agg_desc, cap, k_res, sort_spec, fused=fused)
        # -- execute stage: invoke the pinned executable (donates wire)
        with _trace_guard.trap(), \
                _prof_annotate("query_phase:resident_dispatch"):
            buf = entry.compiled(dev, wire_dev, live_dev, live_views,
                                 step_arr)
        _resident.stats.staged_feed_overlap_ms.record(
            (_time.perf_counter() - t_stage) * 1000.0)
        # -- fetch stage: start the device->host copy now so it overlaps
        # with whatever executes next; collect's device_get then finds
        # the bytes already in flight
        try:
            buf.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
    except BaseException:
        req_hold.release()
        raise
    out_bytes = min(est, int(getattr(buf, "nbytes", 0)) or est)
    req_hold.shrink(out_bytes)
    # the request-breaker hold is attached (with its GC backstop)
    # BEFORE any further accounting can raise — no exit may leak the
    # out_bytes reservation (PR 4's invariant)
    layout = {**layout, "resident": True, "shard_key": shard_key,
              "_breaker_hold": _gc_backstop(buf, req_hold)}
    # residency-bytes accounting (fielddata breaker, held until the
    # entry is evicted): staged feed + queued output + generated code.
    # A fielddata trip here means the entry cannot afford residency —
    # evict it (releasing any partial hold) and serve this result; the
    # NEXT dispatch goes cold until pressure clears.
    code_bytes = 0
    try:
        ma = entry.compiled.memory_analysis()
        code_bytes = int(getattr(ma, "generated_code_size_in_bytes", 0)
                         or 0)
    except Exception:  # noqa: BLE001 — backend-optional introspection
        pass
    try:
        entry.account(code_bytes + int(wire.nbytes) + out_bytes)
    except Exception:  # noqa: BLE001 — breaker trip on accounting
        _resident.cache.evict(entry.key)
    return buf, layout, n_real


def execute_segment_async(segment: Segment, live: np.ndarray,
                          bounds: Sequence[Bound], k: int,
                          agg_desc: tuple = (), agg_params: tuple = (),
                          sort_spec: tuple = ("_score",),
                          sort_params: tuple = (),
                          deadline: float | None = None,
                          step_budget=None,
                          shard_key: tuple | None = None):
    """Dispatch one batched query against one segment WITHOUT syncing.

    Uses the packed wire format: 3 upload buffers, 1 download buffer —
    essential when the device sits behind a multi-ms tunnel. Returns
    (device_buffer, layout, n_real); pass to collect_segment_result.
    The batch is padded to a power of two (repeating the last bound) so
    the compiled-program cache is keyed on log-many batch sizes.

    With ES_TPU_RESIDENT_LOOP set, fused-admitted plans route through a
    pinned AOT-compiled stepped entry (search/resident.py) with a
    donated, asynchronously staged param feed; `deadline` (absolute
    monotonic seconds) then arms the per-chunk DEVICE-side deadline
    check (collect raises SearchTimeoutError when the device reports
    timed_out), `step_budget` carries an injected straggler budget
    (utils/faults.StepBudget), and `shard_key` = (index, shard) labels
    the timeout. All three are ignored on the cold path, whose deadline
    stays cooperative at the caller's collect boundary."""
    n_real = len(bounds)
    if n_real == 0:
        raise ValueError("execute_segment requires at least one bound query")
    b_pad = next_pow2(n_real, floor=1)
    if b_pad != n_real:
        bounds = list(bounds) + [bounds[-1]] * (b_pad - n_real)
    desc, params = finalize(bounds)
    k_eff = min(k, segment.capacity)
    # fused block-max score+top-k admission: the plan classifier
    # accepts (bool clause bundle over dense text + range masks), the
    # pack carries the tile summaries, and every bool boost is positive
    fused = None
    ck = 0
    fused_width = 0
    bundle, reject = _fused_plan_bundle(desc, k_eff, agg_desc, sort_spec,
                                        allow_k0=True)
    if bundle is not None:
        reject = _fused_pack_ok(segment, bundle)
        if reject is None and not _fused_params_ok(desc, params, bundle):
            reject = "nonpositive_boost"
        if reject is not None:
            bundle = None
    if bundle is not None:
        f0 = bundle_primary_field(bundle)
        n_tiles = segment.text[f0].tile_max.shape[1]
        ck = min(k_eff, segment.capacity // n_tiles)
        fused_width = _fused_row_elems(
            segment.capacity, n_tiles, k_eff,
            emit_match=bool(agg_desc),
            vec_clauses=sum(kd in _FUSED_VEC_KINDS
                            for _r, kd, _f, _w in bundle),
            pos_width=_bundle_pos_width(bundle, segment.text))
        fused = (bundle,)
        _fused_stats.record_admit(positional=_bundle_positional(bundle))
    else:
        _fused_stats.record_reject(reject)
    # tiered tile residency (index/tiering.py): a PAGED pack serves
    # fused-admitted plans through the chunked paged walk — the bound
    # computation over the resident summaries picks the survivor tiles,
    # only those stream host->device. Paged packs never pin resident
    # executables (the walk is host-driven); plans outside the fused
    # matrix fall back to a counted, breaker-accounted full upload.
    paged = _tiering.activate(segment)
    if paged:
        if bundle is not None \
                and not any(kd in _FUSED_VEC_KINDS
                            for _r, kd, _f, _w in bundle):
            return _execute_tiered(
                segment, live, desc, params, agg_desc, agg_params,
                sort_spec, sort_params, bundle, k_eff, b_pad, deadline,
                shard_key, n_real)
        # knn bundles on a paged pack take the full-upload fallback:
        # the knn tile bound is a device product (the similarity
        # column), so the HOST survivor oracle
        # (ops/scoring.bundle_tile_bounds_np) cannot mirror it — the
        # tiered walk would have to fetch every vector tile anyway
        ensure_fwd_cols(segment)
    if _resident.enabled():
        res_backend = None if bundle is None else _resident_backend(
            segment, bundle, desc, agg_desc, k_eff, b_pad, ck)
        if res_backend is not None:
            return _execute_resident(
                segment, live, desc, params, agg_desc, agg_params,
                sort_spec, sort_params, bundle, res_backend, k_eff,
                b_pad, deadline, step_budget, shard_key, n_real)
        # resident mode on, but the plan fell outside residency
        # admission (unfused, or an untuned Pallas candidate whose
        # first cold dispatch tunes it): cold dispatch
        _resident.stats.cold_dispatches.inc()
    # request breaker (ref: the request breaker of
    # HierarchyCircuitBreakerService): the dominant transient is the
    # dense [B, cap] score + match accumulators — or, on the fused
    # path, one [B, tile] scoring slab plus the [B, n_tiles*ck]
    # candidate strip. The device executes programs serially, so
    # transients of PIPELINED dispatches never coexist — the transient
    # estimate is checked here and swapped for an output-buffer-sized
    # hold once the program is enqueued; holding full transients per
    # queued dispatch would spuriously trip on any async batch loop.
    from ..utils.breaker import breaker_service
    req_breaker = breaker_service().breaker("request")
    # chunked bodies bound the transient to one chunk's worth
    row_elems = fused_width if fused is not None else segment.capacity
    est = _chunk_b(b_pad, row_elems) * row_elems * 8
    req_hold = req_breaker.hold(est)
    try:
        dev = device_arrays(segment)
        live_dev = _device_live(segment, live)
        live_views = _live_views_for(segment, live_dev, agg_desc)
        wire, pack_static = _pack_trees(params, agg_params, sort_params)
        wire_dev = jnp.asarray(wire)
        if fused is not None:
            # per-(pack fingerprint, shape-bucket) autotune: the first
            # execution warms then best-of-N-times pallas vs xla on the
            # real inputs and caches (+ persists) the winner — k == 0
            # plans now tune too (the mask-only Pallas grid vs the XLA
            # mask engine). The fingerprint (not seg_id) keys the
            # persisted store so the choice survives restarts and a
            # refreshed pack re-tunes. bool(agg_desc) is part of the
            # shape bucket: the agg (emit-match) and agg-less variants
            # of the same desc must tune independently, or whichever
            # runs first would pin — and persist — the other's backend
            # choice
            tune_key = (seg_cache_key(segment), segment.capacity, desc,
                        k_eff, b_pad, bool(agg_desc))
            pallas_reason = _bundle_pallas_reason(
                fused[0], agg_desc, ck,
                _bundle_pos_width(fused[0], segment.text))
            if pallas_reason is not None:
                _fused_stats.record_pallas_reject(pallas_reason)

            def _run(backend_name, _f=fused[0]):
                # audited (graftlint PR): this block_until_ready is the
                # autotuner's stopwatch — the sync IS the measurement.
                # It runs only on a key's first execution (choice then
                # cached + persisted), serialized by _autotune_lock, so
                # the steady-state query path never passes through it.
                jax.block_until_ready(_segment_program_packed(
                    dev, wire_dev, live_dev, live_views,
                    pack_static=pack_static, desc=desc,
                    agg_desc=agg_desc, cap=segment.capacity, k=k_eff,
                    sort_spec=sort_spec, fused=(_f, backend_name)))

            fused = (fused[0],
                     resolve_fused_backend(
                         tune_key, ck, _run,
                         pallas_candidate=pallas_reason is None,
                         persist_keys=(autotune_persist_key(
                             seg_cache_key(segment), segment.capacity,
                             desc, k_eff, bool(agg_desc)),)))
        # value-based cache key (id(segment) could be reused after GC
        # and serve a stale key_dtype): the only segment-dependent
        # layout input is the sort-key dtype, so resolve it here
        key_dtype = _sort_key_dtype(segment, sort_spec)
        layout = _output_layout(
            (segment.capacity, key_dtype, desc, agg_desc, k_eff,
             sort_spec, pack_static[1],
             # the dev tree STRUCTURE keys the eval path too: lazy
             # uploads (kw_sorted/num_sorted/script_vals/view
             # projections) switch interpreter branches, so a layout
             # cached before an ensure_* mutation must not serve the
             # program after it
             jax.tree_util.tree_structure(dev),
             tuple(sorted(live_views)), fused),
            dev, params, live_dev, live_views, agg_params, sort_params,
            desc, agg_desc, segment.capacity, k_eff, sort_spec,
            fused=fused)
        with _trace_guard.trap(), _prof_annotate("query_phase:dispatch"):
            buf = _segment_program_packed(
                dev, wire_dev, live_dev, live_views,
                pack_static=pack_static,
                desc=desc, agg_desc=agg_desc, cap=segment.capacity,
                k=k_eff, sort_spec=sort_spec, fused=fused)
    except BaseException:
        req_hold.release()
        raise
    # program enqueued: downgrade the transient estimate to the queued
    # OUTPUT buffer's footprint (held until collection or GC)
    out_bytes = min(est, int(getattr(buf, "nbytes", 0)) or est)
    req_hold.shrink(out_bytes)
    # layout dicts are cached/shared across calls — attach the per-call
    # hold to a shallow copy
    layout = {**layout, "_breaker_hold": _gc_backstop(buf, req_hold)}
    return buf, layout, n_real


def collect_segment_result(out, layout, n_real: int):
    """Sync + unpack + slice an async result back to the true B."""
    hold = layout.get("_breaker_hold")
    if layout.get("tiered"):
        # tiered chunked walk (see _execute_tiered): `out` is the final
        # state pytree, not a packed wire buffer — fetch it, slice the
        # padding, and fold the never-fetched (I/O-filtered) tiles into
        # the prune counters as the hard skips they are
        try:
            with _trace_guard.trap(), _prof_annotate("query_phase:collect"):
                host = jax.device_get(out)
        finally:
            if hold is not None:
                hold.release()
        k = layout["k"]
        if k > 0:
            top_s, top_i, totals, prune, agg_tree = host
            top_score = np.asarray(top_s)[:n_real]
            top_idx = np.asarray(top_i)[:n_real].astype(np.int32)
        else:
            totals, prune, agg_tree = host
            top_score = np.zeros((n_real, 0), np.float32)
            top_idx = np.zeros((n_real, 0), np.int32)
        total = np.asarray(totals)[:n_real].astype(np.int32)
        top_missing = np.zeros_like(top_idx, dtype=bool)
        hard, thr, examined = (float(x) for x in np.asarray(prune))
        sk = float(layout.get("skipped_tiles", 0))
        _fused_stats.record_prune(
            hard + sk, thr, examined + sk,
            positional=bool(layout.get("fused_positional")))
        # agg leaves round-trip through f32 on the packed-wire path;
        # mirror that here so reduce-side inputs are byte-identical
        agg_leaves = [np.asarray(leaf)[:n_real].astype(np.float32)
                      for leaf in jax.tree_util.tree_leaves(agg_tree)]
        agg_out = jax.tree_util.tree_unflatten(layout["agg_treedef"],
                                               agg_leaves)
        return (top_score, top_score, top_idx, total, top_missing), \
            agg_out
    try:
        with _trace_guard.trap(), _prof_annotate("query_phase:collect"):
            wire = jax.device_get(out)[:n_real]
    finally:
        # the transient device accumulators are dead once the wire
        # buffer is on host — release NOW instead of waiting for GC.
        # Released on the error exit too (a failed device_get must not
        # pin breaker bytes until collection of the GC backstop).
        if hold is not None:
            hold.release()
    k = layout["k"]
    key_is_float = layout["key_dtype"] == np.float32
    n_i = 2 * k + 1 + (0 if key_is_float else k)
    ibuf = wire[:, :n_i]
    n_i_total = n_i
    if layout.get("resident"):
        # resident stepped programs append the device-side timed_out
        # verdict as one trailing i32 column: a laggard step that the
        # per-chunk deadline check preempted surfaces HERE as the same
        # SearchTimeoutError the cooperative path raises — after the
        # breaker hold above is already released
        n_i_total += 1
        if bool(wire[:, n_i].any()):
            _resident.stats.preempted_by_deadline.inc()
            sk = layout.get("shard_key") or (None, None)
            raise SearchTimeoutError(sk[0], sk[1])
    fbuf = np.ascontiguousarray(wire[:, n_i_total:]).view(np.float32)
    top_score = fbuf[:, 0:k]
    top_idx = ibuf[:, 0:k]
    total = ibuf[:, k]
    top_missing = ibuf[:, k + 1: 2 * k + 1].astype(bool)
    if key_is_float:
        top_key = fbuf[:, k: 2 * k]
        f_off = 2 * k
    else:
        top_key = ibuf[:, 2 * k + 1: 3 * k + 1]
        f_off = k
    prune = fbuf[:, f_off: f_off + 3]
    f_off += 3
    if layout.get("fused"):
        hard, thr, examined = prune.sum(axis=0)
        _fused_stats.record_prune(
            hard, thr, examined,
            positional=bool(layout.get("fused_positional")))
    agg_leaves = []
    for shape in layout["agg_shapes"]:
        size = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        leaf = fbuf[:, f_off: f_off + size]
        agg_leaves.append(leaf.reshape(n_real, *shape[1:]))
        f_off += size
    agg_out = jax.tree_util.tree_unflatten(layout["agg_treedef"], agg_leaves)
    return (top_score, top_key, top_idx, total, top_missing), agg_out


# ---------------------------------------------------------------------------
# Tiered tile residency (index/tiering.py): the chunked paged walk
#
# A pack over the HBM budget keeps its forward-index columns in host
# RAM, partitioned into the SAME SCORE_TILE doc tiles the block-max
# walk prunes on. A fused-admitted dispatch then runs:
#
#   1. the bound computation over the PERMANENTLY-RESIDENT summaries,
#      on host (ops/scoring.bundle_tile_bounds_np) — tiles no query in
#      the batch can match are never fetched: pruning as an I/O filter;
#   2. a chunked walk over the survivor tiles in ASCENDING tile order:
#      each chunk's cold tiles stream host->device through the LRU
#      tile pager while the PREVIOUS chunk's program executes (async
#      dispatch = upload/compute overlap), and one jitted chunk program
#      evaluates the ordinary fused engine (XLA or Pallas — the same
#      eval_fused_topk/eval_fused_match entries) over the compacted
#      chunk columns, carrying the running top-k state across chunks
#      exactly like the base->delta pack chaining;
#   3. when the plan has aggregations, the exact per-chunk match masks
#      scatter into a full [B, cap] mask and ONE aggregation program
#      runs over the resident doc-value columns.
#
# Byte-identity argument: survivor tiles ascend, so the compacted walk
# visits the same matchable tiles in the same order as the full walk
# (skipped tiles are exactly the can_match-false tiles, which the full
# walk hard-skips without touching results); doc ids translate through
# a monotone slot->tile map, so lax.top_k tie order is preserved; and
# the running threshold state at every survivor tile equals the full
# walk's state at that tile. Totals and match masks are exact because
# only provably-matchless tiles are skipped. Chunk shapes are static
# (pow2-bucketed chunk_tiles), so page events never recompile, and no
# fingerprint/cache_key input changes with residency state.
# ---------------------------------------------------------------------------


def _bundle_inputs_np(desc: tuple, params: tuple, bundle: tuple):
    """HOST mirror of _bundle_inputs over the not-yet-uploaded numpy
    params — feeds the tiered pager's survivor computation
    (bundle_tile_bounds_np). Walks desc/params in the exact group order
    the classifier emitted the bundle in; keep in lockstep with
    _bundle_inputs above."""
    B = _batch_size(params)
    ones_i = np.ones((B,), np.int32)
    ones_f = np.ones((B,), np.float32)

    def leaf_inputs(d, p):
        if d[0] == "terms_dense":
            qt, wq = p
            return np.asarray(qt), np.asarray(wq)
        tid, weight = p                  # term_text: single-term Q=1
        return np.asarray(tid)[:, None], np.asarray(weight)[:, None]

    if desc[0] != "bool":
        if isinstance(desc[0], str) and positional_prefix(desc[0]):
            return (tuple(np.asarray(x) for x in params)
                    + (ones_i, ones_f),), ones_i, None
        qt, wq = leaf_inputs(desc, params)
        return ((qt, wq, ones_i, ones_f),), ones_i, None
    _, d_must, d_should, d_not, d_filter = desc
    p_must, p_should, p_not, p_filter, msm, boost = params
    groups = {"must": (d_must, p_must), "should": (d_should, p_should),
              "must_not": (d_not, p_not), "filter": (d_filter, p_filter)}
    nxt = {r: 0 for r in groups}
    out = []
    for role, kind, _field, wrapped in bundle:
        dg, pg = groups[role]
        d, p = dg[nxt[role]], pg[nxt[role]]
        nxt[role] += 1
        if kind in _FUSED_RANGE_KINDS:
            lo, hi, _boost_r = p
            out.append((np.asarray(lo), np.asarray(hi)))
        elif wrapped:
            _, _cm, c_should, _cn, _cf = d
            _pm, pc_should, _pn, _pf, msm_c, boost_c = p
            if positional_prefix(kind):
                out.append(tuple(np.asarray(x) for x in pc_should[0])
                           + (np.asarray(msm_c), np.asarray(boost_c)))
            else:
                qt, wq = leaf_inputs(c_should[0], pc_should[0])
                out.append((qt, wq, np.asarray(msm_c),
                            np.asarray(boost_c)))
        elif isinstance(kind, str) and positional_prefix(kind):
            out.append(tuple(np.asarray(x) for x in p)
                       + (ones_i, ones_f))
        else:
            qt, wq = leaf_inputs(d, p)
            out.append((qt, wq, ones_i, ones_f))
    return tuple(out), np.asarray(msm), np.asarray(boost)


def ensure_fwd_cols(segment: Segment) -> None:
    """Full-residency fallback for a PAGED pack serving a plan outside
    the fused tiered path (field sort, unfused clause kinds, rescore):
    upload the forward-index columns after all — breaker-accounted with
    the segment-GC backstop — drop the pack's paged tiles, and record
    the segment un-paged so later dispatches take the ordinary path.
    May trip the fielddata breaker when the pack genuinely cannot fit;
    that surfaces as the same CircuitBreakingError an oversized
    ordinary upload raises. Concurrent callers race benignly: the
    membership check keeps the dev tree single-valued, and a doubled
    hold releases at segment GC via the backstop."""
    paged = _tiering.paged_fields(segment)
    if not paged:
        return
    dev = device_arrays(segment)
    from ..utils.breaker import breaker_service
    fielddata = breaker_service().breaker("fielddata")
    for f in sorted(paged):
        tf = dev["text"].get(f)
        if tf is None or "fwd_tids" in tf:
            continue
        pf = segment.text[f]
        pos = getattr(pf, "fwd_pos", None)
        hold = fielddata.hold(pf.fwd_tids.nbytes + pf.fwd_imps.nbytes
                              + (pos.nbytes if pos is not None else 0))
        try:
            tf["fwd_tids"] = jnp.asarray(pf.fwd_tids)
            tf["fwd_imps"] = jnp.asarray(pf.fwd_imps)
            if pos is not None:
                tf["fwd_pos"] = jnp.asarray(pos)
        except BaseException:
            hold.release()
            raise
        _gc_backstop(segment, hold)
    _tiering.clear_paged(segment)
    _tiering.stats.unfused_full_uploads.inc()


def _tiered_backend(segment: Segment, bundle: tuple, desc, agg_desc,
                    k_eff: int, b_pad: int, ck: int) -> str:
    """Engine for the tiered chunk walk, resolved WITHOUT timing (the
    host-driven chunk loop cannot wall-clock a tune): the resident
    resolution ladder verbatim — forced env > cached/persisted tuned
    choice, same Pallas-candidacy gates (a compacted chunk is just a
    smaller pack on the same SCORE_TILE grid, so kernel availability
    is identical) — except that an UNDECIDED shape runs XLA instead of
    staying cold: both engines are byte-identical, so an untuned pack
    walking chunks on the slower engine is a perf note, not a
    correctness event."""
    return _resident_backend(segment, bundle, desc, agg_desc, k_eff,
                             b_pad, ck) or "xla"


def _tiered_chunk_cols(seg_res: dict, live: jax.Array, tiles_dev,
                       tile_bufs: dict, bundle: tuple, tile: int,
                       chunk_tiles: int):
    """Compacted chunk columns (traced): paged forward tiles
    concatenate into [chunk_cap, L] arrays, everything else — tile_max
    summaries, numeric filter columns + extrema, live mask — gathers
    on-device from the resident arrays. Pad slots (tiles_dev < 0) map
    to out-of-bounds gathers whose fills make them unmatchable: live
    False, tile_max 0, empty numeric extrema intervals."""
    cap = live.shape[0]
    n_full = cap // tile
    sane = jnp.where(tiles_dev < 0, n_full, tiles_dev)
    docs = (sane[:, None] * tile
            + jnp.arange(tile, dtype=jnp.int32)[None, :]).reshape(-1)
    live_c = jnp.take(live, docs, mode="fill", fill_value=False)
    text_fields = bundle_text_fields(bundle)
    num_fields = tuple(dict.fromkeys(
        f for _r, kd, f, _w in bundle if kd in _FUSED_RANGE_KINDS))
    pos_fields = bundle_pos_fields(bundle)
    text_cols = {}
    for f in text_fields:
        parts = tile_bufs[f]
        tids_parts, imps_parts = parts[0], parts[1]
        text_cols[f] = {
            "fwd_tids": jnp.concatenate(tids_parts, axis=0),
            "fwd_imps": jnp.concatenate(imps_parts, axis=0),
            "tile_max": jnp.take(seg_res["text"][f]["tile_max"], sane,
                                 axis=1, mode="fill", fill_value=0.0),
        }
        if f in pos_fields:
            # paged position tiles concatenate like the forward pair;
            # the per-doc length norms are permanently resident and
            # gather through the same slot->tile map (pad fill 1.0 —
            # harmless: pad docs decode to zero phrase freq anyway)
            text_cols[f]["fwd_pos"] = jnp.concatenate(parts[2], axis=0)
            text_cols[f]["k1ln"] = jnp.take(
                seg_res["text"][f]["k1ln"], docs, mode="fill",
                fill_value=1.0)
            text_cols[f]["lnorm"] = jnp.take(
                seg_res["text"][f]["lnorm"], docs, mode="fill",
                fill_value=1.0)
    num_cols = {}
    for f in num_fields:
        e = seg_res["num"][f]
        if e["values"].dtype == jnp.int32:
            lo_fill = int(np.iinfo(np.int32).max)
            hi_fill = int(np.iinfo(np.int32).min)
        else:
            lo_fill, hi_fill = float("inf"), float("-inf")
        num_cols[f] = {
            "values": jnp.take(e["values"], docs, mode="fill",
                               fill_value=0),
            "exists": jnp.take(e["exists"], docs, mode="fill",
                               fill_value=False),
            "tile_lo": jnp.take(e["tile_lo"], sane, mode="fill",
                                fill_value=lo_fill),
            "tile_hi": jnp.take(e["tile_hi"], sane, mode="fill",
                                fill_value=hi_fill),
        }
    return {"text": text_cols, "num": num_cols}, live_c, docs


@partial(jax.jit, static_argnames=("pack_static", "desc", "cap", "k",
                                   "tile", "chunk_tiles", "fused",
                                   "emit_match"))
def _tiered_chunk_program(seg_res: dict, wire, live: jax.Array,
                          tiles_dev, tile_bufs: dict, state, *,
                          pack_static, desc: tuple, cap: int, k: int,
                          tile: int, chunk_tiles: int, fused: tuple,
                          emit_match: bool):
    """One k>0 chunk of the tiered walk. The running top-k state enters
    with GLOBAL doc ids; they are encoded out of the chunk-local id
    range (+chunk_cap — locals are < chunk_cap by construction) so the
    engine's in-walk merge stays positional (existing-first, the tie
    rule), then every id decodes back to global through the monotone
    slot->tile map. Carried state: (top_s, top_i, totals, prune
    [, match_acc])."""
    params, _agg_params, _sort_params = _unpack_trees(wire, pack_static)
    bundle, _backend = fused
    chunk_cap = chunk_tiles * tile
    seg_c, live_c, docs = _tiered_chunk_cols(seg_res, live, tiles_dev,
                                             tile_bufs, bundle, tile,
                                             chunk_tiles)
    run_s, run_i, totals, prune = state[:4]
    out = eval_fused_topk(seg_c, desc, params, live_c, k, bundle,
                          fused[1], emit_match=emit_match,
                          init_topk=(run_s, run_i + chunk_cap))
    if emit_match:
        top_s, top_i, total_c, pruned, match = out
    else:
        top_s, top_i, total_c, pruned = out
    slot = jnp.clip(top_i // tile, 0, chunk_tiles - 1)
    base = jnp.take(tiles_dev, slot) * tile
    glob = jnp.where(top_i >= chunk_cap, top_i - chunk_cap,
                     base + top_i % tile)
    new = (top_s, glob, totals + total_c, prune + pruned)
    if emit_match:
        new = new + (state[4].at[:, docs].set(match, mode="drop"),)
    return new


@partial(jax.jit, static_argnames=("pack_static", "desc", "cap", "tile",
                                   "chunk_tiles", "fused", "emit_match"))
def _tiered_chunk_match_program(seg_res: dict, wire, live: jax.Array,
                                tiles_dev, tile_bufs: dict, state, *,
                                pack_static, desc: tuple, cap: int,
                                tile: int, chunk_tiles: int,
                                fused: tuple, emit_match: bool):
    """The k == 0 (match-mask-only) chunk twin: exact totals and, when
    an aggregation pass follows, the exact match mask scattered into
    the global [B, cap] accumulator. Carried state: (totals, prune
    [, match_acc])."""
    params, _agg_params, _sort_params = _unpack_trees(wire, pack_static)
    bundle, _backend = fused
    seg_c, live_c, docs = _tiered_chunk_cols(seg_res, live, tiles_dev,
                                             tile_bufs, bundle, tile,
                                             chunk_tiles)
    out = eval_fused_match(seg_c, desc, params, live_c, bundle,
                           fused[1], emit_match=emit_match)
    if emit_match:
        total_c, pruned, match = out
        return (state[0] + total_c, state[1] + pruned,
                state[2].at[:, docs].set(match, mode="drop"))
    total_c, pruned = out
    return (state[0] + total_c, state[1] + pruned)


@partial(jax.jit, static_argnames=("pack_static", "desc", "agg_desc",
                                   "cap"))
def _tiered_agg_program(seg: dict, wire, live_views: dict,
                        match: jax.Array, *, pack_static, desc: tuple,
                        agg_desc: tuple, cap: int):
    """ONE aggregation pass over the assembled exact match mask and the
    RESIDENT doc-value columns — the same eval_aggs + sorted-view
    machinery the fully-resident program runs, fed the same mask, so
    agg trees are identical."""
    params, agg_params, _sort_params = _unpack_trees(wire, pack_static)
    B = _batch_size(params)
    plan = _agg_view_plan(desc, agg_desc, agg_params, seg, live_views)
    views = _ViewMasks(desc, params, seg, live_views, cap, B)
    return eval_aggs(agg_desc, agg_params, seg, match, views=views,
                     plan=plan)


def _execute_tiered(segment: Segment, live, desc: tuple, params: tuple,
                    agg_desc: tuple, agg_params: tuple,
                    sort_spec: tuple, sort_params: tuple, bundle: tuple,
                    k_eff: int, b_pad: int, deadline: float | None,
                    shard_key: tuple | None, n_real: int):
    """Serve one fused-admitted dispatch from a PAGED pack via the
    chunked tiered walk (see the section comment above). Returns
    (state_tuple, layout, n_real) for collect_segment_result — the
    layout carries "tiered": True and collect fetches the state pytree
    instead of a packed wire buffer. The deadline is checked
    cooperatively at every chunk boundary (finer than the cold path's
    collect-only check); residency stays with the tile pager, so no
    resident executable is pinned for paged packs."""
    store = _tiering.store_for(segment)
    cap = segment.capacity
    tile = store.tile
    ct = min(_tiering.chunk_tiles(), next_pow2(store.n_tiles))
    emit = bool(agg_desc)
    ck = min(max(k_eff, 0), tile)
    backend = _tiered_backend(segment, bundle, desc, agg_desc, k_eff,
                              b_pad, ck)
    fused = (bundle, backend)
    _tiering.stats.tiered_dispatches.inc()
    text_fields = bundle_text_fields(bundle)
    pos_fields = bundle_pos_fields(bundle)
    num_fields = tuple(dict.fromkeys(
        f for _r, kd, f, _w in bundle if kd in _FUSED_RANGE_KINDS))
    # -- survivor tiles from the resident summaries (host oracle) ------
    cl_np, msm_np, boost_np = _bundle_inputs_np(desc, params, bundle)
    from ..ops.scoring import bundle_tile_bounds_np
    can, _bound = bundle_tile_bounds_np(
        bundle, cl_np, {f: segment.text[f].tile_max for f in text_fields},
        {f: store.extrema(segment, f) for f in num_fields},
        msm_np, boost_np)
    surv = np.nonzero(can.any(axis=0))[0]
    skipped = int(store.n_tiles - surv.size)
    _tiering.note_prune_skipped(skipped)
    k_run = min(k_eff, cap)
    row_elems = (ct * tile + ct * max(min(k_run, tile), 1)
                 + (cap if emit else 0)
                 + _bundle_pos_width(bundle, segment.text) * tile)
    from ..utils.breaker import breaker_service
    req_hold = breaker_service().breaker("request").hold(
        b_pad * row_elems * 8)
    try:
        dev = device_arrays(segment)
        live_dev = _device_live(segment, live)
        live_views = _live_views_for(segment, live_dev, agg_desc)
        wire, pack_static = _pack_trees(params, agg_params, sort_params)
        wire_dev = jax.device_put(wire)
        seg_res = {
            "text": {f: {"tile_max": dev["text"][f]["tile_max"],
                         **({"k1ln": dev["text"][f]["k1ln"],
                             "lnorm": dev["text"][f]["lnorm"]}
                            if f in pos_fields else {})}
                     for f in text_fields},
            "num": {f: {kk: dev["num"][f][kk]
                        for kk in ("values", "exists", "tile_lo",
                                   "tile_hi")}
                    for f in num_fields},
        }
        # initial walk state staged via EXPLICIT device_put: the tiered
        # driver runs outside jit, where an eager jnp.zeros would be an
        # implicit host->device transfer (disallowed under the armed
        # trace guard — page events must stay transfer-clean except for
        # their explicit tile stages)
        if k_run > 0:
            state = (jax.device_put(np.full((b_pad, k_run), -np.inf,
                                            np.float32)),
                     jax.device_put(np.zeros((b_pad, k_run), np.int32)),
                     jax.device_put(np.zeros((b_pad,), np.int32)),
                     jax.device_put(np.zeros((3,), np.float32)))
        else:
            state = (jax.device_put(np.zeros((b_pad,), np.int32)),
                     jax.device_put(np.zeros((3,), np.float32)))
        if emit:
            state = state + (jax.device_put(np.zeros((b_pad, cap),
                                                     bool)),)
        chunks = [surv[i: i + ct] for i in range(0, len(surv), ct)]

        def stage(tiles: np.ndarray):
            """Fetch one chunk's tiles through the LRU pager (misses
            device_put asynchronously — issued while the previous
            chunk's program is still executing, which IS the
            upload/compute overlap)."""
            padded = np.full(ct, -1, np.int64)
            padded[: len(tiles)] = tiles
            t0 = _time.perf_counter()
            bufs = _tiering.pager.fetch(store, text_fields, padded)
            ms = (_time.perf_counter() - t0) * 1000.0
            return jax.device_put(padded.astype(np.int32)), bufs, ms

        pending = stage(chunks[0]) if chunks else None
        for i, _tiles in enumerate(chunks):
            if deadline is not None and _time.monotonic() > deadline:
                sk = shard_key or (None, None)
                raise SearchTimeoutError(sk[0], sk[1])
            tiles_dev, bufs, _ms = pending
            with _trace_guard.trap(), \
                    _prof_annotate("query_phase:tiered_dispatch"):
                if k_run > 0:
                    state = _tiered_chunk_program(
                        seg_res, wire_dev, live_dev, tiles_dev, bufs,
                        state, pack_static=pack_static, desc=desc,
                        cap=cap, k=k_run, tile=tile, chunk_tiles=ct,
                        fused=fused, emit_match=emit)
                else:
                    state = _tiered_chunk_match_program(
                        seg_res, wire_dev, live_dev, tiles_dev, bufs,
                        state, pack_static=pack_static, desc=desc,
                        cap=cap, tile=tile, chunk_tiles=ct, fused=fused,
                        emit_match=emit)
            if i + 1 < len(chunks):
                # prefetch the NEXT chunk while this one executes
                pending = stage(chunks[i + 1])
                _tiering.record_overlap_ms(pending[2])
        agg_tree = {}
        if emit:
            with _trace_guard.trap(), \
                    _prof_annotate("query_phase:tiered_aggs"):
                agg_tree = _tiered_agg_program(
                    dev, wire_dev, live_views, state[-1],
                    pack_static=pack_static, desc=desc,
                    agg_desc=agg_desc, cap=cap)
        out = (state[:4] if k_run > 0 else state[:2]) + (agg_tree,)
    except BaseException:
        req_hold.release()
        raise
    out_leaves = jax.tree_util.tree_leaves(out)
    out_bytes = sum(int(getattr(leaf, "nbytes", 0)) for leaf in out_leaves)
    req_hold.shrink(max(out_bytes, 1))
    agg_leaves, agg_treedef = jax.tree_util.tree_flatten(agg_tree)
    layout = {
        "k": k_run,
        "key_dtype": np.dtype(np.float32),
        "agg_treedef": agg_treedef,
        "agg_shapes": [tuple(s.shape) for s in agg_leaves],
        "fused": True,
        "fused_positional": _bundle_positional(bundle),
        "tiered": True,
        "skipped_tiles": skipped,
        "_breaker_hold": _gc_backstop(out_leaves[0] if out_leaves
                                      else None, req_hold),
    }
    return out, layout, n_real


def _pack_tune_key(base: Segment, delta: Segment, desc: tuple, k_eff: int,
                   b_pad: int, agg: bool) -> tuple:
    return ("pack", seg_cache_key(base), seg_cache_key(delta),
            base.capacity, delta.capacity, desc, k_eff, b_pad, agg)


def _pack_resident_backend(base: Segment, delta: Segment, bundle: tuple,
                           desc, agg_desc, k_eff: int, b_pad: int,
                           ck: int) -> str | None:
    """_resident_backend's base+delta twin: resolve the pack's engine
    without timing (forced env / cached / persisted), None = untuned
    (the cold dispatch tunes it and unblocks residency next time)."""
    forced = _os.environ.get("ES_TPU_FUSED_BACKEND", "").lower()
    if forced in ("pallas", "xla"):
        return forced
    if not _bundle_pallas_ok(bundle, agg_desc, ck,
                             max(_bundle_pos_width(bundle, base.text),
                                 _bundle_pos_width(bundle, delta.text))):
        return "xla"
    choice = _autotune_choices.get(
        _pack_tune_key(base, delta, desc, k_eff, b_pad, bool(agg_desc)))
    if choice is None:
        entry = _autotune_persisted.get(autotune_persist_key(
            f"{seg_cache_key(base)}+{seg_cache_key(delta)}",
            base.capacity + delta.capacity, desc, k_eff, bool(agg_desc)))
        choice = entry["choice"] if entry is not None else None
    if choice is None:
        return None
    if choice == "pallas" and not resident_step_ok():
        return None
    return choice


def execute_pack_async(base: Segment, delta: Segment, live_b: np.ndarray,
                       live_d: np.ndarray, bounds_b: Sequence[Bound],
                       bounds_d: Sequence[Bound], k: int,
                       agg_desc: tuple = (), agg_params_b: tuple = (),
                       agg_params_d: tuple = (),
                       sort_spec: tuple = ("_score",),
                       deadline: float | None = None,
                       step_budget=None, shard_key: tuple | None = None):
    """Dispatch one batched query against a (base, delta) generation
    pair as ONE device program (see _pack_body), without syncing.

    Returns (buf, layout, n_real) for collect_pack_result — or None
    when the plan/pack pair is not pack-admissible (caller falls back
    to the ordinary per-segment dispatches; responses are identical
    either way, this is purely the one-round-trip fast path). Autotune
    and resident keys embed BOTH generations' cache keys, so a
    refresh's delta epoch bump re-keys NOTHING; only compaction (a new
    base fingerprint) does."""
    n_real = len(bounds_b)
    if n_real == 0 or len(bounds_d) != n_real:
        return None
    if tuple(sort_spec) != ("_score",):
        return None
    b_pad = next_pow2(n_real, floor=1)
    if b_pad != n_real:
        bounds_b = list(bounds_b) + [bounds_b[-1]] * (b_pad - n_real)
        bounds_d = list(bounds_d) + [bounds_d[-1]] * (b_pad - n_real)
    desc, params_b = finalize(bounds_b)
    desc_d, params_d = finalize(bounds_d)
    if desc != desc_d:
        return None  # segment-local binds diverged structurally
    cap_b, cap_d = base.capacity, delta.capacity
    k_eff = min(k, cap_b + cap_d)
    # tiered residency: a paged generation (usually the base — deltas
    # are compaction-bounded) dispatches per-segment, where the tiered
    # chunked walk serves it; the one-round-trip pack program assumes a
    # fully-resident pair. Responses are identical either way.
    if _tiering.activate(base) or _tiering.activate(delta):
        return None
    bundle, _reject = _fused_plan_bundle(desc, k_eff, agg_desc, sort_spec,
                                         allow_k0=True)
    if bundle is None:
        return None
    if _fused_pack_ok(base, bundle) is not None \
            or _fused_pack_ok(delta, bundle) is not None:
        return None
    if not _fused_params_ok(desc, params_b, bundle) \
            or not _fused_params_ok(desc, params_d, bundle):
        return None
    f0 = bundle_primary_field(bundle)
    n_tiles_b = base.text[f0].tile_max.shape[1]
    n_tiles_d = delta.text[f0].tile_max.shape[1]
    ck = max(min(k_eff, cap_b // n_tiles_b),
             min(k_eff, cap_d // n_tiles_d))
    n_vec = sum(kd in _FUSED_VEC_KINDS for _r, kd, _f, _w in bundle)
    row_elems = (_fused_row_elems(cap_b, n_tiles_b, k_eff,
                                  emit_match=bool(agg_desc),
                                  vec_clauses=n_vec,
                                  pos_width=_bundle_pos_width(
                                      bundle, base.text))
                 + _fused_row_elems(cap_d, n_tiles_d, k_eff,
                                    emit_match=bool(agg_desc),
                                    vec_clauses=n_vec,
                                    pos_width=_bundle_pos_width(
                                        bundle, delta.text)))
    if _chunk_b(b_pad, row_elems) < b_pad:
        # a batch this wide needs the per-segment path's B-chunked
        # body (the pack body runs one un-chunked walk so its carried
        # top-k state spans the whole batch); fall back rather than
        # hold a chunk-budget-busting transient
        return None
    _fused_stats.record_admit(positional=_bundle_positional(bundle))
    if _resident.enabled():
        res_backend = _pack_resident_backend(base, delta, bundle, desc,
                                             agg_desc, k_eff, b_pad, ck)
        if res_backend is not None:
            return _execute_pack_resident(
                base, delta, live_b, live_d, desc, params_b, params_d,
                agg_desc, agg_params_b, agg_params_d, bundle,
                res_backend, k_eff, b_pad, deadline, step_budget,
                shard_key, n_real)
        _resident.stats.cold_dispatches.inc()
    from ..utils.breaker import breaker_service
    req_hold = breaker_service().breaker("request").hold(
        b_pad * row_elems * 8)
    try:
        dev_b, dev_d = device_arrays(base), device_arrays(delta)
        live_dev_b = _device_live(base, live_b)
        live_dev_d = _device_live(delta, live_d)
        views_b = _live_views_for(base, live_dev_b, agg_desc)
        views_d = _live_views_for(delta, live_dev_d, agg_desc)
        wire, pack_static = _pack_trees(params_b, params_d,
                                        agg_params_b, agg_params_d)
        wire_dev = jnp.asarray(wire)
        tune_key = _pack_tune_key(base, delta, desc, k_eff, b_pad,
                                  bool(agg_desc))
        pallas_reason = _bundle_pallas_reason(
            bundle, agg_desc, ck,
            max(_bundle_pos_width(bundle, base.text),
                _bundle_pos_width(bundle, delta.text)))
        if pallas_reason is not None:
            _fused_stats.record_pallas_reject(pallas_reason)

        def _run(backend_name):
            # the autotuner's stopwatch (first execution per key only,
            # serialized by _autotune_lock — same discipline as the
            # single-segment tuner)
            jax.block_until_ready(_pack_program_packed(
                dev_b, dev_d, wire_dev, live_dev_b, live_dev_d,
                views_b, views_d, pack_static=pack_static, desc=desc,
                agg_desc=agg_desc, cap_b=cap_b, cap_d=cap_d, k=k_eff,
                fused=(bundle, backend_name)))

        fused = (bundle,
                 resolve_fused_backend(
                     tune_key, ck, _run,
                     pallas_candidate=pallas_reason is None,
                     persist_keys=(autotune_persist_key(
                         f"{seg_cache_key(base)}+{seg_cache_key(delta)}",
                         cap_b + cap_d, desc, k_eff, bool(agg_desc)),)))
        layout = _pack_output_layout(
            (cap_b, cap_d, desc, agg_desc, k_eff, pack_static[1],
             jax.tree_util.tree_structure(dev_b),
             jax.tree_util.tree_structure(dev_d),
             tuple(sorted(views_b)), tuple(sorted(views_d)), fused),
            dev_b, dev_d, params_b, params_d, live_dev_b, live_dev_d,
            views_b, views_d, agg_params_b, agg_params_d, desc, agg_desc,
            cap_b, cap_d, k_eff, fused)
        with _trace_guard.trap(), _prof_annotate("query_phase:dispatch"):
            buf = _pack_program_packed(
                dev_b, dev_d, wire_dev, live_dev_b, live_dev_d,
                views_b, views_d, pack_static=pack_static, desc=desc,
                agg_desc=agg_desc, cap_b=cap_b, cap_d=cap_d, k=k_eff,
                fused=fused)
    except BaseException:
        req_hold.release()
        raise
    est = b_pad * row_elems * 8
    out_bytes = min(est, int(getattr(buf, "nbytes", 0)) or est)
    req_hold.shrink(out_bytes)
    layout = {**layout, "_breaker_hold": _gc_backstop(buf, req_hold)}
    return buf, layout, n_real


def _pack_output_layout(cache_key, dev_b, dev_d, params_b, params_d,
                        live_b, live_d, views_b, views_d, agg_params_b,
                        agg_params_d, desc, agg_desc, cap_b, cap_d, k,
                        fused):
    hit = _out_layout_cache.get(cache_key)
    if hit is not None:
        return hit
    shapes = jax.eval_shape(
        partial(_pack_body, desc=desc, agg_desc=agg_desc, cap_b=cap_b,
                cap_d=cap_d, k=k, fused=fused),
        dev_b, dev_d, params_b, params_d, live_b, live_d, views_b,
        views_d, agg_params_b, agg_params_d)
    (ts, _tk, _ti, _tt, _tm), agg_shapes, _prune = shapes
    agg_leaves, agg_treedef = jax.tree_util.tree_flatten(agg_shapes)
    layout = {
        "k": int(ts.shape[1]),
        "key_dtype": np.dtype(np.float32),
        "agg_treedef": agg_treedef,
        "agg_shapes": [tuple(s.shape) for s in agg_leaves],
        "fused": True,
        "fused_positional": _bundle_positional(fused[0]),
        "pack": True,
        "cap_b": cap_b,
    }
    with _out_layout_lock:
        layout = _out_layout_cache.setdefault(cache_key, layout)
    return layout


def _execute_pack_resident(base: Segment, delta: Segment, live_b, live_d,
                           desc: tuple, params_b: tuple, params_d: tuple,
                           agg_desc: tuple, agg_params_b: tuple,
                           agg_params_d: tuple, bundle: tuple,
                           backend: str, k_eff: int, b_pad: int,
                           deadline: float | None, step_budget,
                           shard_key: tuple | None, n_real: int):
    """Serve a base+delta dispatch through a pinned resident entry.
    The entry key embeds BOTH generations' cache keys and the exact
    pack shape signatures — a refresh's delta rebuild (same pow2
    buckets) lands on the SAME pinned executable and just feeds it the
    new epoch's arrays; the delta extent only re-keys when its pow2
    bucket grows. This is the zero-recompile refresh the counters
    (refresh_reuses) prove."""
    cap_b, cap_d = base.capacity, delta.capacity
    k_res = (min(next_pow2(max(k_eff, 1), floor=1), cap_b + cap_d)
             if k_eff > 0 else 0)
    fused = (bundle, backend)
    f0 = bundle_primary_field(bundle)
    n_tiles_b = base.text[f0].tile_max.shape[1]
    n_tiles_d = delta.text[f0].tile_max.shape[1]
    chunk_tiles = max(1, -(-n_tiles_b // _RESIDENT_CHUNKS))
    n_chunks = -(-n_tiles_b // chunk_tiles)
    row_elems = (_fused_row_elems(cap_b, n_tiles_b, k_res,
                                  emit_match=bool(agg_desc),
                                  pos_width=_bundle_pos_width(
                                      bundle, base.text))
                 + _fused_row_elems(cap_d, n_tiles_d, k_res,
                                    emit_match=bool(agg_desc),
                                    pos_width=_bundle_pos_width(
                                        bundle, delta.text)))
    from ..utils.breaker import breaker_service
    est = b_pad * row_elems * 8
    req_hold = breaker_service().breaker("request").hold(est)
    try:
        dev_b, dev_d = device_arrays(base), device_arrays(delta)
        live_dev_b = _device_live(base, live_b)
        live_dev_d = _device_live(delta, live_d)
        views_b = _live_views_for(base, live_dev_b, agg_desc)
        views_d = _live_views_for(delta, live_dev_d, agg_desc)
        wire, pack_static = _pack_trees(params_b, params_d,
                                        agg_params_b, agg_params_d)
        t_stage = _time.perf_counter()
        wire_dev = jax.device_put(wire)
        hi, lo = _split_deadline(deadline)
        delay_ms = float(step_budget.take()) if step_budget is not None \
            else 0.0
        step_arr = jax.device_put(np.asarray(
            [hi, lo, delay_ms / n_chunks, delay_ms], np.float32))
        key = ("pack", seg_cache_key(base), seg_cache_key(delta),
               cap_b, cap_d, desc, agg_desc, k_res, b_pad,
               pack_static[1], jax.tree_util.tree_structure(dev_b),
               jax.tree_util.tree_structure(dev_d),
               tuple(sorted(views_b)), tuple(sorted(views_d)), bundle,
               backend, _dev_shape_sig(dev_b), _dev_shape_sig(dev_d))
        entry = _resident.cache.get(
            key, delta_epoch=getattr(delta, "delta_epoch", 0))
        if entry is None:
            _resident.stats.cold_dispatches.inc()
            import warnings
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not")
                compiled = _resident_pack_program.lower(
                    dev_b, dev_d, wire_dev, live_dev_b, live_dev_d,
                    views_b, views_d, step_arr,
                    pack_static=pack_static, desc=desc,
                    agg_desc=agg_desc, cap_b=cap_b, cap_d=cap_d,
                    k=k_res, fused=fused,
                    chunk_tiles=chunk_tiles).compile()
            entry = _resident.ResidentEntry(
                key, label=repr((desc, k_res, b_pad, bool(agg_desc),
                                 backend, "pack")),
                compiled=compiled, seg_id=base.seg_id,
                fingerprint=base.fingerprint(),
                seg_ref=None,  # epoch segments die; the entry must not
                backend=backend,
                generation=seg_cache_key(delta),
                delta_epoch=getattr(delta, "delta_epoch", 0))
            _resident.cache.put(entry)
        layout = _pack_output_layout(
            (cap_b, cap_d, desc, agg_desc, k_res, pack_static[1],
             jax.tree_util.tree_structure(dev_b),
             jax.tree_util.tree_structure(dev_d),
             tuple(sorted(views_b)), tuple(sorted(views_d)), fused),
            dev_b, dev_d, params_b, params_d, live_dev_b, live_dev_d,
            views_b, views_d, agg_params_b, agg_params_d, desc, agg_desc,
            cap_b, cap_d, k_res, fused)
        with _trace_guard.trap(), \
                _prof_annotate("query_phase:resident_dispatch"):
            buf = entry.compiled(dev_b, dev_d, wire_dev, live_dev_b,
                                 live_dev_d, views_b, views_d, step_arr)
        _resident.stats.staged_feed_overlap_ms.record(
            (_time.perf_counter() - t_stage) * 1000.0)
        try:
            buf.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
    except BaseException:
        req_hold.release()
        raise
    out_bytes = min(est, int(getattr(buf, "nbytes", 0)) or est)
    req_hold.shrink(out_bytes)
    layout = {**layout, "resident": True, "shard_key": shard_key,
              "_breaker_hold": _gc_backstop(buf, req_hold)}
    code_bytes = 0
    try:
        ma = entry.compiled.memory_analysis()
        code_bytes = int(getattr(ma, "generated_code_size_in_bytes", 0)
                         or 0)
    except Exception:  # noqa: BLE001 — backend-optional introspection
        pass
    try:
        entry.account(code_bytes + int(wire.nbytes) + out_bytes)
    except Exception:  # noqa: BLE001 — breaker trip on accounting
        _resident.cache.evict(entry.key)
    return buf, layout, n_real


def collect_pack_result(out, layout, n_real: int):
    """Collect a pack dispatch and split the merged selection back into
    PER-SEGMENT candidate lists (scores stay globally sorted; indices
    below cap_b are base rows, the rest delta rows offset by cap_b), so
    the ordinary cross-segment response merge consumes them unchanged —
    responses are byte-identical to two per-segment dispatches. Returns
    ([base_top, delta_top], [base_aggs, delta_aggs]); the top tuples
    carry a 6th element, the per-row VALID count (a split list can hold
    fewer than min(total, k) entries when the other side won the
    window)."""
    hold = layout.get("_breaker_hold")
    try:
        with _trace_guard.trap(), _prof_annotate("query_phase:collect"):
            wire = jax.device_get(out)[:n_real]
    finally:
        if hold is not None:
            hold.release()
    k = layout["k"]
    n_i = 2 * k + 2
    n_i_total = n_i
    if layout.get("resident"):
        n_i_total += 1
        if bool(wire[:, n_i].any()):
            _resident.stats.preempted_by_deadline.inc()
            sk = layout.get("shard_key") or (None, None)
            raise SearchTimeoutError(sk[0], sk[1])
    ibuf = wire[:, :n_i_total]
    fbuf = np.ascontiguousarray(wire[:, n_i_total:]).view(np.float32)
    top_idx = ibuf[:, :k]
    totals = ibuf[:, k: k + 2]
    top_score = fbuf[:, :k]
    prune = fbuf[:, k: k + 3]
    hard, thr, examined = prune.sum(axis=0)
    _fused_stats.record_prune(
        hard, thr, examined,
        positional=bool(layout.get("fused_positional")))
    f_off = k + 3
    agg_leaves = []
    for shape in layout["agg_shapes"]:
        size = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        leaf = fbuf[:, f_off: f_off + size]
        agg_leaves.append(leaf.reshape(n_real, *shape[1:]))
        f_off += size
    agg_b, agg_d = jax.tree_util.tree_unflatten(layout["agg_treedef"],
                                                agg_leaves)
    cap_b = layout["cap_b"]
    B = n_real
    sb = np.full((B, k), -np.inf, np.float32)
    sd = np.full((B, k), -np.inf, np.float32)
    ib = np.zeros((B, k), np.int32)
    idd = np.zeros((B, k), np.int32)
    vb = np.zeros(B, np.int32)
    vd = np.zeros(B, np.int32)
    for r in range(B):
        valid = top_score[r] > -np.inf
        idxs = top_idx[r][valid]
        scs = top_score[r][valid]
        mb = idxs < cap_b
        nb = int(mb.sum())
        nd = int(valid.sum()) - nb
        sb[r, :nb] = scs[mb]
        ib[r, :nb] = idxs[mb]
        vb[r] = nb
        sd[r, :nd] = scs[~mb]
        idd[r, :nd] = idxs[~mb] - cap_b
        vd[r] = nd
    miss = np.zeros((B, k), bool)
    top_b = (sb, sb, ib, totals[:, 0], miss, vb)
    top_d = (sd, sd, idd, totals[:, 1], miss, vd)
    return [top_b, top_d], [agg_b, agg_d]


def execute_segment(segment: Segment, live: np.ndarray,
                    bounds: Sequence[Bound], k: int,
                    agg_desc: tuple = (), agg_params: tuple = (),
                    sort_spec: tuple = ("_score",), sort_params: tuple = ()):
    """Synchronous wrapper: dispatch + collect. Returns host numpy:
    (top_score [B,k], top_key, top_idx, total [B], top_missing), aggs."""
    out, layout, n_real = execute_segment_async(
        segment, live, bounds, k, agg_desc, agg_params, sort_spec, sort_params)
    return collect_segment_result(out, layout, n_real)
