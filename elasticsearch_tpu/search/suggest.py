"""Suggesters: term (edit-distance) and phrase (best token combination).

Reference analog: search/suggest/ — SuggestPhase.java executing
TermSuggester (Lucene DirectSpellChecker over the term dictionary,
scored by string similarity then doc frequency) and PhraseSuggester
(candidate generation + real-word error model). Completion suggester
(FST-based) is a separate structure; here the prefix variant runs over
the sorted term dictionary.

All suggester work is host-side dictionary traversal — it never needs
the device. Shard-level suggestions merge at the coordinator by
(text, score) like the reference's Suggest.reduce.
"""

from __future__ import annotations

from ..index.segment import Segment
from ..utils.errors import SearchParseError


def parse_suggest(body: dict | None) -> list[dict]:
    if not body:
        return []
    out = []
    global_text = body.get("text")
    for name, spec in body.items():
        if name == "text":
            continue
        if not isinstance(spec, dict):
            raise SearchParseError(f"suggestion [{name}] must be an object")
        kind = next((k for k in ("term", "phrase", "completion")
                     if k in spec), None)
        if kind is None:
            raise SearchParseError(
                f"suggestion [{name}] requires term/phrase/completion")
        conf = spec[kind]
        out.append({
            "name": name, "kind": kind,
            "text": spec.get("text", global_text),
            "field": conf.get("field"),
            "size": int(conf.get("size", 5)),
            "max_edits": int(conf.get("max_edits", 2)),
            "min_word_length": int(conf.get("min_word_length", 4)),
            "prefix_length": int(conf.get("prefix_length", 1)),
            "context": conf.get("context"),
        })
    return out


def _edit_distance(a: str, b: str, cap: int) -> int:
    """Banded Levenshtein with early exit above cap."""
    if abs(len(a) - len(b)) > cap:
        return cap + 1
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        best = i
        for j, cb in enumerate(b, 1):
            v = min(prev[j] + 1, cur[j - 1] + 1,
                    prev[j - 1] + (ca != cb))
            cur.append(v)
            best = min(best, v)
        if best > cap:
            return cap + 1
        prev = cur
    return prev[-1]


def _candidates(token: str, spec: dict, term_dfs: dict[str, int]
                ) -> list[dict]:
    """Rank dictionary terms near `token`: fewer edits first, then higher
    df, then lexicographic — DirectSpellChecker's ordering."""
    cap = spec["max_edits"]
    prefix = token[: spec["prefix_length"]]
    scored = []
    for term, df in term_dfs.items():
        if term == token:
            continue
        if prefix and not term.startswith(prefix):
            continue
        if len(term) < spec["min_word_length"] and len(token) >= \
                spec["min_word_length"]:
            continue
        d = _edit_distance(token, term, cap)
        if d <= cap:
            sim = 1.0 - d / max(len(token), len(term))
            scored.append((d, -df, term, sim))
    scored.sort()
    return [{"text": t, "score": round(sim, 6), "freq": -negdf}
            for _, negdf, t, sim in scored[: spec["size"]]]


def term_dfs_for(segments: list[Segment], field: str) -> dict[str, int]:
    dfs: dict[str, int] = {}
    for seg in segments:
        pf = seg.text.get(field)
        if pf is not None:
            for i, t in enumerate(pf.terms):
                dfs[t] = dfs.get(t, 0) + int(pf.df[i])
        kc = seg.keywords.get(field)
        if kc is not None:
            for i, t in enumerate(kc.terms):
                dfs[t] = dfs.get(t, 0) + int(kc.df[i])
    return dfs


def _completion_options(spec: dict, segments: list[Segment],
                        mappers) -> list[dict]:
    """Prefix-match completion entries, context-filtered, ranked by
    weight desc then text (ref: search/suggest/completion/
    CompletionSuggester + XAnalyzingSuggester weight ordering)."""
    field = spec["field"]
    prefix = str(spec["text"]).lower()
    want_ctx: dict = {}
    fm = mappers.field(field) if mappers is not None else None
    ctx_cfg = (fm.context or {}) if fm is not None else {}
    for ctx_name, cfg in ctx_cfg.items():
        req = (spec.get("context") or {}).get(ctx_name)
        if req is None:
            req = cfg.get("default")
        if req is None:
            continue
        if cfg.get("type") == "geo":
            from ..ops.geo import parse_geo_point, geohash_encode
            from ..index.mapping import _geo_precision_chars
            prec = _geo_precision_chars(cfg.get("precision"))
            lat, lon = parse_geo_point(req)
            want_ctx[ctx_name] = geohash_encode(lat, lon, prec)
        else:
            want_ctx[ctx_name] = ([str(v) for v in req]
                                  if isinstance(req, list) else [str(req)])
    options: dict[str, dict] = {}
    for seg in segments:
        cc = seg.completions.get(field)
        if cc is None:
            continue
        for _row, entry in cc.entries:
            ectx = entry.get("context") or {}
            ok = True
            for ctx_name, want in want_ctx.items():
                have = ectx.get(ctx_name)
                if isinstance(want, str):           # geo: geohash equality
                    if have != want:
                        ok = False
                        break
                else:                               # category: intersection
                    have_list = (have if isinstance(have, list)
                                 else [have] if have is not None else [])
                    if not set(want) & set(have_list):
                        ok = False
                        break
            if not ok:
                continue
            for inp in entry.get("input", []):
                if not inp.lower().startswith(prefix):
                    continue
                text = entry.get("output") or inp
                cur = options.get(text)
                w = float(entry.get("weight", 1))
                if cur is None or w > cur["score"]:
                    opt = {"text": text, "score": w}
                    if entry.get("payload") is not None:
                        opt["payload"] = entry["payload"]
                    options[text] = opt
                break  # one option per entry
    ranked = sorted(options.values(),
                    key=lambda o: (-o["score"], o["text"]))
    return ranked[: spec["size"]]


def execute_suggest(specs: list[dict], segments: list[Segment],
                    analyzer_for, mappers=None) -> dict:
    """-> the response's "suggest" section."""
    out: dict = {}
    for spec in specs:
        field = spec["field"]
        if field is None or spec["text"] is None:
            raise SearchParseError(
                f"suggestion [{spec['name']}] requires [field] and [text]")
        entries = []
        if spec["kind"] == "completion":
            options = _completion_options(spec, segments, mappers)
            out[spec["name"]] = [{
                "text": spec["text"], "offset": 0,
                "length": len(str(spec["text"])), "options": options}]
            continue
        dfs = term_dfs_for(segments, field)
        analyzer = analyzer_for(field)
        if spec["kind"] == "phrase":
            # phrase: suggest whole-text corrections — best candidate per
            # token, joined (ref: PhraseSuggester simplified to a
            # unigram error model)
            tokens = analyzer.analyze(str(spec["text"]))
            corrected = []
            any_change = False
            score = 1.0
            for tok in tokens:
                if dfs.get(tok, 0) > 0:
                    corrected.append(tok)
                    continue
                cands = _candidates(tok, spec, dfs)
                if cands:
                    corrected.append(cands[0]["text"])
                    score *= cands[0]["score"]
                    any_change = True
                else:
                    corrected.append(tok)
            options = ([{"text": " ".join(corrected),
                         "score": round(score, 6)}] if any_change else [])
            entries.append({"text": spec["text"], "offset": 0,
                            "length": len(str(spec["text"])),
                            "options": options})
        else:
            offset = 0
            raw = str(spec["text"])
            for word in raw.split():
                toks = analyzer.analyze(word)
                tok = toks[0] if toks else word.lower()
                options = ([] if dfs.get(tok, 0) > 0
                           else _candidates(tok, spec, dfs))
                entries.append({"text": word,
                                "offset": raw.find(word, offset),
                                "length": len(word),
                                "options": options})
                offset = raw.find(word, offset) + len(word)
        out[spec["name"]] = entries
    return out


def merge_suggests(parts: list[dict], specs: list[dict]) -> dict:
    """Cross-shard reduce (ref: Suggest.reduce): merge options by text,
    summing freq, keeping max score, re-ranking."""
    merged: dict = {}
    for spec in specs:
        name = spec["name"]
        entry_lists = [p[name] for p in parts if name in p]
        if not entry_lists:
            continue
        base = [dict(e, options=[]) for e in entry_lists[0]]
        for i, entry in enumerate(base):
            by_text: dict[str, dict] = {}
            for part in entry_lists:
                if i >= len(part):
                    continue
                for opt in part[i]["options"]:
                    cur = by_text.get(opt["text"])
                    if cur is None:
                        by_text[opt["text"]] = dict(opt)
                    else:
                        cur["freq"] = cur.get("freq", 0) + opt.get("freq", 0)
                        cur["score"] = max(cur["score"], opt["score"])
            opts = sorted(by_text.values(),
                          key=lambda o: (-o["score"], -o.get("freq", 0),
                                         o["text"]))
            entry["options"] = opts[: spec["size"]]
        merged[name] = base
    return merged
