"""Cross-shard reduce: merge per-shard search results into one response.

Reference analog: search/controller/SearchPhaseController.java —
sortDocs (:147, the TopDocs.merge across shard top-k with (score desc,
shard index asc, doc asc) tie-breaking), fillDocIdsToLoad (:271), and the
final merge of hits + aggregation reduce (:282 with
InternalAggregation.reduce).

On a device mesh the same reduce runs INSIDE the jitted program via ICI
collectives (parallel/distributed.py); this host-side controller is the
DCN/coordinator path for shards living in different processes, and the
single-host multi-shard path.
"""

from __future__ import annotations

from .aggregations import AggSpec, finalize_partials, merge_shard_partials


def shards_header(total: int, successful: int,
                  failures: list[dict] | None = None,
                  failed: int | None = None) -> dict:
    """The `_shards` response section (ref: RestActions.buildBroadcast
    ShardsHeader). `failures` carries the structured per-shard entries;
    the key is emitted ONLY when non-empty so fully-successful responses
    stay byte-identical to the pre-failure-semantics output."""
    if failed is None:
        failed = len(failures or ())
    out = {"total": total, "successful": successful, "failed": failed}
    if failures:
        out["failures"] = list(failures)
    return out


def shard_failure(shard: int | None, index: str | None, exc: BaseException,
                  node: str | None = None) -> dict:
    """One structured `_shards.failures` entry (ref:
    ShardSearchFailure.toXContent: shard/index/node/status + the
    ElasticsearchException rendering with `caused_by`)."""
    reason = {"type": type(exc).__name__, "reason": str(exc)}
    # explicit causes only: the implicit __context__ chain re-states the
    # same error on retry paths (scheduler isolation re-raises inside an
    # except block), which would render every failure self-caused
    cause = exc.__cause__
    if cause is not None and cause is not exc:
        reason["caused_by"] = {"type": type(cause).__name__,
                               "reason": str(cause)}
    entry = {"shard": shard, "index": index,
             "status": getattr(exc, "status", 500), "reason": reason}
    if node is not None:
        entry["node"] = node
    return entry


def merge_shard_results(shard_responses: list[dict],
                        agg_specs: list[AggSpec] | None = None,
                        shard_partials: list[dict] | None = None,
                        frm: int = 0, size: int = 10,
                        descending: bool = True,
                        score_sort: bool = True,
                        multi_orders: list[bool] | None = None,
                        total_shards: int | None = None,
                        failures: list[dict] | None = None,
                        timed_out: bool = False) -> dict:
    """Merge per-shard responses (each already sorted, carrying up to
    from+size hits) into the final response.

    Tie-breaking matches the reference: equal keys resolve by shard index
    then per-shard rank (shard hits are already (seg, doc)-ordered).

    Partial-failure semantics: `failures` carries structured entries for
    shards that never produced a response (their count rides
    `total_shards`, which defaults to len(shard_responses) + failures);
    `timed_out` marks deadline-clipped responses. The reduce itself runs
    over the SURVIVING shards only — hits/aggs are exactly what a search
    over those shards alone would return (SearchPhaseController reduces
    whatever QuerySearchResults arrived).
    """
    total = 0
    failed = len(failures or ())
    successful = 0
    max_score = None
    cands: list[tuple] = []
    took = 0
    for shard_idx, resp in enumerate(shard_responses):
        if resp is None or resp.get("_failed"):
            failed += 1
            continue
        successful += 1
        took = max(took, resp.get("took", 0))
        total += resp["hits"]["total"]
        ms = resp["hits"].get("max_score")
        if ms is not None and (max_score is None or ms > max_score):
            max_score = ms
        for rank, hit in enumerate(resp["hits"]["hits"]):
            if multi_orders is not None:
                key = tuple(hit.get("sort") or [])
            elif score_sort:
                key = hit.get("_score") or 0.0
            else:
                key = hit.get("sort", [None])[0]
            cands.append((key, shard_idx, rank, hit))

    def sort_key(c):
        key, shard_idx, rank, _ = c
        missing = key is None
        if descending:
            primary = (missing, -(key if not missing else 0.0))
        else:
            primary = (missing, key if not missing else 0.0)
        return (*primary, shard_idx, rank)

    if multi_orders is not None:
        # multi-key merge: per-key direction + missing-last, mirroring
        # the shard-side lexsort (FieldComparator chain semantics)
        def sort_key(c):  # noqa: F811 — multi-key variant
            key_list, shard_idx, rank, _ = c
            parts = []
            for pos, desc in enumerate(multi_orders):
                v = key_list[pos] if pos < len(key_list) else None
                missing = v is None
                if isinstance(v, str):
                    parts.append((missing, _neg_str(v) if desc else v))
                else:
                    x = float(v) if v is not None else 0.0
                    parts.append((missing, -x if desc else x))
            return (*parts, shard_idx, rank)

        cands.sort(key=sort_key)
        hits = [h for _, _, _, h in cands[frm: frm + size]]
        out = {
            "took": took, "timed_out": timed_out,
            "_shards": shards_header(
                total_shards if total_shards is not None
                else len(shard_responses) + len(failures or ()),
                successful, failures, failed=failed),
            "hits": {"total": total, "max_score": None, "hits": hits},
        }
        if agg_specs:
            merged = merge_shard_partials(agg_specs, shard_partials or [])
            out["aggregations"] = finalize_partials(agg_specs, merged)
        return out

    # strings (keyword sort keys) and floats never mix within one query
    if cands and isinstance(next((c[0] for c in cands if c[0] is not None), 0.0),
                            str):
        def sort_key(c):  # noqa: F811 — string variant
            key, shard_idx, rank, _ = c
            missing = key is None
            k = key if not missing else ""
            return ((missing, k if not descending else _neg_str(k)),
                    shard_idx, rank)

    cands.sort(key=sort_key)
    hits = [h for _, _, _, h in cands[frm: frm + size]]

    out = {
        "took": took,
        "timed_out": timed_out,
        "_shards": shards_header(
            total_shards if total_shards is not None
            else len(shard_responses) + len(failures or ()),
            successful, failures, failed=failed),
        "hits": {"total": total,
                 "max_score": max_score if score_sort else None,
                 "hits": hits},
    }
    if agg_specs:
        merged = merge_shard_partials(agg_specs, shard_partials or [])
        out["aggregations"] = finalize_partials(agg_specs, merged)
    return out


class _neg_str:
    """Inverted string ordering for descending keyword sort."""

    __slots__ = ("s",)

    def __init__(self, s: str):
        self.s = s

    def __lt__(self, other: "_neg_str") -> bool:
        return self.s > other.s

    def __eq__(self, other) -> bool:
        return isinstance(other, _neg_str) and self.s == other.s
