"""Traffic control plane: tenant quotas, priority lanes, adaptive
coalescing, and admission control over the search read path.

Reference analog: the reference's layers 0-1 — 15 named thread pools
with bounded queues answering 429 `EsRejectedExecutionException` when
saturated, under parent circuit-breaker budgets. Those layers shed load
*after* a request holds queue slots and breaker bytes. On this stack
per-query device cost variance is far higher (a lone fused query is
sub-millisecond batched but a cold compile or a 20M-row agg is not), so
admission must act at the REST/node entry BEFORE a query takes a
breaker hold or a device program slot — a shed request costs one token
bucket subtraction and a structured 429 with Retry-After, nothing else.

Four cooperating pieces, all host-side and lock-cheap (no blocking call
ever runs under a traffic lock — graftlint's lock-discipline rule
covers this module):

* **TenantState / token buckets** — every request resolves to a tenant
  id at the REST boundary (`X-Tenant-Id` header / `tenant_id` param,
  the `default` tenant otherwise). Dynamic settings
  `search.traffic.tenant.<id>.rate|burst|max_concurrent|lane` attach a
  refill-rate token bucket and an in-flight concurrency cap;
  unconfigured tenants are unlimited (accounting only).
* **Priority lanes** — the dispatch scheduler drains per-lane queues
  (`interactive` > `msearch` > `scroll` > `bulk`) with per-round batch
  quotas on the non-interactive lanes: a bulk flood is split into
  bounded rounds, and every interactive batch pending at round start
  rides the very next round — interactive can never queue behind an
  arbitrarily deep bulk backlog (starvation is structurally
  impossible, not statistically unlikely).
* **AdaptiveWindow** — replaces the static `ES_TPU_COALESCE_WINDOW_MS`
  with a controller driven by the two signals the scheduler already
  observes: EWMA batch inter-arrival gap (arrival rate) and EWMA
  batches-merged-per-round (real concurrency). Sequential traffic
  (rounds of 1) keeps the window at 0 so lone queries never sleep;
  concurrent traffic opens it toward `target x gap`, clamped to
  `max_ms`. The env/setting static window still wins when set — it is
  the explicit operator override, not the default.
* **Admission** — `admit()` (one search/scroll) raises
  TrafficRejectedError(429, retry_after) when the bucket or the
  concurrency cap says no; `admit_items()` (msearch) grants a prefix
  of the batch and prices the rejected tail, so one over-quota tenant
  degrades to partial progress + structured per-item 429s instead of
  all-or-nothing.

Stats surface under `nodes_stats()["dispatch"]["traffic"]`: per-tenant
admitted/rejected/queued (in-flight), lane depth high-waters, the
current window (mode + ms), and the generation-keyed query-cache hit
rate (fed by node._submit_on_readers).
"""

from __future__ import annotations

import math
import threading
import time

from ..utils.errors import TrafficRejectedError
from ..utils.metrics import EWMA, HighWaterMetric

# lane priority order: lower index drains first. Unknown lanes sort
# after bulk (a plugin-invented lane must not outrank interactive).
LANES = ("interactive", "msearch", "scroll", "bulk")
_LANE_PRIORITY = {name: i for i, name in enumerate(LANES)}

# per-drain-round batch quotas (None = unlimited). Interactive is
# never capped — capping it could delay exactly the traffic the lanes
# exist to protect. Non-interactive defaults keep bulk rounds small
# enough that a mid-flood interactive arrival waits at most one
# bounded round, while still coalescing within the round.
_DEFAULT_LANE_QUOTAS = {"interactive": None, "msearch": 4, "scroll": 2,
                        "bulk": 2}


def lane_priority(lane: str) -> int:
    return _LANE_PRIORITY.get(lane, len(LANES))


class TokenBucket:
    """Classic refill-rate token bucket. `clock` is injectable so quota
    tests are deterministic (seeded virtual time, no sleeps). NOT
    internally locked — the owning TenantState serializes access under
    the controller lock."""

    __slots__ = ("rate", "burst", "tokens", "_t", "_clock")

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        # graftlint: ok(shared-state-race): owner-serialized by design
        # (class doc) — every access runs under the controller's _mx
        self.tokens = self.burst
        self._clock = clock
        # graftlint: ok(shared-state-race): owner-serialized by design
        # (class doc) — every access runs under the controller's _mx
        self._t = clock()

    def _refill(self) -> None:
        now = self._clock()
        if now > self._t:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t) * self.rate)
        self._t = now

    def take(self, n: float = 1.0) -> float:
        """0.0 when n tokens were consumed; otherwise the seconds until
        n tokens will be available (nothing consumed)."""
        self._refill()
        if self.tokens + 1e-9 >= n:
            self.tokens -= n
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return (n - self.tokens) / self.rate

    def take_upto(self, n: int) -> int:
        """Consume as many whole tokens as available, up to n."""
        self._refill()
        granted = min(n, int(self.tokens + 1e-9))
        if granted > 0:
            self.tokens -= granted
        return granted

    def time_until(self, n: float = 1.0) -> float:
        self._refill()
        if self.tokens + 1e-9 >= n:
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return (n - self.tokens) / self.rate


class TenantState:
    """One tenant's quota objects + lifetime counters. Mutated only
    under the controller lock."""

    __slots__ = ("tenant", "bucket", "max_concurrent", "lane",
                 "in_flight", "in_flight_hw", "admitted", "rejected")

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.bucket: TokenBucket | None = None
        self.max_concurrent: int | None = None
        self.lane: str | None = None
        self.in_flight = 0
        self.in_flight_hw = 0
        self.admitted = 0
        self.rejected = 0

    def snapshot(self) -> dict:
        return {"admitted": self.admitted, "rejected": self.rejected,
                "queued": self.in_flight,
                "queued_high_water": self.in_flight_hw,
                "lane": self.lane or "",
                "rate": self.bucket.rate if self.bucket else None,
                "max_concurrent": self.max_concurrent}


class Ticket:
    """One admitted request's in-flight reservation (n slots against
    the tenant's concurrency cap). Release is idempotent — the node's
    finally block and an error path may both call it."""

    __slots__ = ("_controller", "tenant", "_n", "_released", "lane",
                 "granted")

    def __init__(self, controller: "TrafficController", tenant: str,
                 n: int, lane: str, granted: int | None = None):
        self._controller = controller
        self.tenant = tenant
        self._n = n
        self._released = False
        self.lane = lane
        self.granted = n if granted is None else granted

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._controller._release(self.tenant, self._n)


class ItemsTicket(Ticket):
    """admit_items() result: `granted` items proceed (first-come order
    preserved — the admitted prefix), the rest answer 429 priced at
    `retry_after_s`."""

    __slots__ = ("retry_after_s",)

    def __init__(self, controller, tenant, granted: int, requested: int,
                 lane: str, retry_after_s: float):
        super().__init__(controller, tenant, granted, lane,
                         granted=granted)
        self.retry_after_s = retry_after_s
        self._n = granted  # only admitted items hold concurrency slots


class AdaptiveWindow:
    """Coalescing-window controller (see module doc).

    Signals:
      * `observe_arrival()` per batch enqueue -> EWMA inter-arrival gap
      * `observe_round(n)` per drain round -> EWMA merged-batch count
        (the scheduler's real concurrency, incl. in-flight adoption)

    Policy: window stays 0 unless rounds actually merge (>1.05 EWMA —
    sequential callers can never benefit from waiting, their next batch
    arrives only after this one completes) AND another arrival is
    expected within `max_ms`. When open: `target` expected arrivals'
    worth of gap, clamped to [0, max_ms]. Goes back to 0 after
    `idle_reset_s` without arrivals."""

    def __init__(self, enabled: bool = True, max_ms: float = 4.0,
                 target: float = 2.0, decay: float = 0.2,
                 idle_reset_s: float = 1.0, clock=time.monotonic):
        self.enabled = bool(enabled)
        self.max_ms = float(max_ms)
        self.target = float(target)
        self._idle_reset_s = float(idle_reset_s)
        self._clock = clock
        self._mx = threading.Lock()
        self._last_arrival: float | None = None
        # the two signals are utils.metrics.EWMA objects (internally
        # locked, so the shared-state-race pass verifies the updates
        # instead of this class hand-rolling unlocked float math):
        # the gap series is unseeded (first sample seeds it; an idle
        # reset() forgets it), the merged-round series starts AT 1.0
        # (sequential traffic) and decays toward observed rounds
        self._gap = EWMA(alpha=float(decay))
        self._round = EWMA(alpha=float(decay), initial=1.0, seeded=True)
        self._last_window_ms = 0.0

    def observe_arrival(self) -> None:
        now = self._clock()
        with self._mx:
            if self._last_arrival is not None:
                gap = max(now - self._last_arrival, 1e-6)
                if gap <= self._idle_reset_s:
                    self._gap.update(gap)
                else:
                    # a fresh burst after idle: forget the stale gap
                    self._gap.reset()
            self._last_arrival = now

    def observe_round(self, n_batches: int) -> None:
        self._round.update(float(max(n_batches, 1)))

    def window_ms(self) -> float:
        if not self.enabled:
            return 0.0
        now = self._clock()
        with self._mx:
            w = 0.0
            if (self._last_arrival is not None
                    and now - self._last_arrival <= self._idle_reset_s
                    and self._round.value > 1.05
                    and self._gap.initialized):
                gap_ms = self._gap.value * 1000.0
                if gap_ms <= self.max_ms:  # another arrival is likely
                    w = min(self.max_ms, self.target * gap_ms)
            self._last_window_ms = w
            return w

    def snapshot(self) -> dict:
        with self._mx:
            gap_s = self._gap.value if self._gap.initialized else None
            return {"enabled": self.enabled, "max_ms": self.max_ms,
                    "target": self.target,
                    "ewma_gap_ms": (round(gap_s * 1000.0, 4)
                                    if gap_s is not None else None),
                    "ewma_round_batches": round(self._round.value, 3),
                    "last_window_ms": round(self._last_window_ms, 4)}


DEFAULT_TENANT = "default"


class TrafficController:
    """Per-tenant admission + lane policy + the adaptive window, built
    from the flat `search.traffic.*` settings group (node settings
    layered under dynamic cluster settings — reconfigure() republishes
    quotas without dropping counters or in-flight accounting).

    Ops and default lanes: search/count -> interactive, msearch ->
    msearch, scroll -> scroll; a tenant's `lane` setting overrides
    (that is how a known-bulk tenant's msearch traffic rides the bulk
    lane)."""

    _OP_LANES = {"search": "interactive", "msearch": "msearch",
                 "scroll": "scroll"}

    def __init__(self, cfg: dict | None = None,
                 adaptive: AdaptiveWindow | None = None,
                 clock=time.monotonic):
        from ..utils import race_guard
        self._mx = threading.Lock()
        self._clock = clock
        self._tenants: dict[str, TenantState] = race_guard.guarded_dict(
            self._mx, "traffic.TrafficController._tenants")
        self._limits: dict[str, dict] = {}
        self._lane_quotas = dict(_DEFAULT_LANE_QUOTAS)
        self._lane_depth: dict[str, HighWaterMetric] = {
            lane: HighWaterMetric() for lane in LANES}
        self.window = adaptive if adaptive is not None else AdaptiveWindow(
            clock=clock)
        self._cache_hits = 0
        self._cache_misses = 0
        self.reconfigure(cfg or {})

    # -- configuration -----------------------------------------------------
    def reconfigure(self, cfg: dict) -> None:
        """cfg: flat keys with the `search.traffic.` prefix stripped
        (`tenant.<id>.rate`, `lane.<name>.quota`, ...). Existing tenant
        counters and in-flight slots survive; buckets are rebuilt when
        their limits changed (a refreshed bucket starts full — a quota
        edit must not retroactively debt a tenant)."""
        limits: dict[str, dict] = {}
        lane_quotas = dict(_DEFAULT_LANE_QUOTAS)
        for key, val in cfg.items():
            if key.startswith("tenant."):
                # tenant ids are arbitrary header strings and may
                # contain dots: the ATTRIBUTE is the last segment, the
                # id is everything between (rsplit, not a fixed split —
                # a dotted-id tenant's quota must not silently no-op)
                tid, _, attr = key[len("tenant."):].rpartition(".")
                if tid and attr in ("rate", "burst", "max_concurrent",
                                    "lane"):
                    limits.setdefault(tid, {})[attr] = val
            elif key.startswith("lane.") and key.endswith(".quota"):
                if val in (None, ""):
                    continue          # null = unset: default quota stays
                q = int(val)
                name = key[len("lane."):-len(".quota")]
                lane_quotas[name] = None if q <= 0 else q
        with self._mx:
            self._limits = limits
            self._lane_quotas = lane_quotas
            for tenant, st in self._tenants.items():
                self._apply_limits_locked(st, limits.get(tenant))

    def _apply_limits_locked(self, st: TenantState,
                             lim: dict | None) -> None:
        if not lim:
            st.bucket = None
            st.max_concurrent = None
            st.lane = None
            return
        # settings arrive as raw JSON values OR strings: normalize
        # numerically so -1 / "-1" / unset all mean unlimited (rate 0
        # stays meaningful: fully blocked past the burst)
        rate = lim.get("rate")
        rate = None if rate in (None, "") else float(rate)
        if rate is None or rate < 0:
            st.bucket = None
        else:
            burst = float(lim.get("burst") or max(2.0 * rate, 1.0))
            if (st.bucket is None or st.bucket.rate != rate
                    or st.bucket.burst != max(burst, 1.0)):
                st.bucket = TokenBucket(rate, burst, clock=self._clock)
        mc = lim.get("max_concurrent")
        mc = None if mc in (None, "") else int(mc)
        st.max_concurrent = None if (mc is None or mc < 0) else mc
        st.lane = lim.get("lane") or None

    # tenant ids are attacker-controlled (the X-Tenant-Id header is
    # unauthenticated): per-tenant state must be bounded or random ids
    # grow _tenants — and every nodes_stats() snapshot — without limit
    _TENANT_CAP = 1024

    def _tenant_locked(self, tenant: str | None) -> TenantState:
        tid = tenant or DEFAULT_TENANT
        st = self._tenants.get(tid)
        if st is None:
            if len(self._tenants) >= self._TENANT_CAP:
                self._evict_tenants_locked()
            st = TenantState(tid)
            self._apply_limits_locked(st, self._limits.get(tid))
            self._tenants[tid] = st
        return st

    def _evict_tenants_locked(self) -> None:
        """Drop oldest UNCONFIGURED idle tenants (accounting-only
        entries — their counters are the only loss). Operator-
        configured tenants and anything in flight are never evicted;
        if nothing qualifies the map grows past the cap rather than
        corrupting live accounting."""
        spare = [tid for tid, st in self._tenants.items()
                 if tid not in self._limits and st.in_flight == 0
                 and tid != DEFAULT_TENANT]
        for tid in spare[: max(len(self._tenants) - self._TENANT_CAP + 1,
                               self._TENANT_CAP // 8)]:
            del self._tenants[tid]

    # -- admission ---------------------------------------------------------
    def lane_for(self, tenant: str | None, op: str) -> str:
        with self._mx:
            st = self._tenant_locked(tenant)
            return st.lane or self._OP_LANES.get(op, "interactive")

    def admit(self, tenant: str | None, op: str) -> Ticket:
        """Admit one search/scroll; raises TrafficRejectedError (429 +
        retry_after) on a quota/concurrency reject. Runs BEFORE the
        request takes a thread-pool slot or any breaker hold — a shed
        request costs only this bookkeeping."""
        with self._mx:
            st = self._tenant_locked(tenant)
            lane = st.lane or self._OP_LANES.get(op, "interactive")
            if st.max_concurrent is not None \
                    and st.in_flight + 1 > st.max_concurrent:
                st.rejected += 1
                raise TrafficRejectedError(
                    st.tenant, f"concurrency limit "
                    f"[{st.max_concurrent}] reached",
                    retry_after_s=0.1)
            if st.bucket is not None:
                wait = st.bucket.take(1.0)
                if wait > 0.0:
                    st.rejected += 1
                    raise TrafficRejectedError(
                        st.tenant, f"rate limit "
                        f"[{st.bucket.rate:g}/s] exceeded",
                        retry_after_s=wait)
            st.admitted += 1
            st.in_flight += 1
            st.in_flight_hw = max(st.in_flight_hw, st.in_flight)
        return Ticket(self, st.tenant, 1, lane)

    def admit_items(self, tenant: str | None, op: str,
                    n: int) -> ItemsTicket:
        """msearch admission: grant the longest admissible prefix of n
        items (tokens AND concurrency headroom), price the rejected
        tail. Never raises — zero granted is a valid answer and the
        caller renders per-item 429s for the remainder."""
        with self._mx:
            st = self._tenant_locked(tenant)
            lane = st.lane or self._OP_LANES.get(op, "msearch")
            # concurrency clamp FIRST, tokens second — take_upto
            # consumes what it grants, so clamping afterwards would
            # permanently burn tokens for items the concurrency cap
            # then rejects (charging the tenant for work never run)
            granted = n
            if st.max_concurrent is not None:
                granted = max(0, min(
                    granted, st.max_concurrent - st.in_flight))
            if st.bucket is not None:
                granted = st.bucket.take_upto(granted)
            retry_after = 0.0
            if granted < n:
                st.rejected += n - granted
                retry_after = 0.1
                if st.bucket is not None:
                    retry_after = max(retry_after,
                                      st.bucket.time_until(1.0))
            st.admitted += granted
            st.in_flight += granted
            st.in_flight_hw = max(st.in_flight_hw, st.in_flight)
        return ItemsTicket(self, st.tenant, granted, n, lane,
                           retry_after)

    def _release(self, tenant: str, n: int) -> None:
        with self._mx:
            st = self._tenants.get(tenant)
            if st is not None:
                st.in_flight = max(0, st.in_flight - n)

    # -- scheduler hooks ---------------------------------------------------
    def lane_quota(self, lane: str) -> int | None:
        with self._mx:
            return self._lane_quotas.get(
                lane, _DEFAULT_LANE_QUOTAS.get("bulk"))

    def note_lane_depth(self, lane: str, depth: int) -> None:
        with self._mx:
            hw = self._lane_depth.get(lane)
            if hw is None:
                hw = self._lane_depth.setdefault(lane,
                                                 HighWaterMetric())
        hw.record(depth)

    # -- cache accounting (fed by node._submit_on_readers) -----------------
    def note_cache(self, hit: bool) -> None:
        with self._mx:
            if hit:
                self._cache_hits += 1
            else:
                self._cache_misses += 1

    # -- stats -------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._mx:
            tenants = {tid: st.snapshot()
                       for tid, st in sorted(self._tenants.items())}
            lanes = {lane: {"depth_high_water": hw.max,
                            "quota": self._lane_quotas.get(lane)}
                     for lane, hw in sorted(self._lane_depth.items())}
            hits, misses = self._cache_hits, self._cache_misses
        consulted = hits + misses
        return {
            "tenants": tenants,
            "lanes": lanes,
            "window": self.window.snapshot(),
            "query_cache": {
                "hits": hits, "misses": misses,
                "hit_rate": (hits / consulted) if consulted else 0.0},
        }


def retry_after_header(seconds: float) -> str:
    """Retry-After is integer seconds on the wire; sub-second throttle
    horizons still answer at least 1 so naive clients do not hot-loop."""
    if not math.isfinite(seconds):
        return "60"
    return str(max(1, int(math.ceil(seconds))))


def controller_from_settings(settings, clock=time.monotonic
                             ) -> TrafficController:
    """Build from a Settings object: `search.traffic.*` is the quota /
    lane group; the adaptive window reads its knobs from
    `search.dispatch.adaptive_window*` (enabled by default — it
    converges to 0 for sequential traffic, so enabling it costs lone
    queries nothing)."""
    adaptive = AdaptiveWindow(
        enabled=settings.get_bool("search.dispatch.adaptive_window",
                                  True),
        max_ms=settings.get_float(
            "search.dispatch.adaptive_window_max_ms", 4.0),
        target=settings.get_float(
            "search.dispatch.adaptive_window_target", 2.0),
        clock=clock)
    return TrafficController(
        settings.by_prefix("search.traffic.").as_dict(),
        adaptive=adaptive, clock=clock)
