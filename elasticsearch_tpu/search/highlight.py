"""Plain highlighter: wrap query terms in tags over stored _source text.

Reference analog: search/highlight/ — PlainHighlighter.java re-analyzes
the stored field text and marks query-term matches, emitting the best
fragments. (The reference's FVH/postings highlighters need term vectors /
offsets in the index; the plain path re-analyzes, which is what we do —
highlighting is host-side string work and never touches the device.)
"""

from __future__ import annotations

import re

from ..index.mapping import MapperService
from .query_dsl import (BoolQuery, BoostingQuery, ConstantScoreQuery,
                        FuzzyQuery, MatchAllQuery, PhraseQuery, PrefixQuery,
                        Query, SpanFirstQuery, SpanNearQuery, SpanNotQuery,
                        SpanOrQuery, SpanTermQuery, TermQuery, WildcardQuery)


def collect_terms(q: Query, field: str | None = None) -> dict[str, set[str]]:
    """Walk the query AST collecting field -> terms to highlight
    (ref: highlight uses Query.extractTerms)."""
    out: dict[str, set[str]] = {}

    def walk(node: Query):
        if isinstance(node, TermQuery):
            out.setdefault(node.field, set()).add(str(node.value))
        elif isinstance(node, (PrefixQuery, WildcardQuery, FuzzyQuery)):
            out.setdefault(node.field, set()).add(str(node.value))
        elif isinstance(node, BoolQuery):
            for sub in (*node.must, *node.should, *node.filter):
                walk(sub)
        elif isinstance(node, ConstantScoreQuery):
            walk(node.query)
        elif isinstance(node, BoostingQuery):
            walk(node.positive)
        elif isinstance(node, PhraseQuery):
            out.setdefault(node.field, set()).update(map(str, node.terms))
        elif isinstance(node, SpanTermQuery):
            out.setdefault(node.field, set()).add(str(node.value))
        elif isinstance(node, (SpanNearQuery, SpanOrQuery)):
            for sub in node.clauses:
                walk(sub)
        elif isinstance(node, SpanFirstQuery):
            walk(node.match)
        elif isinstance(node, SpanNotQuery):
            walk(node.include)
    walk(q)
    if field is not None:
        out = {f: t for f, t in out.items() if f == field}
    return out


def collect_loose_terms(q: Query, field: str) -> set[str]:
    """Terms targeting `field` from NON-phrase clauses — the ones the
    FVH path tags individually (a term that also appears inside some
    phrase still highlights standalone when a term clause asks for
    it)."""
    out: set[str] = set()

    def walk(node: Query):
        if isinstance(node, (TermQuery, PrefixQuery, WildcardQuery,
                             FuzzyQuery, SpanTermQuery)):
            if node.field == field:
                out.add(str(node.value))
        elif isinstance(node, BoolQuery):
            for sub in (*node.must, *node.should, *node.filter):
                walk(sub)
        elif isinstance(node, ConstantScoreQuery):
            walk(node.query)
        elif isinstance(node, BoostingQuery):
            walk(node.positive)
        elif isinstance(node, (SpanNearQuery, SpanOrQuery)):
            for sub in node.clauses:
                walk(sub)
        elif isinstance(node, SpanFirstQuery):
            walk(node.match)
        elif isinstance(node, SpanNotQuery):
            walk(node.include)
    walk(q)
    return out


def collect_phrases(q: Query, field: str) -> list[tuple[str, ...]]:
    """Phrase term sequences targeting `field` — the FVH path highlights
    whole phrase occurrences, not their individual terms (ref:
    FastVectorHighlighter phrase-aware FieldQuery)."""
    out: list[tuple[str, ...]] = []

    def walk(node: Query):
        if isinstance(node, PhraseQuery) and node.field == field:
            out.append(tuple(map(str, node.terms)))
        elif isinstance(node, BoolQuery):
            for sub in (*node.must, *node.should, *node.filter):
                walk(sub)
        elif isinstance(node, ConstantScoreQuery):
            walk(node.query)
        elif isinstance(node, BoostingQuery):
            walk(node.positive)
    walk(q)
    return out


def parse_highlight(body: dict | None) -> dict | None:
    if not body:
        return None
    fields = body.get("fields")
    if not fields:
        return None
    out = {"fields": {}, "pre": body.get("pre_tags", ["<em>"])[0],
           "post": body.get("post_tags", ["</em>"])[0]}
    for fld, spec in fields.items():
        spec = spec or {}
        out["fields"][fld] = {
            "fragment_size": int(spec.get("fragment_size",
                                          body.get("fragment_size", 100))),
            "number_of_fragments": int(spec.get(
                "number_of_fragments", body.get("number_of_fragments", 5))),
            # plain (default) | fvh | postings — fvh/postings share the
            # phrase-aware best-fragment path here
            "type": str(spec.get("type", body.get("type", "plain"))),
        }
    return out


def highlight_hit(source: dict, query: Query, spec: dict,
                  mapper: MapperService) -> dict[str, list[str]]:
    """-> {field: [fragments]} for one hit."""
    terms_by_field = collect_terms(query)
    result: dict[str, list[str]] = {}
    for fld, fspec in spec["fields"].items():
        value = _field_value(source, fld)
        if value is None:
            continue
        terms = terms_by_field.get(fld, set())
        if not terms:
            continue
        analyzer = mapper.search_analyzer_for(fld)
        if fspec.get("type") in ("fvh", "fast-vector-highlighter",
                                 "fast_vector_highlighter", "postings"):
            frags = _fvh_fragments(
                str(value), collect_loose_terms(query, fld),
                collect_phrases(query, fld), analyzer,
                spec["pre"], spec["post"], fspec["fragment_size"],
                fspec["number_of_fragments"])
        else:
            frags = _fragments(str(value), terms, analyzer, spec["pre"],
                               spec["post"], fspec["fragment_size"],
                               fspec["number_of_fragments"])
        if frags:
            result[fld] = frags
    return result


def _field_value(source: dict, path: str):
    cur = source
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _fvh_fragments(text: str, terms: set[str],
                   phrases: list[tuple[str, ...]], analyzer, pre: str,
                   post: str, fragment_size: int,
                   max_fragments: int) -> list[str]:
    """Phrase-aware best-fragment highlighting (ref:
    FastVectorHighlighter: term-vector positions+offsets drive whole-
    phrase tags and fragments ordered by score; here word offsets come
    from re-tokenizing the stored text, which holds the same
    information).

    Each phrase occurrence is tagged as ONE span; `terms` (from
    non-phrase clauses) tag individually; fragments are scored by the
    number of spans they contain and returned best-first."""
    words = [(m.start(), m.end(), analyzer.analyze(m.group()))
             for m in re.finditer(r"\S+", text)]
    spans: list[tuple[int, int]] = []
    for phrase in phrases:
        n = len(phrase)
        for i in range(len(words) - n + 1):
            if all(phrase[j] in words[i + j][2] for j in range(n)):
                spans.append((words[i][0], words[i + n - 1][1]))
    for s, e, toks in words:
        if any(t in terms for t in toks):
            spans.append((s, e))
    if not spans:
        return []
    spans.sort()
    # build candidate fragments around each span, score by span count
    frags: list[tuple[int, int, int]] = []   # (score, start, end)
    used_until = -1
    for start, end in spans:
        if start < used_until:
            continue
        frag_start = max(0, start - fragment_size // 2)
        frag_end = min(len(text), frag_start + fragment_size)
        used_until = frag_end
        score = sum(1 for s, e in spans
                    if s >= frag_start and e <= frag_end)
        frags.append((score, frag_start, frag_end))
    frags.sort(key=lambda f: (-f[0], f[1]))  # best-scoring first (FVH)
    out: list[str] = []
    for _score, frag_start, frag_end in frags[:max_fragments]:
        frag_text = text[frag_start:frag_end]
        inside = [(s - frag_start, e - frag_start) for s, e in spans
                  if s >= frag_start and e <= frag_end]
        # drop spans nested in an earlier (phrase) span
        merged: list[tuple[int, int]] = []
        for s, e in inside:
            if merged and s < merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], e))
            else:
                merged.append((s, e))
        parts = []
        pos = 0
        for s, e in merged:
            parts.append(frag_text[pos:s])
            parts.append(pre)
            parts.append(frag_text[s:e])
            parts.append(post)
            pos = e
        parts.append(frag_text[pos:])
        out.append("".join(parts))
    return out


def _fragments(text: str, terms: set[str], analyzer, pre: str, post: str,
               fragment_size: int, max_fragments: int) -> list[str]:
    # token-level match: analyze each whitespace word, compare to the
    # (already-analyzed) query terms — mirrors plain highlighting where
    # both sides go through the search analyzer
    spans: list[tuple[int, int]] = []
    for m in re.finditer(r"\S+", text):
        toks = analyzer.analyze(m.group())
        if any(t in terms for t in toks):
            spans.append((m.start(), m.end()))
    if not spans:
        return []
    # greedy fragmenting around match spans (SimpleFragmenter analog)
    frags: list[str] = []
    used_until = -1
    for start, end in spans:
        if start < used_until:
            continue
        frag_start = max(0, start - fragment_size // 2)
        frag_end = min(len(text), frag_start + fragment_size)
        used_until = frag_end
        frag_text = text[frag_start:frag_end]
        # tag every matching word inside the fragment
        offset_spans = [(s - frag_start, e - frag_start)
                        for s, e in spans
                        if s >= frag_start and e <= frag_end]
        out = []
        pos = 0
        for s, e in offset_spans:
            out.append(frag_text[pos:s])
            out.append(pre)
            out.append(frag_text[s:e])
            out.append(post)
            pos = e
        out.append(frag_text[pos:])
        frags.append("".join(out))
        if len(frags) >= max_fragments:
            break
    return frags
