"""Term vectors: per-document term statistics for one or more fields.

Reference analog: action/termvectors/TransportTermVectorsAction +
index/termvectors/ShardTermVectorsService.java — returns, per field, the
doc's terms with term_freq/positions and (optionally) df/ttf from the
shard. The columnar layout serves this directly: the postings CSR plus
the positional sidecar already hold everything, keyed by doc row.
"""

from __future__ import annotations

import numpy as np

from ..index.segment import Segment, PostingsField


def _doc_terms(pf: PostingsField, d: int) -> list[tuple[str, int, list[int]]]:
    """(term, tf, positions) entries of doc row `d` in one text field.
    The forward index gives the doc's term ids in O(slots); only fields
    that exceeded the forward-width cap fall back to a vocabulary scan."""
    if pf.fwd_tids is not None:
        tids = [int(t) for t in pf.fwd_tids[d] if t >= 0]
    else:
        tids = [t_idx for t_idx in range(len(pf.terms))
                if _posting_of(pf, t_idx, d) is not None]
    out = []
    for t_idx in sorted(set(tids)):
        j = _posting_of(pf, t_idx, d)
        if j is None:
            continue
        positions: list[int] = []
        if pf.pos_data is not None:
            ps, pe = int(pf.pos_indptr[j]), int(pf.pos_indptr[j + 1])
            positions = [int(p) for p in pf.pos_data[ps:pe]]
        out.append((pf.terms[t_idx], int(pf.tfs[j]), positions))
    return out


def _posting_of(pf: PostingsField, t_idx: int, d: int) -> int | None:
    """Index into the postings CSR of (term t_idx, doc d), or None."""
    s, e = int(pf.indptr[t_idx]), int(pf.indptr[t_idx + 1])
    j = s + int(np.searchsorted(pf.doc_ids[s:e], d))
    if j < e and int(pf.doc_ids[j]) == d:
        return j
    return None


def term_vectors(segments: list[Segment], live: dict, doc_id: str,
                 fields: list[str] | None = None,
                 term_statistics: bool = False,
                 field_statistics: bool = True,
                 positions: bool = True) -> dict | None:
    """Build the term_vectors section for one document, or None if the
    doc is absent."""
    for seg in segments:
        d = seg.id_map.get(doc_id)
        if d is None or not live.get(seg.seg_id, np.ones(1, bool))[d]:
            continue
        out: dict = {}
        names = fields if fields else sorted(seg.text)
        for name in names:
            pf = seg.text.get(name)
            if pf is None:
                continue
            terms_out: dict = {}
            for term, tf, pos in _doc_terms(pf, d):
                entry: dict = {"term_freq": tf}
                if positions and pos:
                    entry["tokens"] = [{"position": p} for p in pos]
                if term_statistics:
                    t_idx = pf.lookup(term)
                    s, e = int(pf.indptr[t_idx]), int(pf.indptr[t_idx + 1])
                    entry["doc_freq"] = int(pf.df[t_idx])
                    entry["ttf"] = int(pf.tfs[s:e].sum())
                terms_out[term] = entry
            field_out: dict = {"terms": terms_out}
            if field_statistics:
                field_out["field_statistics"] = {
                    "sum_doc_freq": int(pf.df.sum()),
                    "doc_count": int(pf.doc_count),
                    "sum_ttf": int(pf.tfs.sum()),
                }
            out[name] = field_out
        return out
    return None
