"""Term vectors: per-document term statistics for one or more fields.

Reference analog: action/termvectors/TransportTermVectorsAction +
index/termvectors/ShardTermVectorsService.java — returns, per field, the
doc's terms with term_freq/positions and (optionally) df/ttf from the
shard. The columnar layout serves this directly: the postings CSR plus
the positional sidecar already hold everything, keyed by doc row.
"""

from __future__ import annotations

import numpy as np

from ..index.segment import Segment, PostingsField


def _doc_terms(pf: PostingsField, d: int) -> list[tuple[str, int, list[int]]]:
    """(term, tf, positions) entries of doc row `d` in one text field.
    The forward index gives the doc's term ids in O(slots); only fields
    that exceeded the forward-width cap fall back to a vocabulary scan."""
    if pf.fwd_tids is not None:
        tids = [int(t) for t in pf.fwd_tids[d] if t >= 0]
    else:
        tids = [t_idx for t_idx in range(len(pf.terms))
                if _posting_of(pf, t_idx, d) is not None]
    out = []
    for t_idx in sorted(set(tids)):
        j = _posting_of(pf, t_idx, d)
        if j is None:
            continue
        positions: list[int] = []
        if pf.pos_data is not None:
            ps, pe = int(pf.pos_indptr[j]), int(pf.pos_indptr[j + 1])
            positions = [int(p) for p in pf.pos_data[ps:pe]]
        out.append((pf.terms[t_idx], int(pf.tfs[j]), positions))
    return out


def _posting_of(pf: PostingsField, t_idx: int, d: int) -> int | None:
    """Index into the postings CSR of (term t_idx, doc d), or None."""
    s, e = int(pf.indptr[t_idx]), int(pf.indptr[t_idx + 1])
    j = s + int(np.searchsorted(pf.doc_ids[s:e], d))
    if j < e and int(pf.doc_ids[j]) == d:
        return j
    return None


def _field_spans(seg: Segment, d: int, name: str,
                 analyzer=None) -> list[tuple[int, int]]:
    """Character spans of the field's tokens, reconstructed by
    re-scanning the stored _source with the standard word pattern (the
    reference stores offsets in the term-vector postings; the columnar
    store re-derives them from _source on demand — same information,
    zero index-time cost).

    Spans align with POST-FILTER token positions: raw words the
    analyzer's filter chain drops (stop words) or multiplies (ngrams —
    detected as >1 output) contribute no span, keeping position p ->
    spans[p] correct for 1:1 chains and conservatively empty otherwise.
    """
    import json as _json
    import re as _re
    from ..index import analysis as _an
    # span pattern must mirror the field's TOKENIZER; unknown tokenizers
    # yield no offsets rather than wrong ones
    tok = getattr(analyzer, "tokenizer", None)
    if tok is _an.whitespace_tokenizer:
        span_re = _re.compile(r"\S+")
    elif tok is _an.letter_tokenizer:
        span_re = _an._LETTER_RE
    elif tok is _an.standard_tokenizer or analyzer is None \
            or tok is None:
        span_re = _an._WORD_RE
    else:
        return []
    try:
        obj = _json.loads(seg.sources[d])
    except Exception:
        return []
    cur = obj
    for part in name.split("."):
        cur = cur.get(part) if isinstance(cur, dict) else None
    if not isinstance(cur, str):
        return []
    spans = []
    for m in span_re.finditer(cur):
        if analyzer is not None:
            toks = [m.group(0)]
            for f in analyzer.filters:
                toks = f(toks)
            if len(toks) == 0:
                continue            # filtered out: no position emitted
            if len(toks) > 1:
                return []           # token-multiplying chain: offsets
                                    # cannot be derived from _source
        spans.append((m.start(), m.end()))
    return spans


def term_vectors(segments: list[Segment], live: dict, doc_id: str,
                 fields: list[str] | None = None,
                 term_statistics: bool = False,
                 field_statistics: bool = True,
                 positions: bool = True,
                 offsets: bool = True,
                 analyzer_for=None) -> dict | None:
    """Build the term_vectors section for one document, or None if the
    doc is absent."""
    for seg in segments:
        d = seg.id_map.get(doc_id)
        if d is None or not live.get(seg.seg_id, np.ones(1, bool))[d]:
            continue
        out: dict = {}
        names = fields if fields else sorted(seg.text)
        for name in names:
            pf = seg.text.get(name)
            if pf is None:
                continue
            spans = (_field_spans(
                seg, d, name,
                analyzer_for(name) if analyzer_for else None)
                if offsets else [])
            terms_out: dict = {}
            for term, tf, pos in _doc_terms(pf, d):
                entry: dict = {"term_freq": tf}
                if positions and pos:
                    entry["tokens"] = [
                        {"position": p,
                         **({"start_offset": spans[p][0],
                             "end_offset": spans[p][1]}
                            if p < len(spans) else {})}
                        for p in pos]
                if term_statistics:
                    t_idx = pf.lookup(term)
                    s, e = int(pf.indptr[t_idx]), int(pf.indptr[t_idx + 1])
                    entry["doc_freq"] = int(pf.df[t_idx])
                    entry["ttf"] = int(pf.tfs[s:e].sum())
                terms_out[term] = entry
            field_out: dict = {"terms": terms_out}
            if field_statistics:
                field_out["field_statistics"] = {
                    "sum_doc_freq": int(pf.df.sum()),
                    "doc_count": int(pf.doc_count),
                    "sum_ttf": int(pf.tfs.sum()),
                }
            out[name] = field_out
        return out
    return None
