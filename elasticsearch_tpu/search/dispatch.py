"""Search dispatch scheduler: cross-request coalescing + pipelining.

The device charges a flat per-dispatch round trip (~65 ms over the dev
tunnel — bench.py's `tunnel_dispatch_overhead_ms`), which dominates
single-query latency while the batched per-query cost is
sub-millisecond. This scheduler closes the unbatched-traffic gap two
ways, one layer ABOVE the per-reader signature batching the executor
already does:

* **coalescing** — concurrent searches whose plans finalize to the same
  (desc, agg_desc, sort_spec, k, segment) group into ONE batched device
  dispatch (leading dim B; the executor's pow2 batch padding means no
  new compile keys), and the batched wire result is scattered back into
  per-request responses;
* **pipelining** — requests that cannot coalesce (different plan
  shapes, different readers/shards) are dispatched back-to-back through
  the executor's non-syncing entry so their tunnel round trips OVERLAP
  instead of serializing; collection happens in submission order.

Callers build a `DispatchBatch`, submit (reader, body) jobs, and call
`dispatch()`. Batches arriving while another batch executes queue up
and are drained by the next leader (the adaptive zero-latency
coalescing the per-reader MicroBatcher pioneered, now cross-reader).

**Priority lanes** (traffic control plane, search/traffic.py): every
batch carries a lane (`interactive` / `msearch` / `scroll` / `bulk`)
and each drain round takes ALL pending interactive batches plus at
most a per-lane quota of batches from the other lanes — a bulk flood
is split into bounded rounds instead of one monolithic backlog, so an
interactive batch pending at round start always rides the very next
round and can never starve behind a full bulk lane. Leftover batches
stay queued; the leader's drain loop continues until nothing is
pending, so nothing is ever dropped, only re-ordered.

**Coalescing window**: `ES_TPU_COALESCE_WINDOW_MS` (or the
`search.dispatch.coalesce_window_ms` setting) > 0 forces a STATIC
window — the leader sleeps that long before draining so concurrent
REST traffic can coalesce even when requests do not overlap an
in-flight dispatch. With no static window configured, the traffic
controller's AdaptiveWindow decides per drain from observed arrival
rate and per-round merge depth: 0 for sequential traffic (a lone
query never sleeps), up to a few ms under real concurrency.

Stats surface under `nodes_stats()["dispatch"]` (lanes/window/tenant
counters under `["dispatch"]["traffic"]`).
"""

from __future__ import annotations

import os
import threading
import time

from ..utils.errors import SearchTimeoutError
from ..utils.metrics import CounterMetric, HighWaterMetric

# thread-local mirror of the LAST msearch submit's (group_sizes,
# dispatch_count) on the CURRENT thread — how the scheduler's sync path
# (which calls the plain reader.msearch wrapper, so monkeypatch-friendly
# test seams keep working) reads coalescing stats without a shared
# mutable attribute on the reader. Writers: ShardReader.msearch and
# DistributedSearcher.msearch, at the END of each call (so nested
# auxiliary msearch calls inside response building do not win).
submit_stats = threading.local()


def note_submit_stats(group_sizes, dispatches: int) -> None:
    submit_stats.value = (list(group_sizes), dispatches)


class FailoverStats:
    """Replica-failover counters (process-wide: mesh searchers are
    constructed outside any Node, so the counters live here and every
    node's `nodes_stats()["dispatch"]["failover"]` reports them; a Node
    installs a FRESH instance at init and resets on close like the
    fault registry, so two nodes in one process no longer share and
    double-count — see install_failover_stats/reset_failover_stats).

    `retries` counts dispatch attempts moved to another replica row
    after a shard row's dispatch failed; `succeeded`/`failed` count how
    those retries resolved. `per_row` breaks the same counts down by
    PHYSICAL replica row (the full-mesh row id, stable across degraded
    repacks): failures attribute to the row whose attempt failed,
    retries/successes to the row retried onto."""

    def __init__(self):
        self.retries = CounterMetric()
        self.succeeded = CounterMetric()
        self.failed = CounterMetric()
        self._rows_mx = threading.Lock()
        self._rows: dict[int, dict[str, CounterMetric]] = {}

    def _row(self, phys_row: int | None) -> dict | None:
        if phys_row is None:
            return None
        with self._rows_mx:
            row = self._rows.get(phys_row)
            if row is None:
                row = {"retries": CounterMetric(),
                       "succeeded": CounterMetric(),
                       "failed": CounterMetric()}
                self._rows[phys_row] = row
            return row

    def record_retry(self, phys_row: int | None = None) -> None:
        self.retries.inc()
        row = self._row(phys_row)
        if row is not None:
            row["retries"].inc()

    def record_succeeded(self, phys_row: int | None = None) -> None:
        self.succeeded.inc()
        row = self._row(phys_row)
        if row is not None:
            row["succeeded"].inc()

    def record_failed(self, phys_row: int | None = None) -> None:
        self.failed.inc()
        row = self._row(phys_row)
        if row is not None:
            row["failed"].inc()

    def snapshot(self) -> dict:
        with self._rows_mx:
            per_row = {str(r): {k: c.count for k, c in row.items()}
                       for r, row in sorted(self._rows.items())}
        return {"retries": self.retries.count,
                "succeeded": self.succeeded.count,
                "failed": self.failed.count,
                "per_row": per_row}


class EvictionStats:
    """Dead-device eviction lifecycle counters (parallel/repack.py) —
    process-wide like FailoverStats and owned/reset the same way.

    `serving_degraded` is a high-water mark of how many replica rows
    were simultaneously evicted (0 = full replication restored)."""

    def __init__(self):
        self.rows_dead = CounterMetric()
        self.repacks = CounterMetric()
        self.swaps = CounterMetric()
        self.re_expansions = CounterMetric()
        self.serving_degraded = HighWaterMetric()

    def snapshot(self) -> dict:
        return {"rows_dead": self.rows_dead.count,
                "repacks": self.repacks.count,
                "swaps": self.swaps.count,
                "re_expansions": self.re_expansions.count,
                "serving_degraded": {
                    "high_water": self.serving_degraded.max,
                    "last": self.serving_degraded.last}}


class MembershipStats:
    """Pod-membership lifecycle counters (parallel/membership.py +
    parallel/multihost.py) — process-wide like FailoverStats and
    owned/reset the same way.

    `joins` counts NEW hosts admitted to the pod; `replacements` the
    subset-like sibling where the joiner takes over a crashed/known
    host id (the kill→replace arc); `drains` graceful decommissions
    (drain_host — planned, distinguished from crash eviction);
    `lease_handoffs` voluntary coordinator-lease transfers (an idle
    holder granting LEASE_RELEASE); `fenced_drivers` exec attempts
    409'd by lease-term fencing (each one is a seq collision the PR 13
    convention would have risked); `partitions_survived` membership
    transitions REFUSED for lack of quorum (a minority half declining
    to fork the pod state — the split-brain that did not happen)."""

    def __init__(self):
        self.joins = CounterMetric()
        self.replacements = CounterMetric()
        self.drains = CounterMetric()
        self.lease_handoffs = CounterMetric()
        self.fenced_drivers = CounterMetric()
        self.partitions_survived = CounterMetric()

    def snapshot(self) -> dict:
        return {"joins": self.joins.count,
                "replacements": self.replacements.count,
                "drains": self.drains.count,
                "lease_handoffs": self.lease_handoffs.count,
                "fenced_drivers": self.fenced_drivers.count,
                "partitions_survived": self.partitions_survived.count}


failover_stats = FailoverStats()
eviction_stats = EvictionStats()
membership_stats = MembershipStats()
# serializes the install/reset pair: two nodes racing init/close could
# otherwise interleave the reads and rebinds and strand one node's
# counters installed under the other's ownership check
_process_stats_mx = threading.Lock()


def install_process_stats() -> tuple[
        FailoverStats, EvictionStats, MembershipStats]:
    """Node-init hook: install FRESH failover/eviction/membership
    counter objects so a new node never inherits (or double-counts
    into) a previous node's counters. Returns the installed triple;
    the node passes it back to reset_process_stats on close."""
    global failover_stats, eviction_stats, membership_stats
    with _process_stats_mx:
        failover_stats = FailoverStats()
        eviction_stats = EvictionStats()
        membership_stats = MembershipStats()
        return failover_stats, eviction_stats, membership_stats


def reset_process_stats(if_owner=None) -> None:
    """Node-close hook, fault-registry convention: reset only while the
    installed objects are still the closing node's (a node must not
    clobber counters someone configured after it)."""
    global failover_stats, eviction_stats, membership_stats
    with _process_stats_mx:
        if if_owner is None or \
                if_owner == (failover_stats, eviction_stats,
                             membership_stats):
            failover_stats = FailoverStats()
            eviction_stats = EvictionStats()
            membership_stats = MembershipStats()


class DispatchStats:
    """Scheduler counters (thread-safe; plumbed into nodes_stats).

    Granularity: `queries` and `coalesced_queries` count PER-SHARD query
    executions (one search against an S-shard index is S entries) —
    the unit the scheduler actually batches and dispatches."""

    def __init__(self):
        self.queries = CounterMetric()
        self.coalesced_queries = CounterMetric()
        self.batches_dispatched = CounterMetric()
        self.pipeline_depth = HighWaterMetric()
        self._window_batches = CounterMetric()
        self._window_coalesced = CounterMetric()
        self._adopted_batches = CounterMetric()
        # traffic control plane (search/traffic.py) — set by the
        # scheduler when a node wires one in; snapshot() then reports
        # per-tenant admission counters, lane depths, the adaptive
        # window, and the query-cache hit rate under "traffic"
        self.traffic = None

    def record_round(self, n_batches: int, windowed: bool) -> None:
        """A drain round merged n_batches callers. `windowed` rounds
        credit the timed window (ES_TPU_COALESCE_WINDOW_MS held the
        leader open); merges in un-windowed rounds are in-flight
        ADOPTION (a batch arrived while a dispatch executed) and are
        counted separately so the window knob's hit rate reflects only
        what the window bought."""
        if windowed:
            self._window_batches.inc(n_batches)
            if n_batches > 1:
                self._window_coalesced.inc(n_batches - 1)
        elif n_batches > 1:
            self._adopted_batches.inc(n_batches - 1)

    def record_groups(self, group_sizes, dispatches: int) -> None:
        self.batches_dispatched.inc(dispatches)
        for sz in group_sizes:
            if sz > 1:
                self.coalesced_queries.inc(sz)

    def snapshot(self) -> dict:
        from ..utils import race_guard, trace_guard
        from .resident import resident_stats
        wb = self._window_batches.count
        wc = self._window_coalesced.count
        snap = {
            "queries": self.queries.count,
            "coalesced_queries": self.coalesced_queries.count,
            "batches_dispatched": self.batches_dispatched.count,
            "pipeline_depth": self.pipeline_depth.max,
            "adopted_batches": self._adopted_batches.count,
            "window": {"batches": wb, "coalesced": wc,
                       "hit_rate": (wc / wb if wb else 0.0)},
            "failover": failover_stats.snapshot(),
            # dead-device eviction lifecycle (parallel/repack.py):
            # rows evicted, degraded repacks, searcher swaps,
            # re-expansions, serving-degraded high-water
            "eviction": eviction_stats.snapshot(),
            # pod-membership lifecycle (parallel/membership.py):
            # joins, replacements, drains, lease handoffs, fenced
            # drivers, partitions survived — all zero single-host
            "membership": membership_stats.snapshot(),
            # resident query loop (search/resident.py): pinned-entry
            # hits, evictions, preemptions, residency bytes — all zero
            # with ES_TPU_RESIDENT_LOOP unset
            "resident": resident_stats(),
        }
        if self.traffic is not None:
            snap["traffic"] = self.traffic.snapshot()
        # runtime hygiene counters (utils/trace_guard.py): present only
        # while the guard is armed, so bench runs report unexpected
        # transfers/recompiles alongside latency without changing the
        # steady-state stats shape
        tg = trace_guard.snapshot()
        if tg is not None:
            snap.update(tg)
        # race sanitizer trips (utils/race_guard.py): same contract —
        # the key exists only while ES_TPU_RACE_GUARD armed it
        rg = race_guard.snapshot()
        if rg is not None:
            snap.update(rg)
        return snap


class _Job:
    """One shard-level search riding a DispatchBatch. `deadline` is an
    absolute time.monotonic() cutoff (None = no deadline): the reader's
    collect phase raises SearchTimeoutError past it, and the caller
    (node._finish_on_readers) converts that into a failed-by-timeout
    shard on a `timed_out: true` response."""

    __slots__ = ("reader", "body", "with_partials", "deadline", "_result",
                 "_error", "_done")

    def __init__(self, reader, body: dict, with_partials: bool,
                 deadline: float | None = None):
        self.reader = reader
        self.body = body
        self.with_partials = with_partials
        self.deadline = deadline
        self._result = None
        self._error = None
        self._done = False

    def result(self) -> dict:
        if not self._done:
            raise RuntimeError(
                "dispatch job collected before batch.dispatch()")
        if self._error is not None:
            raise self._error
        return self._result


class DispatchBatch:
    """One caller's set of shard-level jobs, dispatched as a unit (and
    possibly merged with concurrently-arriving batches). `lane` is the
    priority lane the scheduler drains it from (traffic control plane;
    defaults to interactive — the protected class)."""

    def __init__(self, scheduler: "DispatchScheduler",
                 lane: str = "interactive"):
        self._scheduler = scheduler
        self.lane = lane
        self.jobs: list[_Job] = []
        self._done = threading.Event()

    def submit(self, reader, body: dict, with_partials: bool = False,
               deadline: float | None = None) -> _Job:
        job = _Job(reader, body, with_partials, deadline)
        self.jobs.append(job)
        return job

    def dispatch(self) -> None:
        """Execute every submitted job; per-job errors are re-raised by
        job.result(), never by dispatch() itself."""
        if not self.jobs:
            self._done.set()
            return
        self._scheduler.run(self)


class DispatchScheduler:
    """Leader-drain scheduler over DispatchBatches (see module doc)."""

    def __init__(self, window_ms: float = 0.0, traffic=None):
        from ..utils import race_guard
        self._mx = threading.Lock()
        # graftlint: ok(lock-discipline): serialization latch, not a data
        # lock — the leader HOLDS it across the coalescing window sleep
        # and the drain's dispatch/collect by design; waiters are exactly
        # the batches the drain is executing, parked on batch._done
        self._leader = threading.Lock()
        self._pending: list[DispatchBatch] = race_guard.guarded_list(
            self._mx, "dispatch.DispatchScheduler._pending")
        self._window_default = float(window_ms)
        # traffic control plane (search/traffic.py): lane quotas for the
        # weighted drain, the adaptive coalescing window, and the stats
        # surface. None = legacy single-FIFO behavior (static window
        # only), so scheduler unit tests need no controller.
        self._traffic = traffic
        self.stats = DispatchStats()
        self.stats.traffic = traffic

    def batch(self, lane: str = "interactive") -> DispatchBatch:
        return DispatchBatch(self, lane=lane)

    def window_ms(self) -> float:
        """Effective coalescing window for THIS drain. Precedence: the
        env override (explicit operator knob), then a non-zero static
        setting, then the traffic controller's adaptive window (0 when
        traffic is sequential or the controller is absent)."""
        raw = os.environ.get("ES_TPU_COALESCE_WINDOW_MS")
        if raw not in (None, ""):
            try:
                return float(raw)
            except ValueError:
                pass
        if self._window_default > 0:
            return self._window_default
        if self._traffic is not None:
            return self._traffic.window.window_ms()
        return self._window_default

    # -- core --------------------------------------------------------------
    def run(self, batch: DispatchBatch) -> None:
        with self._mx:
            self._pending.append(batch)
            lane_depth = sum(1 for b in self._pending
                             if b.lane == batch.lane)
        if self._traffic is not None:
            self._traffic.note_lane_depth(batch.lane, lane_depth)
            self._traffic.window.observe_arrival()
        if self._leader.acquire(blocking=False):
            try:
                w = self.window_ms()
                if w > 0:
                    # hold the door for concurrent REST traffic that
                    # would otherwise just miss this drain (static: the
                    # operator asked; adaptive: the controller predicts
                    # another arrival inside the window)
                    time.sleep(w / 1000.0)
                self._drain(windowed=w > 0, until=batch)
            finally:
                self._leader.release()
        # a leader was mid-flight: it adopts this batch in a coming
        # round. Wait on COMPLETION, not on the leader lock — with
        # priority lanes the leader may keep draining a deep bulk
        # backlog long after this batch's round finished, and an
        # interactive caller must return the moment its own round
        # completes. The timed re-check only closes the rare
        # enqueue/last-take race (a leader exited without seeing this
        # batch): the first retry comes fast, then the poll backs off
        # so a deep backlog of waiting callers is not a wakeup storm.
        poll_s = 0.001
        while not batch._done.wait(timeout=poll_s):
            if self._leader.acquire(blocking=False):
                try:
                    self._drain(windowed=False, until=batch)
                finally:
                    self._leader.release()
            poll_s = 0.05

    def _lane_quota(self, lane: str) -> int | None:
        if lane == "interactive":
            return None  # the protected class is never capped
        if self._traffic is not None:
            return self._traffic.lane_quota(lane)
        return None  # no controller: legacy single-FIFO drain

    def _take_round_locked(self) -> list[DispatchBatch]:
        """One drain round: ALL interactive batches plus up to the
        per-lane quota from each other lane, in lane priority order
        (FIFO within a lane — Python's sort is stable). Leftovers stay
        pending for the next round, where freshly-arrived interactive
        batches again outrank them."""
        if not self._pending:
            return []
        from .traffic import lane_priority
        ordered = sorted(self._pending, key=lambda b: lane_priority(b.lane))
        take: list[DispatchBatch] = []
        leftover: list[DispatchBatch] = []
        counts: dict[str, int] = {}
        for b in ordered:
            q = self._lane_quota(b.lane)
            c = counts.get(b.lane, 0)
            if q is not None and c >= q:
                leftover.append(b)
            else:
                counts[b.lane] = c + 1
                take.append(b)
        # leftovers keep within-lane FIFO order (the sort above is
        # stable); new arrivals append after them under the same lock.
        # In-place (not a rebind): the list is a race_guard-declared
        # structure and must keep its guard for the process lifetime
        self._pending[:] = leftover
        return take

    def _drain(self, windowed: bool = False,
               until: "DispatchBatch | None" = None) -> None:
        """Drain rounds until nothing is pending — or, when `until` is
        given, until that batch's round has completed. The early exit
        keeps a drain leader's OWN latency bounded under a sustained
        over-quota flood (leftover rounds would otherwise pin an
        interactive caller's thread for the flood's duration); every
        leftover batch has its own caller parked in run(), whose timed
        leader re-check picks the backlog up within one poll."""
        first = True
        while True:
            if until is not None and until._done.is_set():
                return
            with self._mx:
                round_ = self._take_round_locked()
            if not round_:
                return
            # only the FIRST round's merges were bought by the timed
            # window; later rounds of the same drain are in-flight
            # adoption like any un-windowed leader's
            self.stats.record_round(len(round_), windowed and first)
            if self._traffic is not None:
                self._traffic.window.observe_round(len(round_))
            first = False
            try:
                self._execute([j for b in round_ for j in b.jobs])
            finally:
                for b in round_:
                    b._done.set()

    # -- execution ---------------------------------------------------------
    @staticmethod
    def _deadline_kw(g: list[_Job]) -> dict:
        """Deadline kwargs for a coalesced group's reader call — empty
        when no deadline, so plain mock readers without the kwarg keep
        working. Grouping buckets deadlines to 10 ms (see _execute), so
        members differ by less than a bucket; the LATEST wins — a
        cooperative timeout may fire a few ms late but must never fail
        a request before its own deadline."""
        if g[0].deadline is None:
            return {}
        return {"deadline": max(j.deadline for j in g)}

    def _fail_or_isolate(self, g: list[_Job], e: Exception) -> None:
        """A group's shared execution failed: retry singly so
        batch-mates survive one bad body — EXCEPT on deadline exits,
        where re-dispatching cannot succeed (the deadline won't
        un-pass) and only burns device time the laggard already
        wasted."""
        if isinstance(e, SearchTimeoutError):
            for j in g:
                j._error = e
                j._done = True
        else:
            self._run_isolated(g)

    def _execute(self, jobs: list[_Job]) -> None:
        self.stats.queries.inc(len(jobs))
        groups: dict[tuple, list[_Job]] = {}
        order: list[tuple] = []
        for j in jobs:
            # deadlines bucket at 10 ms rather than keying raw floats:
            # msearch items sharing one `timeout` compute deadlines
            # microseconds apart, and exact-float keys would put every
            # job in its own group — silently disabling coalescing for
            # any deadline-carrying traffic. Different timeout ORDERS
            # (100ms vs 10s) still split, as they must.
            dkey = (None if j.deadline is None
                    else int(j.deadline * 100))
            key = (id(j.reader), j.with_partials, dkey)
            g = groups.get(key)
            if g is None:
                groups[key] = g = []
                order.append(key)
            g.append(j)
        if len(order) == 1:
            # single target: the plain synchronous reader path (same
            # signature-grouped batching inside, nothing to pipeline)
            self._run_sync(groups[order[0]])
            return
        # pipelined: enqueue EVERY group's device programs back-to-back
        # through the reader's non-syncing submit, then collect in
        # submission order — round trips overlap instead of serializing
        pendings = []
        for key in order:
            g = groups[key]
            if not hasattr(g[0].reader, "msearch_submit"):
                # reader without a split entry (plain mock / legacy):
                # sync per-group, still batched within the reader — and
                # never let a missing interface masquerade as a parse
                # error in the isolated fallback
                self._run_sync(g)
                continue
            try:
                pend = g[0].reader.msearch_submit(
                    [j.body for j in g], g[0].with_partials,
                    **self._deadline_kw(g))
            except Exception:  # noqa: BLE001 — submit-time (parse) error
                self._run_isolated(g)
                continue
            pendings.append((g, pend))
        # depth = device programs enqueued before the first collection —
        # the number of tunnel round trips actually overlapped
        self.stats.pipeline_depth.record(
            sum(p.dispatch_count for _g, p in pendings))
        for g, pend in pendings:
            try:
                rs = pend.finish()
            except Exception as e:  # noqa: BLE001 — one bad body fails
                # the shared program (see _fail_or_isolate)
                self._fail_or_isolate(g, e)
                continue
            for j, r in zip(g, rs):
                j._result = r
                j._done = True
            self.stats.record_groups(pend.group_sizes,
                                     pend.dispatch_count)
        for j in jobs:  # backstop: no job may leave undecided
            if not j._done:
                j._error = RuntimeError("dispatch job was not executed")
                j._done = True

    def _run_sync(self, g: list[_Job]) -> None:
        reader = g[0].reader
        submit_stats.value = None
        try:
            rs = reader.msearch([j.body for j in g], g[0].with_partials,
                                **self._deadline_kw(g))
        except Exception as e:  # noqa: BLE001
            self._fail_or_isolate(g, e)
            return
        for j, r in zip(g, rs):
            j._result = r
            j._done = True
        sub = getattr(submit_stats, "value", None)
        if sub is not None:
            # msearch_submit enqueued every group x segment program
            # before its finish collected any — that WAS the in-flight
            # depth, even through the sync wrapper
            self.stats.pipeline_depth.record(sub[1])
            self.stats.record_groups(*sub)
        else:
            self.stats.pipeline_depth.record(1)

    def _run_isolated(self, g: list[_Job]) -> None:
        """Per-job fallback: each body runs alone so only the bad one
        errors (batch-mates must not inherit a stranger's 400)."""
        for j in g:
            if j._done:
                continue
            try:
                kw = {} if j.deadline is None else {"deadline": j.deadline}
                j._result = j.reader.msearch([j.body], j.with_partials,
                                             **kw)[0]
            except Exception as e:  # noqa: BLE001
                j._error = e
            j._done = True
