"""Resident query loop: pinned on-device executables + staged feeds.

The dispatch scheduler (PR 3) amortized the flat per-dispatch tunnel
round trip across CONCURRENT traffic; a truly lone query still paid one
full synchronous dispatch — jit-dispatch overhead, param upload, program
launch, result fetch, all serialized. This module keeps the read path's
hot programs RESIDENT instead: per `(pack fingerprint, plan signature,
pow2 k-bucket, batch bucket)` the executor AOT-compiles the fused
stepped program once (``jax.jit(...).lower().compile()``), pins the
executable here, and serves every later call through it with

  * an asynchronously ``jax.device_put``-staged query-param wire buffer
    (DONATED to the executable, so XLA reuses its memory) that lands
    while earlier enqueued work executes — the feed stage;
  * the pinned executable invocation — the execute stage;
  * an async copy-to-host started at enqueue — the fetch stage;

so a lone query pays a one-way param feed + result fetch instead of a
monolithic round trip. The stepped program additionally carries a
device-side deadline check per tile-loop chunk (see ops/scoring.py
``step``), which turns PR 4's cooperative collect-boundary timeout into
a preemptive one: a laggard step exits early and reports ``timed_out``
from the device. BOTH fused engines step: an XLA-tuned shape pins the
chunked fori tile loop, a pallas-tuned shape pins the chunked
``pallas_call`` grid (ops/pallas_scoring — threshold and prune state
carried across kernel-chunk boundaries, the deadline callback hosted
between chunks), so pallas-tuned packs no longer fall back to cold
dispatch; the entry key carries the engine.

Residency is opt-in via ``ES_TPU_RESIDENT_LOOP`` (unset => every
response stays byte-identical to the cold path and all counters here
read zero). ``search.resident.max_entries`` /
``ES_TPU_RESIDENT_MAX_ENTRIES`` cap the pinned-entry LRU. Stats surface
under ``nodes_stats()["dispatch"]["resident"]``.
"""

from __future__ import annotations

import os
import threading
import weakref

from ..utils.metrics import CounterMetric, HighWaterMetric

_TRUE = ("1", "true", "on", "yes")

DEFAULT_MAX_ENTRIES = 32


def enabled() -> bool:
    """Residency is an explicit opt-in: with the env unset the read
    path never touches this module's caches or counters."""
    return os.environ.get("ES_TPU_RESIDENT_LOOP", "").lower() in _TRUE


def default_max_entries() -> int:
    try:
        return int(os.environ.get("ES_TPU_RESIDENT_MAX_ENTRIES",
                                  str(DEFAULT_MAX_ENTRIES)))
    except ValueError:
        return DEFAULT_MAX_ENTRIES


class ResidentStats:
    """Process-wide resident-loop counters (the executor serves every
    node in the process, like the fused-scoring stats)."""

    def __init__(self):
        self.resident_hits = CounterMetric()
        self.cold_dispatches = CounterMetric()
        self.evictions = CounterMetric()
        self.preempted_by_deadline = CounterMetric()
        # streaming write path (index/engine.py delta mode): entries a
        # refresh REUSED across a delta-epoch bump (the refresh-storm
        # fix made provable from stats — each count is one avoided
        # recompile+retune), and entries evicted because a background
        # compaction re-keyed their generation (the only event allowed
        # to evict on the write path)
        self.refresh_reuses = CounterMetric()
        self.compaction_evictions = CounterMetric()
        # how long a staged param feed had to land on-device before its
        # step was invoked (ms, high-water) — the overlap the split
        # feed/execute/fetch pipeline buys over a monolithic dispatch
        self.staged_feed_overlap_ms = HighWaterMetric()

    def snapshot(self, cache: "ResidentCache") -> dict:
        return {
            "resident_hits": self.resident_hits.count,
            "cold_dispatches": self.cold_dispatches.count,
            "evictions": self.evictions.count,
            "preempted_by_deadline": self.preempted_by_deadline.count,
            "refresh_reuses": self.refresh_reuses.count,
            "compaction_evictions": self.compaction_evictions.count,
            "staged_feed_overlap_ms": {
                "high_water": round(
                    float(self.staged_feed_overlap_ms.max), 3),
                "last": round(float(self.staged_feed_overlap_ms.last), 3),
            },
            **cache.snapshot(),
        }


class ResidentEntry:
    """One pinned executable + its feed slot.

    ``nbytes`` is the entry's residency footprint (staged wire + queued
    output buffers + generated code where the backend reports it); the
    cache accounts it against the fielddata breaker for the life of the
    entry — pinned executables are long-lived HBM tenants exactly like
    uploaded columns, and must be visible to the same parent budget."""

    __slots__ = ("key", "label", "compiled", "seg_id", "fingerprint",
                 "seg_ref", "backend", "generation", "delta_epoch",
                 "nbytes", "hits", "_hold", "__weakref__")

    def __init__(self, key, label: str, compiled, seg_id, fingerprint,
                 seg_ref, backend: str = "xla",
                 generation: str | None = None, delta_epoch: int = 0):
        self.key = key
        self.label = label
        self.compiled = compiled
        self.seg_id = seg_id
        self.fingerprint = fingerprint
        self.seg_ref = seg_ref
        self.backend = backend
        # streaming write path: `generation` is the Segment.cache_key
        # the entry is pinned under ("delta(<base>):c<cap>" for delta
        # entries — no seg_ref, survives epoch bumps, evicted only by
        # compaction); `delta_epoch` is the LAST epoch served, advanced
        # by ResidentCache.get so refresh reuse is countable
        self.generation = generation if generation is not None \
            else fingerprint
        self.delta_epoch = delta_epoch
        self.nbytes = 0
        self.hits = 0
        self._hold = 0

    def account(self, nbytes: int) -> None:
        """Record the entry's residency bytes (known after the first
        execution) against the fielddata breaker."""
        if nbytes <= self._hold:
            return
        from ..utils.breaker import breaker_service
        add = nbytes - self._hold
        breaker_service().breaker("fielddata").add_estimate(add)
        self._hold = nbytes
        self.nbytes = nbytes

    def release(self) -> None:
        if self._hold:
            from ..utils.breaker import breaker_service
            breaker_service().breaker("fielddata").release(self._hold)
            self._hold = 0


class ResidentCache:
    """LRU of pinned entries. Keys embed the pack FINGERPRINT, so a
    refresh/merge (which mints a new fingerprint) can never serve a
    stale executable; the stale entry itself is evicted by the dead-
    segment sweep (entries hold only a weakref to their segment) or by
    the LRU cap, releasing its breaker hold."""

    def __init__(self, max_entries: int | None = None):
        from ..utils import race_guard
        self._mx = threading.Lock()
        # key -> ResidentEntry (LRU order)
        self._entries: dict = race_guard.guarded_dict(
            self._mx, "resident.ResidentCache._entries")
        self.max_entries = max_entries or default_max_entries()

    def configure(self, max_entries: int) -> None:
        with self._mx:
            self.max_entries = max(1, int(max_entries))
            self._trim_locked()

    def get(self, key, delta_epoch: int | None = None
            ) -> ResidentEntry | None:
        with self._mx:
            e = self._entries.pop(key, None)
            if e is None:
                return None
            self._entries[key] = e            # LRU touch
            e.hits += 1
            stats.resident_hits.inc()
            if delta_epoch is not None and delta_epoch != e.delta_epoch:
                # the pinned executable survived a refresh's epoch bump
                # and now serves the NEW delta contents — the zero-
                # eviction refresh, made countable
                stats.refresh_reuses.inc()
                e.delta_epoch = delta_epoch
            return e

    def put(self, entry: ResidentEntry) -> None:
        with self._mx:
            self._sweep_locked()
            # two threads racing the same cold compile: the displaced
            # duplicate must drop its breaker hold (not an eviction —
            # the plan stays resident under the winner)
            old = self._entries.pop(entry.key, None)
            if old is not None and old is not entry:
                old.release()
            self._entries[entry.key] = entry
            self._trim_locked()

    def evict(self, key) -> None:
        """Evict one entry (e.g. its residency bytes tripped the
        fielddata breaker at accounting time)."""
        with self._mx:
            self._evict_locked(key)

    def _evict_locked(self, key) -> None:
        # drop the cache's reference only — a thread that looked the
        # entry up just before the eviction may still be mid-invoke, so
        # the executable itself dies with its last reference
        e = self._entries.pop(key, None)
        if e is not None:
            e.release()
            stats.evictions.inc()

    def _trim_locked(self) -> None:
        while len(self._entries) > self.max_entries:
            self._evict_locked(next(iter(self._entries)))

    def _sweep_locked(self) -> None:
        """Drop entries whose segment died (pack refresh/merge replaced
        it): a dead segment's executable pins unreachable device columns
        and can never be keyed again (the fingerprint changed)."""
        dead = [k for k, e in self._entries.items()
                if e.seg_ref is not None and e.seg_ref() is None]
        for k in dead:
            self._evict_locked(k)

    def evict_segment(self, seg_id) -> None:
        """Explicit invalidation (Segment.drop_device / cache clear):
        the pinned executables reference the dropped device columns and
        must not outlive them."""
        with self._mx:
            for k in [k for k, e in self._entries.items()
                      if e.seg_id == seg_id]:
                self._evict_locked(k)

    def evict_generation(self, gen_prefix: str) -> int:
        """Compaction re-key (index/engine.Engine._compact_now): drop
        every entry pinned under a generation key starting with
        `gen_prefix` (a compaction retires EVERY capacity bucket of the
        folded delta, so this matches on the "delta(<base>)" prefix).
        Returns how many entries were evicted; they also count in the
        compaction_evictions stat — rare and background by design."""
        with self._mx:
            dead = [k for k, e in self._entries.items()
                    if isinstance(e.generation, str)
                    and e.generation.startswith(gen_prefix)]
            for k in dead:
                self._evict_locked(k)
        if dead:
            stats.compaction_evictions.inc(len(dead))
        return len(dead)

    def clear(self) -> None:
        with self._mx:
            for k in list(self._entries):
                self._evict_locked(k)

    def snapshot(self) -> dict:
        with self._mx:
            entries = [{"plan": e.label, "fingerprint": e.fingerprint,
                        "backend": e.backend, "bytes": e.nbytes,
                        "hits": e.hits, "generation": e.generation,
                        "delta_epoch": e.delta_epoch}
                       for e in self._entries.values()]
            max_entries = self.max_entries
        return {"entries": entries,
                "entry_count": len(entries),
                "max_entries": max_entries,
                "residency_bytes": sum(e["bytes"] for e in entries)}


stats = ResidentStats()
cache = ResidentCache()


def configure(max_entries: int | None = None) -> None:
    """Node startup hook (`search.resident.max_entries`). The cache is
    process-global, so with several in-process nodes the last
    configuration wins — same convention as the breaker service."""
    if max_entries is not None:
        cache.configure(max_entries)


def evict_segment(seg_id) -> None:
    cache.evict_segment(seg_id)


def evict_generation(gen_prefix: str) -> int:
    """Compaction hook (index/engine.py): retire every pinned entry of
    a folded delta generation. The ONLY write-path event that evicts."""
    return cache.evict_generation(gen_prefix)


def evict_segments(seg_ids) -> None:
    """Batch invalidation for a retired pack's segments (the elastic
    repack swap, parallel/repack.py): the old pack's pinned executables
    reference device columns the swap just retired — reclaim them NOW
    instead of waiting for the weakref sweep."""
    for sid in seg_ids:
        cache.evict_segment(sid)


def note_mesh_programs_dropped(n: int) -> None:
    """A retired DistributedSearcher's pinned shard_map programs died
    with the instance (its `_compiled` cache IS the mesh's resident
    entry table). Counted as evictions through the same counters the
    mesh reports reuse through — and, like them, only while residency
    is enabled (counters read zero otherwise)."""
    if n > 0 and enabled():
        stats.evictions.inc(n)


def reset() -> None:
    """Test hook: drop every pinned entry, zero the counters, restore
    the default entry cap."""
    global stats
    cache.clear()
    cache.configure(default_max_entries())
    # graftlint: ok(shared-state-race): test-only hook, called between
    # requests with no dispatch in flight; the rebind itself is atomic
    stats = ResidentStats()


def resident_stats() -> dict:
    """Snapshot for nodes_stats()["dispatch"]["resident"]."""
    return stats.snapshot(cache)


def make_ref(segment) -> weakref.ref | None:
    try:
        return weakref.ref(segment)
    except TypeError:
        return None
