"""Query DSL: JSON -> query AST.

Reference analog: index/query/ (157 files of paired Parser/Builder
classes registered in IndexQueryParserService.java). Here the DSL parses
into a small frozen AST; compound queries desugar into the three
primitives the device executor evaluates:

  * scored term clauses over text postings (scatter-add of eager impacts)
  * dense column predicates (keyword ordinal compare, numeric range,
    exists, ids)
  * bool combination (must/should/must_not/filter + minimum_should_match)

`match` -> bool over analyzed terms; `terms` -> bool should; etc. This
mirrors how Lucene rewrites high-level queries, but the rewrite target is
a dense-tensor plan instead of BooleanQuery/TermQuery objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Sequence

from ..utils.errors import QueryParsingError
from ..index.mapping import MapperService


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Query:
    pass


@dataclass(frozen=True)
class MatchAllQuery(Query):
    boost: float = 1.0


@dataclass(frozen=True)
class MatchNoneQuery(Query):
    pass


@dataclass(frozen=True)
class TermQuery(Query):
    """Exact term; binds to text postings or keyword ordinal compare.
    Ref: index/query/TermQueryParser.java."""

    field: str
    value: object
    boost: float = 1.0


@dataclass(frozen=True)
class RangeQuery(Query):
    """Ref: index/query/RangeQueryParser.java."""

    field: str
    gte: object = None
    gt: object = None
    lte: object = None
    lt: object = None
    boost: float = 1.0


@dataclass(frozen=True)
class ExistsQuery(Query):
    """Ref: index/query/ExistsFilterParser.java."""

    field: str


@dataclass(frozen=True)
class IdsQuery(Query):
    """Ref: index/query/IdsQueryParser.java."""

    values: tuple[str, ...]


@dataclass(frozen=True)
class PrefixQuery(Query):
    """Ref: index/query/PrefixQueryParser.java. Binds by expanding against
    the segment term dictionary (sorted -> range of terms)."""

    field: str
    value: str
    boost: float = 1.0
    max_expansions: int = 128


@dataclass(frozen=True)
class WildcardQuery(Query):
    """Ref: index/query/WildcardQueryParser.java. Expanded host-side
    against the term dictionary."""

    field: str
    value: str
    boost: float = 1.0
    max_expansions: int = 128


@dataclass(frozen=True)
class FuzzyQuery(Query):
    """Ref: index/query/FuzzyQueryParser.java; edit-distance expansion."""

    field: str
    value: str
    fuzziness: int = 2
    boost: float = 1.0
    max_expansions: int = 50


@dataclass(frozen=True)
class PhraseQuery(Query):
    """Positional phrase. tid resolution happens at bind time; terms here
    are analyzed tokens in order. prefix_last expands the final term
    against the term dictionary (match_phrase_prefix). Ref:
    index/query/MatchQueryParser.java (type=phrase / phrase_prefix),
    Lucene PhraseQuery."""

    field: str
    terms: tuple[str, ...]
    slop: int = 0
    boost: float = 1.0
    prefix_last: bool = False
    max_expansions: int = 50


@dataclass(frozen=True)
class BM25FQuery(Query):
    """multi_match type=cross_fields as true BM25F: the analyzed terms
    score against a single virtual document — shared per-term IDF
    (rarest interpretation: max df across fields), per-field weighted
    term frequency and length normalization, ONE BM25 saturation across
    fields. fields is ((name, weight), ...) from the `f^w` syntax. Ref:
    index/query/MultiMatchQueryParser.java (cross_fields),
    Lucene BM25FQuery / combined_fields."""

    fields: tuple[tuple[str, float], ...]
    terms: tuple[str, ...]
    boost: float = 1.0


@dataclass(frozen=True)
class RegexpQuery(Query):
    """Ref: index/query/RegexpQueryParser.java — expanded host-side
    against the sorted term dictionary."""

    field: str
    value: str
    boost: float = 1.0
    max_expansions: int = 128


@dataclass(frozen=True)
class SpanTermQuery(Query):
    """Ref: index/query/SpanTermQueryParser.java."""

    field: str
    value: str
    boost: float = 1.0


@dataclass(frozen=True)
class SpanNearQuery(Query):
    """Ref: index/query/SpanNearQueryParser.java."""

    clauses: tuple[Query, ...]
    slop: int = 0
    in_order: bool = True
    boost: float = 1.0


@dataclass(frozen=True)
class SpanOrQuery(Query):
    """Ref: index/query/SpanOrQueryParser.java."""

    clauses: tuple[Query, ...]
    boost: float = 1.0


@dataclass(frozen=True)
class SpanFirstQuery(Query):
    """Ref: index/query/SpanFirstQueryParser.java."""

    match: Query
    end: int
    boost: float = 1.0


@dataclass(frozen=True)
class SpanNotQuery(Query):
    """Ref: index/query/SpanNotQueryParser.java."""

    include: Query
    exclude: Query
    pre: int = 0
    post: int = 0
    boost: float = 1.0


@dataclass(frozen=True)
class NestedQuery(Query):
    """Block-join child query projected to parents. Ref:
    index/query/NestedQueryParser.java (ToParentBlockJoinQuery)."""

    path: str
    query: Query
    score_mode: str = "avg"    # none|sum|avg|max|min
    boost: float = 1.0


@dataclass(frozen=True)
class ParentsMatchQuery(Query):
    """Internal: nested rows whose parent matches `query` — the scope
    filter for nested aggregations (NestedAggregator's parentDocs)."""

    query: Query


@dataclass(frozen=True)
class MoreLikeThisQuery(Query):
    """Ref: index/query/MoreLikeThisQueryParser.java + Lucene
    MoreLikeThis term selection (tf-idf ranked interesting terms). Term
    selection is per-segment at bind time so df statistics are real."""

    fields: tuple[str, ...]
    like_texts: tuple[str, ...]            # analyzed at bind time
    unlike_texts: tuple[str, ...] = ()     # ignore_like/unlike exclusion
    exclude_ids: tuple[str, ...] = ()      # the "like" docs themselves
    min_term_freq: int = 2
    min_doc_freq: int = 5
    max_query_terms: int = 25
    minimum_should_match: str = "30%"
    boost: float = 1.0


@dataclass(frozen=True)
class BoolQuery(Query):
    """Ref: index/query/BoolQueryParser.java."""

    must: tuple[Query, ...] = ()
    should: tuple[Query, ...] = ()
    must_not: tuple[Query, ...] = ()
    filter: tuple[Query, ...] = ()
    minimum_should_match: int | None = None
    boost: float = 1.0


@dataclass(frozen=True)
class ConstantScoreQuery(Query):
    """Ref: index/query/ConstantScoreQueryParser.java."""

    query: Query
    boost: float = 1.0


@dataclass(frozen=True)
class KnnQuery(Query):
    """Vector similarity as a SCORING CLAUSE: every live doc carrying a
    vector matches, scored by the field similarity's transformed value
    (ops/knn.knn_score_column) times `boost`. Composable anywhere a
    query is (bool must/should, function_score...), which is what lets
    a hybrid BM25+vector search serve as ONE fused device dispatch —
    the executor admits it into the fused clause bundle
    (search/executor._fused_plan_bundle). The top-level `knn` search
    section rewrites onto this node (shard_searcher.rewrite_knn_body).
    Ref: modern ES knn query (approximate in ES; exact-per-doc here,
    the coarse IVF stage lives in the pure-knn path instead)."""

    field: str
    vector: tuple[float, ...] = ()
    boost: float = 1.0


@dataclass(frozen=True)
class GeoDistanceQuery(Query):
    """Docs within `distance_m` meters of (lat, lon). Ref:
    index/query/GeoDistanceQueryParser.java / GeoDistanceRangeQueryParser
    (from_m > 0 makes it a ring). Filter context: constant score."""

    field: str
    lat: float
    lon: float
    distance_m: float
    from_m: float = 0.0
    boost: float = 1.0


@dataclass(frozen=True)
class GeoBoundingBoxQuery(Query):
    """Ref: index/query/GeoBoundingBoxQueryParser.java. Handles the
    date-line crossing case (left > right)."""

    field: str
    top: float
    left: float
    bottom: float
    right: float
    boost: float = 1.0


@dataclass(frozen=True)
class GeoPolygonQuery(Query):
    """Ref: index/query/GeoPolygonQueryParser.java — point-in-polygon by
    ray casting over the vertex list."""

    field: str
    points: tuple  # ((lat, lon), ...)
    boost: float = 1.0


@dataclass(frozen=True)
class GeoShapeQuery(Query):
    """Ref: index/query/GeoShapeQueryParser.java. `shape_json` is the
    GeoJSON shape serialized to a canonical string (keeps the node
    hashable for plan signatures); relation is intersects | disjoint |
    within. Rasterization onto the field's prefix tree happens at bind
    time (ops/geo_shape.py)."""

    field: str
    shape_json: str
    relation: str = "intersects"
    boost: float = 1.0


@dataclass(frozen=True)
class ShapeTokensQuery(Query):
    """Internal: constant-score disjunction over prefix-tree cell tokens
    of a geo_shape field (the bind target GeoShapeQuery decomposes
    into)."""

    field: str
    tokens: tuple[str, ...]
    boost: float = 1.0


@dataclass(frozen=True)
class ScriptQuery(Query):
    """Script filter: matches docs where the expression is truthy.
    Ref: index/query/ScriptQueryParser.java (filter context; constant
    score)."""

    script: str
    params: tuple = ()             # sorted ((name, value), ...)
    boost: float = 1.0


@dataclass(frozen=True)
class ScoreFunction:
    """One scoring function. Ref: index/query/functionscore/ —
    weight (WeightBuilder), field_value_factor
    (FieldValueFactorFunctionParser), random_score
    (RandomScoreFunctionParser), gauss/exp/linear decay
    (DecayFunctionParser)."""

    kind: str                      # weight|field_value_factor|random_score|
                                   # gauss|exp|linear
    field: str | None = None
    weight: float = 1.0
    filter: "Query | None" = None
    # field_value_factor
    factor: float = 1.0
    modifier: str = "none"
    missing: float = 0.0
    # random_score
    seed: int = 0
    # decay
    origin: object = None
    scale: object = None
    offset: object = 0
    decay: float = 0.5
    # script_score
    script: str | None = None
    script_params: tuple = ()      # sorted ((name, value), ...)


@dataclass(frozen=True)
class FunctionScoreQuery(Query):
    """Ref: index/query/functionscore/FunctionScoreQueryParser.java."""

    query: Query
    functions: tuple[ScoreFunction, ...] = ()
    score_mode: str = "multiply"   # multiply|sum|avg|max|min|first
    boost_mode: str = "multiply"   # multiply|replace|sum|avg|max|min
    max_boost: float = float("inf")
    min_score: float | None = None
    boost: float = 1.0


@dataclass(frozen=True)
class BoostingQuery(Query):
    """Ref: index/query/BoostingQueryParser.java — positive scores minus
    demoted negative matches."""

    positive: Query
    negative: Query
    negative_boost: float = 0.2


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


def _dotted_get(obj: dict, path: str):
    cur = obj
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _single_entry(obj: dict, ctx: str) -> tuple[str, object]:
    if not isinstance(obj, dict) or len(obj) != 1:
        raise QueryParsingError(f"[{ctx}] expected an object with a single key, got {obj!r}")
    return next(iter(obj.items()))


def resolve_msm(value, n_optional: int) -> int | None:
    """minimum_should_match forms: int, "3", "75%", "-25%" (ref:
    common/lucene/search/Queries.calculateMinShouldMatch)."""
    if value is None:
        return None
    s = str(value).strip()
    try:
        if s.endswith("%"):
            pct = float(s[:-1])
            if pct < 0:
                return max(n_optional - int(n_optional * -pct / 100.0), 0)
            return int(n_optional * pct / 100.0)
        return int(s)
    except ValueError:
        raise QueryParsingError(f"failed to parse minimum_should_match [{value}]")


# plugin-registered query parsers: name -> fn(parser, body) -> Query
# (ref: indices/query/IndicesQueriesModule.java addQuery — the
# extension point query plugins use; see plugins.py)
CUSTOM_QUERY_PARSERS: dict[str, Callable] = {}


class QueryParser:
    """JSON query dict -> AST. Needs the mapper for `match` analysis.

    Ref: index/query/IndexQueryParserService.java dispatching to the
    registered *Parser classes by key.
    """

    def __init__(self, mapper_service: MapperService,
                 index_name: str | None = None,
                 doc_lookup=None):
        """doc_lookup: optional callable doc_id -> source dict | None,
        used by more_like_this to resolve `like` documents; index_name
        feeds the `indices` query."""
        self.mappers = mapper_service
        self.index_name = index_name
        self.doc_lookup = doc_lookup

    def parse(self, query: dict | None) -> Query:
        if query is None or query == {}:
            return MatchAllQuery()
        name, body = _single_entry(query, "query")
        handler = getattr(self, f"_parse_{name}", None)
        if handler is None:
            custom = CUSTOM_QUERY_PARSERS.get(name)
            if custom is not None:
                return custom(self, body)
            raise QueryParsingError(f"no query registered for [{name}]")
        return handler(body)

    # -- leaf queries ------------------------------------------------------

    def _parse_match_all(self, body) -> Query:
        return MatchAllQuery(boost=float((body or {}).get("boost", 1.0)))

    def _parse_match_none(self, body) -> Query:
        return MatchNoneQuery()

    @staticmethod
    def _id_values(fld: str, values) -> tuple[str, ...]:
        """term/terms on the _id/_uid metadata fields become doc-id
        lookups (ref: index/mapper/internal/IdFieldMapper.termQuery
        delegating to _uid); _uid values are "type#id"."""
        out = []
        for v in values:
            sv = str(v)
            if fld == "_uid" and "#" in sv:
                sv = sv.split("#", 1)[1]
            out.append(sv)
        return tuple(out)

    def _parse_term(self, body) -> Query:
        fld, spec = _single_entry(body, "term")
        value = spec.get("value") if isinstance(spec, dict) else spec
        if fld in ("_id", "_uid"):
            return IdsQuery(self._id_values(fld, [value]))
        if isinstance(spec, dict):
            return TermQuery(fld, value, float(spec.get("boost", 1.0)))
        return TermQuery(fld, spec)

    def _parse_terms(self, body) -> Query:
        body = dict(body)
        boost = float(body.pop("boost", 1.0))
        body.pop("minimum_should_match", None)
        fld, values = _single_entry(body, "terms")
        if not isinstance(values, (list, tuple)):
            raise QueryParsingError("[terms] values must be an array")
        if fld in ("_id", "_uid"):
            return IdsQuery(self._id_values(fld, values))
        return BoolQuery(
            should=tuple(TermQuery(fld, v) for v in values),
            minimum_should_match=1, boost=boost)

    def _parse_match(self, body) -> Query:
        fld, spec = _single_entry(body, "match")
        if isinstance(spec, dict):
            # ES 2.0 match type=phrase/phrase_prefix
            # (ref: MatchQueryParser.java "type" element)
            mtype = str(spec.get("type", "boolean")).lower()
            if mtype in ("phrase", "phrase_prefix"):
                return self._phrase({fld: spec}, fld,
                                    prefix_last=mtype == "phrase_prefix")
            text = spec.get("query")
            operator = str(spec.get("operator", "or")).lower()
            boost = float(spec.get("boost", 1.0))
            msm = spec.get("minimum_should_match")
        else:
            text, operator, boost, msm = spec, "or", 1.0, None
        analyzer = self.mappers.search_analyzer_for(fld)
        terms = analyzer.analyze(str(text))
        if not terms:
            return MatchNoneQuery()
        clauses = tuple(TermQuery(fld, t) for t in terms)
        if len(clauses) == 1:
            q = clauses[0]
            return TermQuery(q.field, q.value, boost)
        if operator == "and":
            return BoolQuery(must=clauses, boost=boost)
        return BoolQuery(should=clauses,
                         minimum_should_match=resolve_msm(msm, len(clauses)) or 1,
                         boost=boost)

    def _parse_multi_match(self, body) -> Query:
        """Ref: index/query/MultiMatchQueryParser.java (best_fields ->
        max-like; we implement the 2.0 default 'most_fields-ish' sum via
        bool should across per-field match queries)."""
        fields = body.get("fields") or []
        text = body.get("query")
        if not fields:
            raise QueryParsingError("[multi_match] requires [fields]")
        pairs = []
        for f in fields:
            boost = 1.0
            if "^" in f:
                f, b = f.split("^", 1)
                boost = float(b)
            pairs.append((f, boost))
        mtype = str(body.get("type", "best_fields")).lower()
        if mtype == "cross_fields":
            # BM25F "one virtual document" scoring (all fields share
            # one analyzer group — we analyze with the first field's
            # search analyzer, the common mapping for cross_fields)
            analyzer = self.mappers.search_analyzer_for(pairs[0][0])
            terms = analyzer.analyze(str(text))
            if not terms:
                return MatchNoneQuery()
            return BM25FQuery(tuple(pairs), tuple(terms),
                              boost=float(body.get("boost", 1.0)))
        shoulds = []
        for f, boost in pairs:
            sub = self._parse_match({f: {"query": text, "boost": boost}})
            if not isinstance(sub, MatchNoneQuery):
                shoulds.append(sub)
        if not shoulds:
            return MatchNoneQuery()
        return BoolQuery(should=tuple(shoulds), minimum_should_match=1,
                         boost=float(body.get("boost", 1.0)))

    def _parse_match_phrase(self, body) -> Query:
        return self._phrase(body, "match_phrase", prefix_last=False)

    def _parse_match_phrase_prefix(self, body) -> Query:
        return self._phrase(body, "match_phrase_prefix", prefix_last=True)

    def _phrase(self, body, ctx: str, prefix_last: bool) -> Query:
        fld, spec = _single_entry(body, ctx)
        if isinstance(spec, dict):
            text = spec.get("query")
            slop = int(spec.get("slop", 0))
            boost = float(spec.get("boost", 1.0))
            max_exp = int(spec.get("max_expansions", 50))
        else:
            text, slop, boost, max_exp = spec, 0, 1.0, 50
        analyzer = self.mappers.search_analyzer_for(fld)
        terms = analyzer.analyze(str(text))
        if not terms:
            return MatchNoneQuery()
        if len(terms) == 1 and not prefix_last:
            return TermQuery(fld, terms[0], boost)
        if len(terms) == 1 and prefix_last:
            return PrefixQuery(fld, terms[0], boost, max_exp)
        return PhraseQuery(fld, tuple(terms), slop=slop, boost=boost,
                           prefix_last=prefix_last, max_expansions=max_exp)

    def _parse_range(self, body) -> Query:
        fld, spec = _single_entry(body, "range")
        if not isinstance(spec, dict):
            raise QueryParsingError("[range] body must be an object")
        legacy = {"from": "gte", "to": "lte"}
        kw = {}
        for k, v in spec.items():
            k = legacy.get(k, k)
            if k in ("gte", "gt", "lte", "lt"):
                kw[k] = v
            elif k in ("boost",):
                kw["boost"] = float(v)
            elif k in ("include_lower", "include_upper", "format", "time_zone"):
                pass  # include_* handled via from/to in legacy form; format TODO
        return RangeQuery(fld, **kw)

    def _parse_exists(self, body) -> Query:
        return ExistsQuery(body["field"])

    def _parse_missing(self, body) -> Query:
        # ref: index/query/MissingFilterParser.java == not exists
        return BoolQuery(must_not=(ExistsQuery(body["field"]),))

    def _parse_ids(self, body) -> Query:
        values = body.get("values") or []
        return IdsQuery(tuple(str(v) for v in values))

    def _parse_prefix(self, body) -> Query:
        fld, spec = _single_entry(body, "prefix")
        if isinstance(spec, dict):
            return PrefixQuery(fld, str(spec.get("value") or spec.get("prefix")),
                               float(spec.get("boost", 1.0)))
        return PrefixQuery(fld, str(spec))

    def _parse_wildcard(self, body) -> Query:
        fld, spec = _single_entry(body, "wildcard")
        if isinstance(spec, dict):
            return WildcardQuery(fld, str(spec.get("value") or spec.get("wildcard")),
                                 float(spec.get("boost", 1.0)))
        return WildcardQuery(fld, str(spec))

    def _parse_fuzzy(self, body) -> Query:
        fld, spec = _single_entry(body, "fuzzy")
        if isinstance(spec, dict):
            fuzz = spec.get("fuzziness", "AUTO")
            fuzz = 2 if str(fuzz).upper() == "AUTO" else int(fuzz)
            return FuzzyQuery(fld, str(spec.get("value")), fuzz,
                              float(spec.get("boost", 1.0)))
        return FuzzyQuery(fld, str(spec))

    def _parse_regexp(self, body) -> Query:
        # no expansion cap: ES regexp matching is automaton-based over the
        # whole term dictionary (max_determinized_states guards automaton
        # complexity, not result count — Python's re has no analog)
        fld, spec = _single_entry(body, "regexp")
        if isinstance(spec, dict):
            return RegexpQuery(fld, str(spec.get("value")),
                               float(spec.get("boost", 1.0)),
                               max_expansions=1 << 30)
        return RegexpQuery(fld, str(spec), max_expansions=1 << 30)

    # -- spans -------------------------------------------------------------

    def _parse_span(self, query: dict, ctx: str) -> Query:
        q = self.parse(query)
        if not isinstance(q, (SpanTermQuery, SpanNearQuery, SpanOrQuery,
                              SpanFirstQuery, SpanNotQuery)):
            raise QueryParsingError(f"[{ctx}] clauses must be span queries")
        return q

    def _parse_span_term(self, body) -> Query:
        fld, spec = _single_entry(body, "span_term")
        if isinstance(spec, dict):
            return SpanTermQuery(fld, str(spec.get("value")),
                                 float(spec.get("boost", 1.0)))
        return SpanTermQuery(fld, str(spec))

    def _parse_span_near(self, body) -> Query:
        clauses = tuple(self._parse_span(c, "span_near")
                        for c in body.get("clauses") or [])
        if not clauses:
            raise QueryParsingError("[span_near] requires [clauses]")
        return SpanNearQuery(clauses, slop=int(body.get("slop", 0)),
                             in_order=bool(body.get("in_order", True)),
                             boost=float(body.get("boost", 1.0)))

    def _parse_span_or(self, body) -> Query:
        clauses = tuple(self._parse_span(c, "span_or")
                        for c in body.get("clauses") or [])
        if not clauses:
            raise QueryParsingError("[span_or] requires [clauses]")
        return SpanOrQuery(clauses, boost=float(body.get("boost", 1.0)))

    def _parse_span_first(self, body) -> Query:
        match = body.get("match")
        if match is None:
            raise QueryParsingError("[span_first] requires [match]")
        return SpanFirstQuery(self._parse_span(match, "span_first"),
                              end=int(body.get("end", 1)),
                              boost=float(body.get("boost", 1.0)))

    def _parse_span_not(self, body) -> Query:
        include = body.get("include")
        exclude = body.get("exclude")
        if include is None or exclude is None:
            raise QueryParsingError(
                "[span_not] requires [include] and [exclude]")
        return SpanNotQuery(self._parse_span(include, "span_not"),
                            self._parse_span(exclude, "span_not"),
                            pre=int(body.get("pre", 0)),
                            post=int(body.get("post", 0)),
                            boost=float(body.get("boost", 1.0)))

    def _parse_span_multi(self, body) -> Query:
        # span wrapper around prefix/wildcard/fuzzy/regexp: expansion
        # happens at bind anyway; treat inner spans as single-position
        # terms is not possible generally, so accept and return the inner
        # multi-term query for scoring purposes (set semantics preserved
        # when used standalone; ref: SpanMultiTermQueryParser.java)
        inner = body.get("match")
        if inner is None:
            raise QueryParsingError("[span_multi] requires [match]")
        return self.parse(inner)

    # -- more_like_this / common -------------------------------------------

    def _parse_more_like_this(self, body) -> Query:
        fields = tuple(body.get("fields") or
                       [n for n, f in self.mappers.mapper.fields.items()
                        if f.type == "text"])
        likes = body.get("like")
        if likes is None:
            likes = body.get("like_text")
        if likes is None:
            # legacy docs/ids arrays (ref: MoreLikeThisQueryParser "docs"/
            # "ids"): ids are document references, not literal text;
            # both keys may appear together and merge
            likes = [({"_id": d} if isinstance(d, (str, int)) else d)
                     for d in [*(body.get("docs") or []),
                               *(body.get("ids") or [])]]
        if not isinstance(likes, list):
            likes = [likes]

        exclude_ids: list[str] = []

        def collect(entries) -> list[str]:
            texts: list[str] = []
            for like in entries:
                if isinstance(like, (str, int)):
                    # bare strings in like/like_text/ignore_like are
                    # literal text (doc references were wrapped into
                    # {_id} dicts above)
                    texts.append(str(like))
                    continue
                if isinstance(like, dict):
                    did = like.get("_id") or like.get(
                        "_doc", {}).get("_id")
                    if did is not None and self.doc_lookup is not None:
                        src = self.doc_lookup(str(did))
                        if src is not None:
                            exclude_ids.append(str(did))
                            for f in fields:
                                v = _dotted_get(src, f)
                                if v is not None:
                                    texts.append(str(v))
                    elif like.get("doc"):
                        for f in fields:
                            v = _dotted_get(like["doc"], f)
                            if v is not None:
                                texts.append(str(v))
            return texts

        texts = collect(likes)
        unlikes = body.get("ignore_like", body.get("unlike"))
        if unlikes is not None and not isinstance(unlikes, list):
            unlikes = [unlikes]
        n_excl = len(exclude_ids)
        unlike_texts = collect(unlikes or [])
        del exclude_ids[n_excl:]   # ignore-docs are not result excludes
        if not texts:
            return MatchNoneQuery()
        include = bool(body.get("include", False))
        return MoreLikeThisQuery(
            fields=fields, like_texts=tuple(texts),
            unlike_texts=tuple(unlike_texts),
            exclude_ids=() if include else tuple(exclude_ids),
            min_term_freq=int(body.get("min_term_freq", 2)),
            min_doc_freq=int(body.get("min_doc_freq", 5)),
            max_query_terms=int(body.get("max_query_terms", 25)),
            minimum_should_match=str(body.get("minimum_should_match", "30%")),
            boost=float(body.get("boost", 1.0)))

    _parse_mlt = _parse_more_like_this
    _parse_fuzzy_like_this = _parse_more_like_this  # deprecated alias

    def _parse_common(self, body) -> Query:
        """common terms query (ref: index/query/CommonTermsQueryParser.java).
        The high/low-frequency split depends on per-segment df, but the
        eager-impact design already down-weights frequent terms via idf, so
        the rewrite is a match query honoring low_freq_operator/msm."""
        fld, spec = _single_entry(body, "common")
        if not isinstance(spec, dict):
            spec = {"query": spec}
        msm = spec.get("minimum_should_match")
        if isinstance(msm, dict):
            msm = msm.get("low_freq")
        return self._parse_match({fld: {
            "query": spec.get("query"),
            "operator": spec.get("low_freq_operator", "or"),
            "minimum_should_match": msm,
            "boost": spec.get("boost", 1.0)}})

    def _parse_nested(self, body) -> Query:
        path = body.get("path")
        if not path:
            raise QueryParsingError("[nested] requires [path]")
        inner = body.get("query") or body.get("filter")
        if inner is None:
            raise QueryParsingError("[nested] requires [query]")
        return NestedQuery(
            path=str(path), query=self.parse(inner),
            score_mode=str(body.get("score_mode", "avg")).lower(),
            boost=float(body.get("boost", 1.0)))

    def _parse__parents_match(self, body) -> Query:
        return ParentsMatchQuery(self.parse(body.get("query")))

    # -- misc wrappers ------------------------------------------------------

    def _parse_wrapper(self, body) -> Query:
        import base64
        import json as _json
        raw = body.get("query") if isinstance(body, dict) else body
        if isinstance(raw, str):
            raw = _json.loads(base64.b64decode(raw))
        return self.parse(raw)

    def _parse_indices(self, body) -> Query:
        # ref: index/query/IndicesQueryParser.java
        targets = body.get("indices") or [body.get("index")]
        match = self.index_name is None or self.index_name in targets
        if match:
            return self.parse(body.get("query"))
        no_match = body.get("no_match_query", "all")
        if no_match == "none":
            return MatchNoneQuery()
        if no_match == "all" or no_match is None:
            return MatchAllQuery()
        return self.parse(no_match)

    def _parse_type(self, body) -> Query:
        # single-doc-type world (ref: TypeFilterParser; types were removed
        # in later ES — everything is _doc)
        value = body.get("value")
        if value in ("_doc", "doc", None):
            return MatchAllQuery()
        return MatchNoneQuery()

    def _parse_limit(self, body) -> Query:
        return MatchAllQuery()  # deprecated no-op filter (LimitFilterParser)

    def _parse_template(self, body) -> Query:
        """template query: inline mustache-rendered query
        (ref: index/query/TemplateQueryParser.java)."""
        from .templates import render_template
        spec = body.get("inline") or body.get("query") or body.get("template")
        params = body.get("params") or {}
        if isinstance(spec, dict) and "inline" in spec:
            params = spec.get("params") or params
            spec = spec["inline"]
        rendered = render_template(spec, params)
        return self.parse(rendered)

    # -- compound ----------------------------------------------------------

    def _parse_list(self, body, ctx) -> tuple[Query, ...]:
        if body is None:
            return ()
        items = body if isinstance(body, list) else [body]
        return tuple(self.parse(i) for i in items)

    def _parse_bool(self, body) -> Query:
        should = self._parse_list(body.get("should"), "should")
        return BoolQuery(
            must=self._parse_list(body.get("must"), "must"),
            should=should,
            must_not=self._parse_list(body.get("must_not"), "must_not"),
            filter=self._parse_list(body.get("filter"), "filter"),
            minimum_should_match=resolve_msm(body.get("minimum_should_match"),
                                             len(should)),
            boost=float(body.get("boost", 1.0)),
        )

    def _parse_constant_score(self, body) -> Query:
        inner = body.get("filter") or body.get("query")
        if inner is None:
            raise QueryParsingError("[constant_score] requires [filter] or [query]")
        return ConstantScoreQuery(self.parse(inner), float(body.get("boost", 1.0)))

    def _parse_filtered(self, body) -> Query:
        # legacy ES 2.0 form, ref: index/query/FilteredQueryParser.java
        q = self.parse(body.get("query")) if body.get("query") else MatchAllQuery()
        f = self.parse(body.get("filter")) if body.get("filter") else None
        if f is None:
            return q
        return BoolQuery(must=(q,), filter=(f,))

    def _parse_boosting(self, body) -> Query:
        return BoostingQuery(
            positive=self.parse(body["positive"]),
            negative=self.parse(body["negative"]),
            negative_boost=float(body.get("negative_boost", 0.2)),
        )

    def _parse_dis_max(self, body) -> Query:
        # approximation: sum-of-scores bool should (true max lands with the
        # executor's max-combine mode); matches set semantics exactly
        return BoolQuery(should=self._parse_list(body.get("queries"), "dis_max"),
                         minimum_should_match=1,
                         boost=float(body.get("boost", 1.0)))

    def _parse_and(self, body) -> Query:
        filters = body.get("filters") if isinstance(body, dict) else body
        return BoolQuery(filter=self._parse_list(filters, "and"))

    def _parse_or(self, body) -> Query:
        filters = body.get("filters") if isinstance(body, dict) else body
        return BoolQuery(should=self._parse_list(filters, "or"),
                         minimum_should_match=1)

    def _parse_query_string(self, body) -> Query:
        """Minimal query_string: `field:value` pairs, AND/OR/NOT/-term
        operators, bare terms matched across all text fields.

        Ref: index/query/QueryStringQueryParser.java — the full Lucene
        syntax (grouping, ranges, fuzziness suffixes) lands with the
        parser module; this covers the URI-search `q=` workhorse forms.
        """
        if isinstance(body, str):
            text, default_field = body, None
        else:
            text = str(body.get("query", ""))
            default_field = body.get("default_field")
        default_and = (not isinstance(body, str)
                       and str(body.get("default_operator", "or")
                               ).lower() == "and")
        tokens = text.split()
        text_fields = [n for n, f in self.mappers.mapper.fields.items()
                       if f.type == "text"] or ["_all"]

        # pass 1: collect clauses with their surrounding explicit operators
        # items: [clause, op_before (AND/OR/None), negate, required(+)]
        items: list[list] = []
        op_before: str | None = None
        i = 0
        while i < len(tokens):
            tok = tokens[i]
            if tok in ("AND", "OR", "&&", "||"):
                op_before = "AND" if tok in ("AND", "&&") else "OR"
                i += 1
                continue
            negate = False
            required = False
            if tok == "NOT" or tok == "!":
                negate = True
                i += 1
                tok = tokens[i] if i < len(tokens) else ""
            elif tok.startswith("-") and len(tok) > 1:
                negate = True
                tok = tok[1:]
            elif tok.startswith("+") and len(tok) > 1:
                required = True
                tok = tok[1:]
            if ":" in tok:
                fld, val = tok.split(":", 1)
                clause = self._parse_match({fld: val})
            elif default_field:
                clause = self._parse_match({default_field: tok})
            else:
                subs = [self._parse_match({f: tok}) for f in text_fields]
                subs = [s for s in subs if not isinstance(s, MatchNoneQuery)]
                clause = (BoolQuery(should=tuple(subs),
                                    minimum_should_match=1)
                          if subs else MatchNoneQuery())
            items.append([clause, op_before, negate, required])
            op_before = None
            i += 1

        # pass 2: resolve operators BOTH ways — an AND binds its left and
        # right operands as required; an explicit OR makes both optional
        # (overriding default_operator=and), matching Lucene's resolution
        n = len(items)
        group = ["must" if default_and else "should"] * n
        for j in range(n):
            if items[j][1] == "AND":
                group[j] = "must"
                if j > 0:
                    group[j - 1] = "must"
            elif items[j][1] == "OR":
                group[j] = "should"
                if j > 0 and not items[j - 1][3]:
                    group[j - 1] = "should"
        musts, shoulds, must_nots = [], [], []
        for j, (clause, _op, negate, required) in enumerate(items):
            if negate:
                must_nots.append(clause)
            elif required or group[j] == "must":
                musts.append(clause)
            else:
                shoulds.append(clause)
        if not (musts or shoulds or must_nots):
            return MatchAllQuery()
        return BoolQuery(must=tuple(musts), should=tuple(shoulds),
                         must_not=tuple(must_nots),
                         minimum_should_match=1 if shoulds and not musts else 0)

    def _parse_simple_query_string(self, body) -> Query:
        return self._parse_query_string(body)

    def _parse_function_score(self, body) -> Query:
        inner = self.parse(body.get("query")) if body.get("query") \
            else MatchAllQuery()
        raw_fns = body.get("functions")
        if raw_fns is None:
            # single-function shorthand: the function keys live at top level
            raw_fns = [{k: v for k, v in body.items()
                        if k not in ("query", "boost", "score_mode",
                                     "boost_mode", "max_boost", "min_score")}]
        functions = []
        for spec in raw_fns:
            spec = dict(spec)
            flt = self.parse(spec.pop("filter")) if spec.get("filter") \
                else None
            spec.pop("filter", None)
            weight = float(spec.pop("weight", 1.0))
            if not spec:
                functions.append(ScoreFunction("weight", weight=weight,
                                               filter=flt))
                continue
            kind, conf = _single_entry(spec, "function_score.functions")
            if kind == "field_value_factor":
                functions.append(ScoreFunction(
                    "field_value_factor", field=conf["field"], weight=weight,
                    filter=flt, factor=float(conf.get("factor", 1.0)),
                    modifier=str(conf.get("modifier", "none")).lower(),
                    missing=float(conf.get("missing", 0.0))))
            elif kind == "random_score":
                functions.append(ScoreFunction(
                    "random_score", weight=weight, filter=flt,
                    seed=int(conf.get("seed", 0) or 0)))
            elif kind in ("gauss", "exp", "linear", "lin"):
                fld, dconf = _single_entry(conf, kind)
                functions.append(ScoreFunction(
                    "linear" if kind == "lin" else kind, field=fld,
                    weight=weight, filter=flt,
                    origin=dconf.get("origin"), scale=dconf.get("scale"),
                    offset=dconf.get("offset", 0),
                    decay=float(dconf.get("decay", 0.5))))
            elif kind == "script_score":
                from ..script import parse_script_spec
                src, sparams = parse_script_spec(conf)
                functions.append(ScoreFunction(
                    "script_score", weight=weight, filter=flt, script=src,
                    script_params=tuple(sorted(sparams.items()))))
            else:
                raise QueryParsingError(
                    f"unknown score function [{kind}]")
        return FunctionScoreQuery(
            query=inner, functions=tuple(functions),
            score_mode=str(body.get("score_mode", "multiply")).lower(),
            boost_mode=str(body.get("boost_mode", "multiply")).lower(),
            max_boost=float(body.get("max_boost", float("inf"))),
            min_score=(float(body["min_score"])
                       if body.get("min_score") is not None else None),
            boost=float(body.get("boost", 1.0)))

    _GEO_OPTION_KEYS = frozenset((
        "distance", "distance_type", "unit", "optimize_bbox", "boost",
        "validation_method", "coerce", "ignore_malformed", "from", "to",
        "gt", "gte", "lt", "lte",
        "include_lower", "include_upper", "_name", "type"))

    def _geo_field_value(self, body: dict, ctx: str):
        field = None
        value = None
        for k, v in body.items():
            if k not in self._GEO_OPTION_KEYS:
                if field is not None:
                    raise QueryParsingError(
                        f"[{ctx}] multiple geo fields: [{field}], [{k}]")
                field, value = k, v
        if field is None:
            raise QueryParsingError(f"[{ctx}] requires a geo_point field")
        return field, value

    def _parse_knn(self, body) -> Query:
        if not isinstance(body, dict) or "field" not in body:
            raise QueryParsingError("[knn] requires [field]")
        vec = body.get("query_vector")
        if not isinstance(vec, (list, tuple)) or not vec:
            raise QueryParsingError("[knn] requires [query_vector]")
        return KnnQuery(field=str(body["field"]),
                        vector=tuple(float(x) for x in vec),
                        boost=float(body.get("boost", 1.0)))

    def _parse_geo_distance(self, body) -> Query:
        from ..ops.geo import parse_distance, parse_geo_point
        field, value = self._geo_field_value(body, "geo_distance")
        if "distance" not in body:
            raise QueryParsingError("[geo_distance] requires [distance]")
        lat, lon = parse_geo_point(value)
        return GeoDistanceQuery(
            field=field, lat=lat, lon=lon,
            distance_m=parse_distance(body["distance"],
                                      body.get("unit", "m")),
            boost=float(body.get("boost", 1.0)))

    def _parse_geo_distance_range(self, body) -> Query:
        from ..ops.geo import parse_distance, parse_geo_point
        field, value = self._geo_field_value(body, "geo_distance_range")
        lat, lon = parse_geo_point(value)
        unit = body.get("unit", "m")
        # gte/lte aliases accepted by GeoDistanceRangeQueryParser (the
        # exclusive gt/lt variants collapse to inclusive: distance rings
        # are continuous so the boundary set has measure zero)
        to = body.get("to", body.get("lte", body.get("lt")))
        frm = body.get("from", body.get("gte", body.get("gt")))
        return GeoDistanceQuery(
            field=field, lat=lat, lon=lon,
            distance_m=(parse_distance(to, unit) if to is not None
                        else float("inf")),
            from_m=parse_distance(frm, unit) if frm is not None else 0.0,
            boost=float(body.get("boost", 1.0)))

    def _parse_geo_bounding_box(self, body) -> Query:
        from ..ops.geo import parse_geo_point
        field, value = self._geo_field_value(body, "geo_bounding_box")
        if not isinstance(value, dict):
            raise QueryParsingError("[geo_bounding_box] requires corners")
        if "top_left" in value and "bottom_right" in value:
            top, left = parse_geo_point(value["top_left"])
            bottom, right = parse_geo_point(value["bottom_right"])
        elif "top_right" in value and "bottom_left" in value:
            top, right = parse_geo_point(value["top_right"])
            bottom, left = parse_geo_point(value["bottom_left"])
        elif all(k in value for k in ("top", "left", "bottom", "right")):
            try:
                top = float(value["top"])
                left = float(value["left"])
                bottom = float(value["bottom"])
                right = float(value["right"])
            except (TypeError, ValueError):
                raise QueryParsingError(
                    "[geo_bounding_box] corner values must be numbers")
        else:
            raise QueryParsingError(
                "[geo_bounding_box] requires both corners "
                "(top_left/bottom_right, top_right/bottom_left, or "
                "top/left/bottom/right)")
        return GeoBoundingBoxQuery(field=field, top=top, left=left,
                                   bottom=bottom, right=right,
                                   boost=float(body.get("boost", 1.0)))

    def _parse_geo_polygon(self, body) -> Query:
        from ..ops.geo import parse_geo_point
        field, value = self._geo_field_value(body, "geo_polygon")
        pts = (value or {}).get("points") if isinstance(value, dict) else None
        if not pts or len(pts) < 3:
            raise QueryParsingError(
                "[geo_polygon] requires at least 3 [points]")
        return GeoPolygonQuery(
            field=field,
            points=tuple(parse_geo_point(p) for p in pts),
            boost=float(body.get("boost", 1.0)))

    def _parse_geo_shape(self, body) -> Query:
        """Ref: index/query/GeoShapeQueryParser.java — inline `shape`
        (GeoJSON) or `indexed_shape` reference; relation intersects
        (default) | disjoint | within."""
        import json as _json
        field, value = self._geo_field_value(body, "geo_shape")
        if not isinstance(value, dict):
            raise QueryParsingError("[geo_shape] requires an object")
        relation = str(value.get("relation", "intersects")).lower()
        if relation not in ("intersects", "disjoint", "within"):
            raise QueryParsingError(
                f"unknown geo_shape relation [{relation}]")
        shape = value.get("shape")
        if shape is None and isinstance(value.get("indexed_shape"), dict):
            ref = value["indexed_shape"]
            ref_index = ref.get("index")
            if ref_index not in (None, self.index_name):
                raise QueryParsingError(
                    f"[geo_shape] indexed_shape index [{ref_index}] is "
                    f"not this index; resolve cross-index shapes before "
                    f"the shard phase")
            if self.doc_lookup is None or ref.get("id") is None:
                raise QueryParsingError(
                    "[geo_shape] indexed_shape requires [id]")
            src = self.doc_lookup(str(ref["id"]))
            if src is None:
                raise QueryParsingError(
                    f"shape [{ref['id']}] not found")
            path = str(ref.get("path", ref.get("shape_field_name",
                                               "shape")))
            shape = src
            for part in path.split("."):
                shape = shape.get(part) if isinstance(shape, dict) else None
            if shape is None:
                raise QueryParsingError(
                    f"no shape found at path [{path}] on [{ref['id']}]")
        if not isinstance(shape, dict):
            raise QueryParsingError("[geo_shape] requires a [shape]")
        from ..ops.geo_shape import parse_shape
        parse_shape(shape)  # validate early (400, not per-shard surprise)
        return GeoShapeQuery(
            field=field,
            shape_json=_json.dumps(shape, sort_keys=True,
                                   separators=(",", ":")),
            relation=relation,
            boost=float(body.get("boost", 1.0)))

    def _parse_script(self, body) -> Query:
        from ..script import parse_script_spec
        src, params = parse_script_spec(body)
        return ScriptQuery(script=src,
                           params=tuple(sorted(params.items())),
                           boost=float(body.get("boost", 1.0))
                           if isinstance(body, dict) else 1.0)

    def _parse_not(self, body) -> Query:
        if isinstance(body, dict):
            inner = body.get("query") or body.get("filter")
            if inner is None:
                inner = body  # legacy bare form: {"not": {<query>}}
        else:
            inner = body
        return BoolQuery(must_not=(self.parse(inner),))


def lucene_str(q: Query) -> str:
    """Render a query AST the way Lucene 5 toString renders the
    equivalent query — the shape the validate-query explain API exposes
    (ref: action/admin/indices/validate/query/TransportValidateQuery-
    Action explain = query.toString())."""
    if isinstance(q, MatchAllQuery):
        return "ConstantScore(*:*)"
    if isinstance(q, TermQuery):
        return f"{q.field}:{q.value}"
    if isinstance(q, ConstantScoreQuery):
        return f"ConstantScore({lucene_str(q.query)})"
    if isinstance(q, IdsQuery):
        return "_uid:" + " _uid:".join(q.values)
    if isinstance(q, BoolQuery):
        parts = []
        for sub in getattr(q, "must", ()) or ():
            parts.append(f"+{lucene_str(sub)}")
        for sub in getattr(q, "filter", ()) or ():
            parts.append(f"#{lucene_str(sub)}")
        for sub in getattr(q, "should", ()) or ():
            parts.append(lucene_str(sub))
        for sub in getattr(q, "must_not", ()) or ():
            parts.append(f"-{lucene_str(sub)}")
        return " ".join(parts)
    return repr(q)
