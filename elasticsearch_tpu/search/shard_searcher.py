"""Shard-level search: segments -> merged hits + reduced aggs + fetch.

Reference analog: search/SearchService.java executeQueryPhase/
executeFetchPhase over an acquired searcher, plus the per-shard part of
SearchPhaseController. A ShardReader is the immutable
`Engine.acquireSearcher` analog: a point-in-time view over segments +
live masks. Cross-SEGMENT merging here mirrors Lucene's cross-leaf
collection; cross-SHARD merging lives in search/controller.py.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..index.mapping import MapperService
from ..index.segment import Segment
from ..utils import faults
from ..utils.errors import SearchParseError, SearchTimeoutError
from .query_dsl import QueryParser, Query
from .executor import (QueryBinder, execute_segment, execute_segment_async,
                       execute_pack_async, collect_segment_result,
                       collect_pack_result)
from .aggregations import (parse_aggs, ShardAggContext, reduce_aggs,
                           shard_partials, AggSpec)
from .highlight import parse_highlight, highlight_hit
from .suggest import parse_suggest, execute_suggest


def rewrite_knn_body(body: dict) -> dict:
    """Top-level HYBRID `knn` section -> plain query-DSL form: the knn
    spec becomes a `knn` SCORING CLAUSE in a bool should beside the
    query section (minimum_should_match 1 — a hit matches either
    side), combined by ES's hybrid score-sum rule. As a plain query it
    rides the whole fused substrate: bundle admission
    (executor._fused_plan_bundle), ONE device dispatch for BM25+vector
    top-k, pack (base+delta) dispatch, coalescing and pipelining on
    the DispatchScheduler, and the mesh shard_map program. Shared with
    parallel/distributed.py so single-chip and mesh rewrite
    identically."""
    spec = body["knn"]
    knn_node = {"knn": {"field": spec["field"],
                        "query_vector": spec["query_vector"],
                        "boost": float(spec.get("boost", 1.0))}}
    q = body.get("query")
    if q:
        new_q = {"bool": {"should": [q, knn_node],
                          "minimum_should_match": 1}}
    else:
        new_q = knn_node
    out = {k: v for k, v in body.items() if k not in ("knn", "query")}
    out["query"] = new_q
    return out


def knn_body_mode(body: dict, mappers: MapperService) -> tuple[str, str]:
    """(mode, admission reason) for a top-level `knn` search section:

      "rewrite"    — hybrid (a `query` section rides along): rewrite
                     onto the bundle substrate (rewrite_knn_body);
      "candidates" — pure knn: per-segment candidate top-k dispatched
                     ASYNC at submit (IVF probe where the segment
                     carries an index, exact scan otherwise) so vector
                     searches pipeline through the dispatch scheduler
                     like everything else; counted as "ivf" / "exact"
                     by what the submit actually used;
      "host"       — shapes the device paths cannot take (unmapped
                     field, unsupported similarity, nonpositive
                     boost): the legacy host-driven combine, counted
                     under admission.knn as host_fallback:<why>.
    """
    spec = body.get("knn") or {}
    field = spec.get("field")
    fm = mappers.field(field) if field else None
    if fm is None or fm.type != "dense_vector":
        return "host", "host_fallback:unmapped_field"
    sim = fm.similarity if fm.similarity else "cosine"
    from ..ops.knn import SIMILARITIES
    if sim not in SIMILARITIES:
        return "host", f"host_fallback:similarity:{sim}"
    try:
        if float(spec.get("boost", 1.0)) <= 0.0:
            return "host", "host_fallback:nonpositive_boost"
    except (TypeError, ValueError):
        return "host", "host_fallback:bad_boost"
    if body.get("query"):
        return "rewrite", "query_rewrite"
    return "candidates", "candidates"


def _pack_dispatch_enabled() -> bool:
    """Base+delta one-dispatch kill switch (`ES_TPU_PACK_DISPATCH=0`):
    with it off, delta-mode readers fall back to per-segment dispatches
    — an A/B and bisection tool; responses are identical either way."""
    import os
    return os.environ.get("ES_TPU_PACK_DISPATCH", "1").lower() not in (
        "0", "false", "off")


class _PendingMsearch:
    """In-flight half of a split msearch (see ShardReader.msearch_submit):
    device programs are already enqueued; finish() collects in
    submission order and builds responses. `group_sizes` (queries per
    coalesced signature group) and `dispatch_count` (device programs
    enqueued) feed the dispatch scheduler's stats."""

    __slots__ = ("reader", "bodies", "with_partials", "started",
                 "knn_idx", "knn_sub", "parsed", "multi", "main",
                 "groups", "no_segments", "group_sizes",
                 "dispatch_count", "deadline", "step_budget")

    def __init__(self, reader: "ShardReader", bodies: list[dict],
                 with_partials: bool, started: float,
                 knn_idx: list[int], parsed: dict[int, dict]):
        self.reader = reader
        self.bodies = bodies
        self.with_partials = with_partials
        self.started = started
        self.knn_idx = knn_idx
        # per-knn-item ASYNC candidate dispatches (device programs
        # already enqueued at submit; None = legacy host path)
        self.knn_sub: dict[int, dict | None] = {}
        self.parsed = parsed
        self.multi: set[int] = set()
        self.main: list[int] = []
        self.groups: list[dict] = []
        self.no_segments = False
        self.group_sizes: list[int] = []
        self.dispatch_count = 0
        self.deadline: float | None = None
        # straggler budget for resident (device-stepped) dispatches —
        # None on the cold path (utils/faults.StepBudget)
        self.step_budget = None

    def finish(self) -> list[dict]:
        return self.reader._msearch_finish(self)


@dataclass
class ShardHit:
    doc_id: str
    score: float | None
    sort_key: float | None
    seg_ord: int
    local_doc: int
    source: bytes


class ShardReader:
    """Point-in-time searcher over a shard's segments (+ deletions)."""

    def __init__(self, index_name: str, segments: list[Segment],
                 live_masks: dict[str, np.ndarray], mapper: MapperService,
                 shard_id: int = 0):
        self.index_name = index_name
        self.segments = [s for s in segments if s.num_docs > 0]
        # live_all: engine deletions + parent-liveness propagated onto
        # nested child rows; live: additionally restricted to primary rows
        # (hidden block-join children never surface as hits — ref: Lucene
        # NonNestedDocsFilter)
        self.live_all = {}
        self.live = {}
        for s in self.segments:
            la = np.array(live_masks.get(s.seg_id, _default_live(s)),
                          dtype=bool, copy=True)
            if s.parent_of is not None:
                ch = s.parent_of >= 0
                la[ch] &= la[s.parent_of[ch]]
            self.live_all[s.seg_id] = la
            self.live[s.seg_id] = la & s.primary_mask()
        self.mappers = mapper
        self.shard_id = shard_id
        self._global_ords: dict[str, tuple[list[str], list[np.ndarray]]] = {}
        self._generation_key: tuple | None = None

    def generation_key(self) -> tuple:
        """Content-exact generation of this point-in-time view — the
        shard-request cache's invalidation signal (index/cache.py).
        Per segment: `Segment.cache_key()` (base content fingerprint /
        delta `(base generation, pow2 extent)` key), the delta epoch
        (bumped every delta rebuild, so a refresh that added docs
        re-keys even though the delta cache_key is epoch-stable), and
        a digest of the live mask (deletes flip bits without touching
        the segment). Memoized: the reader is immutable, one digest
        pass per refresh."""
        if self._generation_key is None:
            import hashlib
            parts = []
            for seg in self.segments:
                h = hashlib.blake2b(digest_size=8)
                h.update(self.live_all[seg.seg_id].tobytes())
                parts.append((seg.cache_key(),
                              int(getattr(seg, "delta_epoch", 0) or 0),
                              h.hexdigest()))
            self._generation_key = (self.index_name, self.shard_id,
                                    tuple(parts))
        return self._generation_key

    # -- global ordinals (ref: fielddata/ordinals/GlobalOrdinalsBuilder) ---
    def global_ords(self, field: str) -> tuple[list[str], list[np.ndarray]]:
        cached = self._global_ords.get(field)
        if cached is not None:
            return cached
        all_terms: set[str] = set()
        for seg in self.segments:
            kc = seg.keywords.get(field)
            if kc is not None:
                all_terms.update(kc.terms)
        terms = sorted(all_terms)
        lookup = {t: i for i, t in enumerate(terms)}
        seg_maps = []
        for seg in self.segments:
            kc = seg.keywords.get(field)
            if kc is None:
                seg_maps.append(np.zeros(1, dtype=np.int32))
            else:
                seg_maps.append(np.asarray([lookup[t] for t in kc.terms],
                                           dtype=np.int32))
        result = (terms, seg_maps)
        self._global_ords[field] = result
        return result

    # -- search ------------------------------------------------------------
    def search(self, body: dict) -> dict:
        return self.msearch([body])[0]

    def count(self, body: dict | None = None) -> int:
        res = self.search({"query": (body or {}).get("query"), "size": 0})
        return res["hits"]["total"]

    def msearch(self, bodies: list[dict], with_partials: bool = False,
                deadline: float | None = None) -> list[dict]:
        """Execute a batch of requests; structurally-identical requests are
        batched into one device program (leading dim B).

        with_partials=True attaches "_agg_partials" (keyed shard partials
        for the coordinator's cross-shard reduce) instead of finalized
        "aggregations" — the QUERY phase of a distributed search."""
        pend = self.msearch_submit(bodies, with_partials,
                                   deadline=deadline)
        out = pend.finish()
        # stamped AFTER finish(): auxiliary msearch calls inside it
        # (derived aggs, rescore windows, sig_terms) wrote the same
        # thread-local, so the outermost call wins — the dispatch
        # scheduler's sync path reads the stats of the call it made
        from .dispatch import note_submit_stats
        note_submit_stats(pend.group_sizes, pend.dispatch_count)
        return out

    def msearch_submit(self, bodies: list[dict],
                       with_partials: bool = False,
                       deadline: float | None = None) -> "_PendingMsearch":
        """Dispatch half of msearch: parse, group structurally-identical
        requests, and enqueue EVERY group's device programs through the
        non-syncing executor entry WITHOUT collecting — so a scheduler
        (search/dispatch.py) can pipeline several readers' round trips
        before any collection. `.finish()` collects in submission order
        and builds the responses. knn / multi-sort / empty-reader items
        are deferred to finish (they are host-driven, nothing to
        pipeline).

        `deadline` (absolute monotonic seconds) is the cooperative
        search deadline: finish() raises SearchTimeoutError instead of
        collecting once it has passed, releasing any still-queued
        breaker holds — the whole shard counts as failed-by-timeout.

        This is also the reader dispatch boundary the fault-injection
        registry (utils/faults.py) hooks: an injected shard_error /
        breaker_trip raises here exactly where a real device error
        would, and an injected shard_delay makes this shard a
        straggler."""
        faults.on_dispatch("reader", index=self.index_name,
                           shard=self.shard_id)
        started = time.monotonic()
        # resident mode: device-stepped dispatches meter any injected
        # straggler delay INSIDE device execution (per tile chunk, where
        # the preemptive deadline check can cut it short); the budget
        # object is shared across this pend's segment dispatches so the
        # shard sleeps its delay once, like the collect boundary would
        step_budget = None
        from .resident import enabled as _resident_enabled
        if _resident_enabled() and faults.enabled():
            step_budget = faults.StepBudget("reader",
                                            index=self.index_name,
                                            shard=self.shard_id)
        n = len(bodies)
        from .executor import _fused_stats
        bodies = list(bodies)
        knn_idx = []
        knn_modes: dict[int, str] = {}
        for i, b in enumerate(bodies):
            if not (b or {}).get("knn"):
                continue
            mode, reason = knn_body_mode(b, self.mappers)
            if mode != "candidates":
                # candidates items record "ivf" / "exact" from the
                # submit helper instead, so IVF-served and exact-
                # degraded segments are distinguishable in the stats
                _fused_stats.record_knn(reason)
            if mode == "rewrite" and self.segments:
                # hybrid BM25+knn: the knn spec becomes a scoring
                # clause in a plain bool query and the item joins the
                # ordinary grouped path — fused bundle admission, pack
                # dispatch, scheduler coalescing all apply
                bodies[i] = rewrite_knn_body(b)
            else:
                knn_idx.append(i)
                knn_modes[i] = mode
        knn_set = set(knn_idx)
        parsed = {i: self._parse_request(bodies[i])
                  for i in range(n) if i not in knn_set}
        pend = _PendingMsearch(self, bodies, with_partials, started,
                               knn_idx, parsed)
        pend.deadline = deadline
        pend.step_budget = step_budget
        if not self.segments:
            pend.no_segments = True
            return pend
        for i in knn_idx:
            # pure-knn items dispatch their per-segment candidate
            # top-k HERE (async, nothing collected) so they pipeline
            # with every other enqueued program; finish() combines
            if knn_modes[i] != "candidates":
                pend.knn_sub[i] = None
                continue
            sub = self._knn_candidates_submit(bodies[i])
            _fused_stats.record_knn(
                "ivf" if any(kind == "ivf" for _o, kind, _p
                             in sub["pending"]) else "exact")
            pend.knn_sub[i] = sub
        pend.multi = {i for i, p in parsed.items()
                      if p["sort_spec"][0] == "multi"}
        pend.main = [i for i in range(n)
                     if i not in knn_set and i not in pend.multi]

        # group request indices by (plan signature per segment, agg/sort/k sig)
        groups: dict[tuple, list[int]] = {}
        bound_per_req: dict[int, list] = {}
        for i in pend.main:
            p = parsed[i]
            per_seg_bounds = [
                QueryBinder(seg, self.mappers,
                            live=self.live[seg.seg_id],
                            dfs=p["dfs_stats"]).bind(p["query"])
                for seg in self.segments]
            bound_per_req[i] = per_seg_bounds
            sig = (tuple(b.signature() for b in per_seg_bounds), p["static_sig"])
            groups.setdefault(sig, []).append(i)

        for sig, idxs in groups.items():
            p0 = parsed[idxs[0]]
            agg_ctx = ShardAggContext(self.segments,
                                      self._ords_for(p0["agg_specs"]))
            agg_desc, agg_params = agg_ctx.build(p0["agg_specs"])
            k = p0["from"] + p0["size"]
            if k == 0 and (p0["sort_spec"][0] != "_score"
                           or p0["rescore"] is not None):
                # size-0 requests skip top-k entirely only on the plain
                # score-sort path; sorted/rescored requests keep k>=1
                k = 1
            sort_spec = p0["sort_spec"]
            if p0["agg_specs"]:
                # sorted-space query views: project the filter columns
                # onto each agg layout so the agg mask never rides a
                # per-query permutation gather (see executor.py)
                from .executor import ensure_agg_views
                for si, seg in enumerate(self.segments):
                    ensure_agg_views(seg, bound_per_req[idxs[0]][si],
                                     agg_desc)
            sort_terms = None
            sort_maps = [() for _ in self.segments]
            if sort_spec[0] == "field" and sort_spec[3] == "kw":
                sort_terms, seg_maps = self.global_ords(sort_spec[1])
                sort_maps = [(m,) for m in seg_maps]
            elif sort_spec[0] == "field" and sort_spec[3] == "script":
                from ..script import compile_script
                from .executor import ensure_script_vals
                cs = compile_script(sort_spec[1].split("\x00", 1)[0])
                for seg in self.segments:
                    ensure_script_vals(seg, cs.fields)
            elif sort_spec[0] == "field" and len(sort_spec) > 4:
                # extended spec (geo origin etc.): extras become dynamic
                # sort_params; the static jit key keeps only the 4-tuple
                extras = tuple(np.float32(e) for e in sort_spec[4:])
                sort_maps = [extras for _ in self.segments]
                sort_spec = sort_spec[:4]
            # dispatch all segments async; collection happens in
            # finish(), so round trips overlap across segments AND
            # across groups/readers. Nested-scope requests (aggregations
            # over hidden child rows) lift the primary-row restriction.
            live_sel = self.live_all if p0["nested_scope"] else self.live
            pending = []
            # streaming write path: a (base, delta) generation pair
            # serves fused-admitted plans in ONE device dispatch (the
            # delta walk chains onto the base's running top-k;
            # executor.execute_pack_async) — one tunnel round trip per
            # refresh-heavy reader instead of one per segment, with
            # byte-identical responses. Inadmissible plans fall back
            # to the per-segment dispatches below.
            if len(self.segments) == 2 \
                    and getattr(self.segments[1], "delta_parent",
                                None) is not None \
                    and _pack_dispatch_enabled():
                b_seg, d_seg = self.segments
                pack = execute_pack_async(
                    b_seg, d_seg, live_sel[b_seg.seg_id],
                    live_sel[d_seg.seg_id],
                    [bound_per_req[i][0] for i in idxs],
                    [bound_per_req[i][1] for i in idxs], k,
                    agg_desc=agg_desc,
                    agg_params_b=agg_params[0] if agg_params else (),
                    agg_params_d=agg_params[1] if agg_params else (),
                    sort_spec=sort_spec, deadline=deadline,
                    step_budget=step_budget,
                    shard_key=(self.index_name, self.shard_id))
                if pack is not None:
                    pending.append(pack)
            if not pending:
                for si, seg in enumerate(self.segments):
                    bounds = [bound_per_req[i][si] for i in idxs]
                    pending.append(execute_segment_async(
                        seg, live_sel[seg.seg_id], bounds, k,
                        agg_desc=agg_desc, agg_params=agg_params[si],
                        sort_spec=sort_spec, sort_params=sort_maps[si],
                        deadline=deadline, step_budget=step_budget,
                        shard_key=(self.index_name, self.shard_id)))
            pend.groups.append({"idxs": idxs, "p0": p0, "agg_ctx": agg_ctx,
                                "pending": pending,
                                "sort_terms": sort_terms})
        pend.group_sizes = [len(g["idxs"]) for g in pend.groups]
        pend.dispatch_count = sum(len(g["pending"]) for g in pend.groups)
        return pend

    @staticmethod
    def _release_pending_holds(pend: "_PendingMsearch") -> None:
        """Release every breaker hold still queued on the pend. Holds
        release at most once (utils/breaker.Hold), so sweeping ALL
        groups is safe after any number of them already collected."""
        for g in pend.groups:
            for _out, layout, _n in g["pending"]:
                hold = layout.get("_breaker_hold")
                if hold is not None:
                    hold.release()

    def _deadline_check(self, pend: "_PendingMsearch") -> None:
        if pend.deadline is not None \
                and time.monotonic() > pend.deadline:
            raise SearchTimeoutError(self.index_name, self.shard_id)

    def _msearch_finish(self, pend: "_PendingMsearch") -> list[dict]:
        try:
            return self._msearch_finish_inner(pend)
        except BaseException:
            # NO exit may leak breaker reservations: deadline raises,
            # collect-phase injected faults, and real device errors
            # mid-collect all sweep the still-queued holds before
            # propagating (the GC backstop alone accumulates estimates
            # into spurious trips under tight chaos/error loops)
            self._release_pending_holds(pend)
            raise

    def _msearch_finish_inner(self, pend: "_PendingMsearch") -> list[dict]:
        # collect-phase fault boundary: a straggler shard (injected
        # shard_delay) burns wall-clock HERE, where the caller waits on
        # device results — so only this shard (and shards collected
        # after it) can miss the deadline, never already-collected ones.
        # When a resident stepped dispatch already took the straggler
        # budget (metered inside device execution), delay rules are
        # skipped so the shard is not slowed twice.
        faults.on_dispatch("reader", index=self.index_name,
                           shard=self.shard_id, phase="collect",
                           skip_delay=bool(pend.step_budget is not None
                                           and pend.step_budget.taken))
        bodies = pend.bodies
        parsed = pend.parsed
        started = pend.started
        with_partials = pend.with_partials
        responses: list[dict | None] = [None] * len(bodies)
        for i in pend.knn_idx:
            # host-driven paths honor the deadline too: without this, a
            # knn/multi-sort-only pend would never consult it at all
            self._deadline_check(pend)
            sub = pend.knn_sub.get(i)
            if sub is None:
                responses[i] = self._knn_search(bodies[i], started,
                                                with_partials)
            else:
                responses[i] = self._knn_collect(bodies[i], sub, started,
                                                 with_partials)
        if pend.no_segments:
            for i, p in parsed.items():
                responses[i] = self._empty_response(p, started,
                                                    with_partials)
            return responses  # type: ignore[return-value]
        for i in sorted(pend.multi):
            self._deadline_check(pend)
            p = parsed[i]
            responses[i] = self._multi_sort_search(bodies[i], p,
                                                   started, with_partials)
            if p["highlight"] is not None:
                self._apply_highlight(responses[i], p)
            if p["suggest_specs"]:
                responses[i]["suggest"] = execute_suggest(
                    p["suggest_specs"], self.segments,
                    self.mappers.search_analyzer_for, self.mappers)
        for g in pend.groups:
            # deadline passed before this group's collect: the shard is
            # a laggard and fails whole by timeout (holds released by
            # the _msearch_finish wrapper). Fully-resident groups skip
            # the cooperative pre-check: EVERY dispatch carries the
            # device-side per-chunk deadline verdict (incl. a final
            # post-loop check), and collect_segment_result raises the
            # same SearchTimeoutError when one reports timed_out — a
            # step that beat the cutoff on-device is collected rather
            # than discarded on host lag. A group with ANY cold
            # dispatch keeps the cooperative check: that dispatch has
            # no device verdict to fall back on.
            if not all(l.get("resident") for _o, l, _n in g["pending"]):
                self._deadline_check(pend)
            idxs = g["idxs"]
            p0 = g["p0"]
            agg_ctx = g["agg_ctx"]
            partials = []
            seg_tops = []
            for out, layout, n_real in g["pending"]:
                if layout.get("pack"):
                    # one pack dispatch covered (base, delta): the
                    # collect splits back into per-segment candidate
                    # lists + per-segment agg partials, so everything
                    # downstream is unchanged
                    tops2, aggs2 = collect_pack_result(out, layout,
                                                       n_real)
                    seg_tops.extend(tops2)
                    partials.extend(aggs2)
                    continue
                top, aggs = collect_segment_result(out, layout, n_real)
                seg_tops.append(top)
                partials.append(aggs)
            if p0["agg_specs"] and with_partials:
                part_json = shard_partials(p0["agg_specs"], agg_ctx, partials,
                                           len(idxs))
                agg_json = [{} for _ in idxs]
            elif p0["agg_specs"]:
                part_json = None
                agg_json = reduce_aggs(p0["agg_specs"], agg_ctx, partials,
                                       len(idxs))
            else:
                part_json = None
                agg_json = [{} for _ in idxs]
            for bi, i in enumerate(idxs):
                responses[i] = self._build_response(
                    parsed[i], seg_tops, bi, agg_json[bi], started,
                    sort_terms=g["sort_terms"])
                if part_json is not None:
                    responses[i]["_agg_partials"] = part_json[bi]
        for i in pend.main:
            # post-processing (rescore windows, derived aggs, sig_terms
            # fan back into msearch) is host-driven and unbounded — a
            # shard that finishes it past the cutoff is a laggard too
            self._deadline_check(pend)
            p = parsed[i]
            if p["rescore"] is not None:
                self._apply_rescore(responses[i], p)
            if p["highlight"] is not None:
                self._apply_highlight(responses[i], p)
            if p["suggest_specs"]:
                responses[i]["suggest"] = execute_suggest(
                    p["suggest_specs"], self.segments,
                    self.mappers.search_analyzer_for, self.mappers)
            if p["derived_specs"]:
                self._apply_derived(responses[i], p, with_partials)
            self._apply_sig_subs(responses[i], p, with_partials)
        return responses  # type: ignore[return-value]

    def sig_term_counts(self, field: str, flt_field: str | None = None,
                        flt_value=None,
                        allowed_ids=None) -> tuple[int, dict]:
        """(n_docs, {token: doc_count}) over live docs, optionally
        restricted to docs whose `flt_field` equals `flt_value`. Counts
        TOKENS of analyzed text via the postings CSR (the fielddata view
        significant_terms works on in the reference — ref:
        SignificantTermsAggregatorFactory bg/fg frequency lookup);
        keyword fields count whole values."""
        total = 0
        counts: dict[str, int] = {}
        for seg in self.segments:
            mask = self.live[seg.seg_id].copy()
            if allowed_ids is not None:
                # enclosing-query scope: only docs the query matched
                in_q = np.zeros(seg.capacity, dtype=bool)
                for d, did in enumerate(seg.ids):
                    if did in allowed_ids:
                        in_q[d] = True
                mask &= in_q
            if flt_field is not None:
                kc = (seg.keywords.get(flt_field)
                      or seg.keywords.get(f"{flt_field}.keyword"))
                if kc is None:
                    continue
                t = kc.term_index.get(str(flt_value), -1)
                if t < 0:
                    continue
                m = kc.ords == t
                if kc.mv_ords is not None:
                    m |= (kc.mv_ords == t).any(axis=1)
                mask &= m
            total += int(mask.sum())
            pf = seg.text.get(field)
            if pf is not None:
                tids = np.repeat(
                    np.arange(len(pf.terms), dtype=np.int64),
                    np.diff(pf.indptr))
                sel = mask[pf.doc_ids]
                bc = np.bincount(tids[sel], minlength=len(pf.terms))
                for t_idx in np.nonzero(bc)[0]:
                    term = pf.terms[int(t_idx)]
                    counts[term] = counts.get(term, 0) + int(bc[t_idx])
            else:
                kc = (seg.keywords.get(field)
                      or seg.keywords.get(f"{field}.keyword"))
                if kc is None:
                    continue
                live_ords = kc.ords[mask]
                bc = np.bincount(live_ords[live_ords >= 0],
                                 minlength=len(kc.terms))
                for t_idx in np.nonzero(bc)[0]:
                    term = kc.terms[int(t_idx)]
                    counts[term] = counts.get(term, 0) + int(bc[t_idx])
        return total, counts

    def _apply_sig_subs(self, resp: dict, p: dict,
                        with_partials: bool) -> None:
        """significant_terms nested under a terms agg (see
        aggregations.apply_sig_subs). Single-host path only; the mesh
        path reduces its own partials and does not carry sig sub-aggs."""
        if with_partials:
            return
        if not any(getattr(spec, "sig_subs", None)
                   for spec in p["agg_specs"]):
            return
        from .aggregations import apply_sig_subs

        def search_ids(query: dict) -> set:
            r = self.search({"query": query, "size": 10_000,
                             "_source": False})
            return {h["_id"] for h in r["hits"]["hits"]}

        apply_sig_subs(p["agg_specs"], resp.get("aggregations", {}),
                       [self], raw_query=p["raw_query"],
                       search_ids=search_ids)

    def _apply_derived(self, resp: dict, p: dict,
                       with_partials: bool) -> None:
        """Derived bucket aggs (filter/filters/range/date_range/missing/
        global/top_hits): each bucket is an auxiliary filtered request
        through the same batched executor; nested sub-aggregations of any
        kind recurse naturally. Ref: the wrapped-collector designs in
        search/aggregations/bucket/{filter,filters,range,missing,global}.
        """
        for spec in p["derived_specs"]:
            if spec.kind in ("nested", "reverse_nested", "children"):
                aux_bodies = [self._scope_shift_body(spec, p)]
            elif spec.kind == "significant_terms":
                # foreground (query scope) vs background (whole index)
                # term counts; scored host-side with JLH
                base = {"size": 0, "aggs": spec.sub_raw}
                aux_bodies = [
                    {"query": p["raw_query"] or {"match_all": {}}, **base},
                    {"query": {"match_all": {}}, **base}]
                for b2 in aux_bodies:
                    if p["nested_scope"]:
                        b2["_nested_scope"] = p["nested_scope"]
            else:
                aux_bodies = []
                for key, flt, _extra in spec.buckets:
                    if spec.mode == "ignore_query":
                        q = flt or {"match_all": {}}
                    else:
                        clauses = {"filter": [flt] if flt else []}
                        if p["raw_query"] is not None:
                            clauses["must"] = [p["raw_query"]]
                        q = {"bool": clauses}
                    size = spec.top_hits_size if spec.kind == "top_hits" \
                        else 0
                    body = {"query": q, "size": size,
                            "_source": spec.top_hits_source}
                    if spec.sub_raw:
                        body["aggs"] = spec.sub_raw
                    # derived aggs nested inside a scope-shifted context
                    # (e.g. filter under nested) stay in that scope
                    if p["nested_scope"]:
                        body["_nested_scope"] = p["nested_scope"]
                    if p["reverse_ctx"]:
                        body["_reverse_ctx"] = p["reverse_ctx"]
                    aux_bodies.append(body)
            aux = self.msearch(aux_bodies, with_partials)
            if with_partials:
                derived = {}
                for (key, _f, _x), ar in zip(spec.buckets, aux):
                    bucket = {"count": ar["hits"]["total"],
                              "sub": ar.get("_agg_partials", {})}
                    if spec.kind == "top_hits":
                        bucket["hits"] = ar["hits"]["hits"]
                    derived[key] = bucket
                resp.setdefault("_agg_partials", {})[spec.name] = \
                    {"derived": derived}
            else:
                resp.setdefault("aggregations", {})[spec.name] = \
                    self._stitch_derived(spec, aux)

    def _scope_shift_body(self, spec, p: dict) -> dict:
        """Aux request for scope-shifting bucket aggs: nested (to child
        rows), reverse_nested (back to parents), children (to join-child
        docs). The aux request's own derived/sub aggs recurse naturally."""
        outer = p["raw_query"]
        if spec.kind == "nested":
            path = spec.mode.split(":", 1)[1]
            q = {"bool": {"filter": [
                {"term": {"_nested_path": path}},
                {"_parents_match": {"query": outer or {"match_all": {}}}}]}}
            body = {"query": q, "size": 0, "_nested_scope": path,
                    "_reverse_ctx": {"path": path, "outer": outer}}
        elif spec.kind == "reverse_nested":
            ctx = p.get("reverse_ctx")
            if not ctx:
                raise SearchParseError(
                    "[reverse_nested] must be nested inside a [nested] "
                    "aggregation")
            clauses: dict = {"filter": [{"nested": {
                "path": ctx["path"], "query": {"match_all": {}}}}]}
            if ctx.get("outer"):
                clauses["must"] = [ctx["outer"]]
            body = {"query": {"bool": clauses}, "size": 0}
        else:  # children
            ctype = spec.mode.split(":", 1)[1]
            fm = self._join_field("children")
            parent_rel = None
            for parent, kids in (fm.relations or {}).items():
                kids = kids if isinstance(kids, list) else [kids]
                if ctype in kids:
                    parent_rel = parent
            if parent_rel is None:
                raise SearchParseError(
                    f"[children] no relation to type [{ctype}]")
            q = {"bool": {
                "must": [{"has_parent": {"parent_type": parent_rel,
                                         "query": outer or
                                         {"match_all": {}}}}],
                "filter": [{"term": {fm.name: ctype}}]}}
            body = {"query": q, "size": 0}
        if spec.sub_raw:
            body["aggs"] = spec.sub_raw
        return body

    def _stitch_derived(self, spec, aux: list[dict]) -> dict:
        def bucket_json(ar: dict) -> dict:
            out = {"doc_count": ar["hits"]["total"]}
            out.update(ar.get("aggregations", {}))
            return out

        if spec.kind == "top_hits":
            ar = aux[0]
            return {"hits": {"total": ar["hits"]["total"],
                             "max_score": ar["hits"]["max_score"],
                             "hits": ar["hits"]["hits"]}}
        if spec.kind == "significant_terms":
            from .aggregations import significant_buckets
            fg, bg = aux[0], aux[1]
            return significant_buckets(
                spec, fg["hits"]["total"],
                fg["aggregations"]["__sig_terms"]["buckets"],
                bg["hits"]["total"],
                bg["aggregations"]["__sig_terms"]["buckets"])
        if spec.kind in ("filter", "missing", "global", "nested",
                         "reverse_nested", "children"):
            return bucket_json(aux[0])
        if spec.kind == "filters":
            return {"buckets": {key: bucket_json(ar)
                                for (key, _f, _x), ar in
                                zip(spec.buckets, aux)}}
        buckets = []
        for (key, _f, extra), ar in zip(spec.buckets, aux):
            buckets.append({"key": key,
                            **{k: v for k, v in extra.items()
                               if v is not None},
                            **bucket_json(ar)})
        return {"buckets": buckets}

    def _knn_spec(self, body: dict) -> tuple:
        spec = body["knn"]
        field = spec["field"]
        qv = np.asarray(spec["query_vector"], dtype=np.float32)
        k = int(spec.get("k", spec.get("num_candidates", 10)))
        boost = float(spec.get("boost", 1.0))
        fm = self.mappers.field(field)
        similarity = (fm.similarity if fm is not None and fm.similarity
                      else "cosine")
        return field, qv, k, boost, similarity

    def _knn_exact_dispatch(self, seg, field: str, qv: np.ndarray,
                            k: int, similarity: str):
        """Exact-scan candidate dispatch for one segment (async).
        Large segments select candidates approximately like the
        reference's HNSW stage (exact top_k over a 1M-doc score row
        costs ~80x more), but with a 4x overscan window whose exact
        re-sort at combine keeps the FINAL k effectively exact."""
        from ..ops.knn import knn_topk
        from .executor import device_arrays, _device_live

        dev = device_arrays(seg)["vec"][field]
        live = _device_live(seg, self.live[seg.seg_id])
        approx = seg.capacity >= (1 << 18)
        window = min(max(4 * k, 100), seg.capacity) if approx \
            else min(k, seg.capacity)
        return knn_topk(
            dev["values"], dev["norms"], dev["exists"], live,
            qv[None, :], similarity=similarity, k=window,
            approx_recall=0.99 if approx else None)

    def _knn_candidates_submit(self, body: dict) -> dict:
        """Dispatch half of a pure-knn search: per-segment candidate
        top-k ENQUEUED here (jax dispatch is async), collected in
        finish — vector searches overlap round trips with every other
        submitted program instead of serializing host-side. Segments
        carrying (or lazily building — index/ann.ensure_ann) an IVF
        index serve the coarse-quantized probe (ops/ann.ivf_topk);
        the rest take the exact scan. `site=ann:phase=probe` is the
        fault boundary: an injected error here surfaces exactly like a
        real device error — a structured `_shards.failures` partial."""
        from ..index import ann as ann_idx
        from ..index import tiering as _tiering
        from ..ops import ann as ann_ops
        from .executor import device_arrays, _device_live

        field, qv, k, _boost, similarity = self._knn_spec(body)
        pending = []
        for seg_ord, seg in enumerate(self.segments):
            vc = seg.vectors.get(field)
            if vc is None:
                continue
            ann = ann_idx.ensure_ann_device(
                seg, field, similarity, index=self.index_name,
                shard=self.shard_id)
            if ann is None:
                pending.append((seg_ord, "exact",
                                self._knn_exact_dispatch(
                                    seg, field, qv, k, similarity)))
                continue
            faults.on_dispatch("ann", index=self.index_name,
                               shard=self.shard_id, phase="probe")
            ai, adev = ann
            nprobe = ann_idx.default_nprobe(ai.n_clusters)
            probe = None
            if _tiering.enabled() and _tiering.paged_fields(seg):
                # oversubscribed pack: rank + pick the probe set with
                # the HOST bound mirror (ops/ann.cluster_bounds_np) so
                # the device program never touches clusters the bound
                # already ruled out — the PR 11 I/O-filter idea at
                # cluster granularity
                nb = ann_ops.cluster_bounds_np(
                    ai.centroids, ai.radii, qv[None, :],
                    similarity=similarity)
                rank = ann_ops.cluster_bounds_np(
                    ai.centroids, np.zeros_like(ai.radii),
                    qv[None, :], similarity=similarity)
                order = np.argsort(-rank, axis=1,
                                   kind="stable")[:, :nprobe]
                probe = (jnp.asarray(np.take_along_axis(nb, order,
                                                        axis=1)),
                         jnp.asarray(order.astype(np.int32)))
            dev = device_arrays(seg)["vec"][field]
            live = _device_live(seg, self.live[seg.seg_id])
            out = ann_ops.ivf_topk(
                dev["values"], dev["norms"], dev["exists"], live,
                adev["members"], adev["centroids"],
                adev["radii"], jnp.asarray(qv[None, :]),
                similarity=similarity, k=min(k, seg.capacity),
                nprobe=nprobe, probe=probe)
            pending.append((seg_ord, "ivf", out))
        return {"pending": pending, "k": k}

    def _knn_collect(self, body: dict, sub: dict, started: float,
                     with_partials: bool) -> dict:
        """Collect half: sync the candidate buffers, merge across
        segments (score desc, (segment, doc) tie order — the exact
        host rule the legacy path used), build the response."""
        from .executor import _fused_stats

        cands: list[tuple[float, int, int]] = []
        for seg_ord, kind, out in sub["pending"]:
            if kind == "ivf":
                scores, idx, stats = out
                st = np.asarray(stats)
                _fused_stats.record_ann_prune(int(st[0]), int(st[1]),
                                              int(st[2]))
            else:
                scores, idx = out
            s = np.asarray(scores[0])
            ix = np.asarray(idx[0])
            for j in range(s.shape[0]):
                if np.isfinite(s[j]):
                    cands.append((float(s[j]), seg_ord, int(ix[j])))
        cands.sort(key=lambda c: (-c[0], c[1], c[2]))
        return self._knn_build_response(body, cands[: sub["k"]],
                                        started, with_partials)

    def _knn_search(self, body: dict, started: float,
                    with_partials: bool = False) -> dict:
        """Host-fallback kNN (optionally hybrid with a query section)
        — the legacy synchronous path, kept for shapes the device
        paths decline (knn_body_mode "host") and for empty readers.

        Ref: BASELINE.json config[4] (dense_vector kNN + BM25 rescore);
        API shape follows modern ES `knn` search. Scoring = one MXU
        matmul per segment (ops/knn.py); hybrid combine = score sum with
        boosts, the ES hybrid-retrieval rule. Aggregations over kNN hits
        run host-side (candidate sets are k-sized, not corpus-sized).
        """
        field, qv, k, _boost, similarity = self._knn_spec(body)
        cands: list[tuple[float, int, int]] = []
        for seg_ord, seg in enumerate(self.segments):
            vc = seg.vectors.get(field)
            if vc is None:
                continue
            scores, idx = self._knn_exact_dispatch(seg, field, qv, k,
                                                   similarity)
            s = np.asarray(scores[0])
            ix = np.asarray(idx[0])
            for j in range(s.shape[0]):
                if np.isfinite(s[j]):
                    cands.append((float(s[j]), seg_ord, int(ix[j])))
        cands.sort(key=lambda c: (-c[0], c[1], c[2]))
        return self._knn_build_response(body, cands[:k], started,
                                        with_partials)

    def _knn_build_response(self, body: dict,
                            cands: list[tuple[float, int, int]],
                            started: float, with_partials: bool) -> dict:
        spec = body["knn"]
        k = int(spec.get("k", spec.get("num_candidates", 10)))
        knn_boost = float(spec.get("boost", 1.0))
        # fetch options / highlight reuse the standard request parsing
        p = self._parse_request({kk: vv for kk, vv in body.items()
                                 if kk != "knn"})
        combined: dict[str, float] = {}
        locate: dict[str, tuple[int, int]] = {}
        for score, seg_ord, local in cands:
            did = self.segments[seg_ord].ids[local]
            combined[did] = score * knn_boost
            locate[did] = (seg_ord, local)
        if body.get("query"):
            qboost = 1.0
            sub = self.msearch([{"query": body["query"],
                                 "size": max(k, p["from"] + p["size"]),
                                 "_source": False}])[0]
            for h in sub["hits"]["hits"]:
                did = h["_id"]
                combined[did] = combined.get(did, 0.0) + \
                    (h["_score"] or 0.0) * qboost
                if did not in locate:
                    seg, local = self._locate(did)
                    if seg is not None:
                        locate[did] = (self.segments.index(seg), local)

        ranked = sorted(combined.items(), key=lambda kv: (-kv[1], kv[0]))
        window = ranked[p["from"]: p["from"] + p["size"]]
        hits = []
        for did, score in window:
            seg_ord, local = locate[did]
            seg = self.segments[seg_ord]
            hit = {"_index": self.index_name, "_type": "_doc",
                   "_id": did, "_score": float(score)}
            if p["want_version"]:
                hit["_version"] = int(seg.versions[local])
            if p["source_filter"] is not False:
                src = filter_source(_load_source(seg.sources[local]),
                                    p["source_filter"])
                if src is not None:
                    hit["_source"] = src
            hits.append(hit)
        resp = {
            "took": int((time.monotonic() - started) * 1000),
            "timed_out": False,
            "_shards": {"total": 1, "successful": 1, "failed": 0},
            "hits": {"total": len(ranked),
                     "max_score": ranked[0][1] if ranked else None,
                     "hits": hits},
        }
        if p["highlight"] is not None:
            self._apply_highlight(resp, p)
        if p["agg_specs"] and with_partials:
            resp["_agg_partials"] = {}
        return resp

    def _multi_sort_search(self, body: dict, p: dict, started: float,
                           with_partials: bool = False) -> dict:
        """Multi-key field sort: the device returns the packed match
        bitmask; the host gathers the sort-key columns for matching rows
        and lexsorts (exact Lucene FieldComparator-chain semantics,
        missing-last per key). Exactness over the full match set — no
        top-k truncation risk on tie-heavy primaries."""
        keys = p["sort_spec"][1]
        agg_desc = (("__match", ("matchmask",)),)
        pending = []
        for seg in self.segments:
            bound = QueryBinder(seg, self.mappers,
                                live=self.live[seg.seg_id],
                                dfs=p["dfs_stats"]).bind(p["query"])
            pending.append(execute_segment_async(
                seg, self.live[seg.seg_id], [bound], 1,
                agg_desc=agg_desc, agg_params=((),),
                sort_spec=("_score",), sort_params=()))
        rows_per_seg: list[np.ndarray] = []
        for si, (out, layout, n_real) in enumerate(pending):
            _top, aggs = collect_segment_result(out, layout, n_real)
            seg = self.segments[si]
            mask = np.unpackbits(
                np.asarray(aggs["__match"]["mask"][0]).astype(np.uint8),
                bitorder="little")[: seg.capacity].astype(bool)
            mask &= self.live[seg.seg_id]
            rows_per_seg.append(np.nonzero(mask)[0])

        # per-key global ordinal spaces for keyword keys
        gords = {fld: self.global_ords(fld)
                 for fld, _d, kind in keys if kind == "kw"}
        seg_ids = np.concatenate(
            [np.full(r.size, si, dtype=np.int64)
             for si, r in enumerate(rows_per_seg)]) \
            if rows_per_seg else np.empty(0, np.int64)
        locals_ = np.concatenate(rows_per_seg) \
            if rows_per_seg else np.empty(0, np.int64)
        lex_arrays: list[np.ndarray] = []
        display: list[tuple] = []   # (kind, per-seg accessor) for hit sort
        for fld, desc, kind in keys:
            # keep each key column in its raw dtype: int64 sort values
            # beyond 2^53 would lose precision (and so order) as float64
            if kind == "kw":
                key_dtype = np.int64
            else:
                raw_dtypes = {self.segments[si].numerics[fld].raw.dtype
                              for si in range(len(self.segments))
                              if fld in self.segments[si].numerics}
                key_dtype = (np.int64 if raw_dtypes == {np.dtype(np.int64)}
                             else np.float64)
            vals = np.zeros(locals_.size, dtype=key_dtype)
            miss = np.ones(locals_.size, dtype=bool)
            off = 0
            for si, rows in enumerate(rows_per_seg):
                seg = self.segments[si]
                nrow = rows.size
                if kind == "kw":
                    kc = seg.keywords.get(fld)
                    if kc is not None and nrow:
                        terms, seg_maps = gords[fld]
                        ords = kc.ords[rows]
                        has = ords >= 0
                        vals[off:off + nrow][has] = \
                            seg_maps[si][ords[has]].astype(key_dtype)
                        miss[off:off + nrow] = ~has
                else:
                    nc = seg.numerics.get(fld)
                    if nc is not None and nrow:
                        has = nc.exists[rows]
                        vals[off:off + nrow][has] = \
                            nc.raw[rows][has].astype(key_dtype)
                        miss[off:off + nrow] = ~has
                off += nrow
            lex_arrays.append((miss, np.where(miss, vals.dtype.type(0),
                                              -vals if desc else vals)))
            display.append((fld, kind))
        # np.lexsort: LAST array is the primary key -> build least-
        # significant-first: (doc, seg) tie-breaks, then key_n..key_1,
        # each key's missing flag outranking its value (missing last)
        lsb_first: list[np.ndarray] = [locals_, seg_ids]
        for miss, vals in reversed(lex_arrays):
            lsb_first.append(vals)
            lsb_first.append(miss)
        order = np.lexsort(tuple(lsb_first))
        total = int(locals_.size)
        window = order[p["from"]: p["from"] + p["size"]]

        hits = []
        for j in window:
            si = int(seg_ids[j])
            d = int(locals_[j])
            seg = self.segments[si]
            hit = {"_index": self.index_name, "_type": "_doc",
                   "_id": seg.ids[d], "_score": None}
            sort_vals = []
            for fld, kind in display:
                if kind == "kw":
                    kc = seg.keywords.get(fld)
                    sort_vals.append(
                        kc.terms[kc.ords[d]]
                        if kc is not None and kc.ords[d] >= 0 else None)
                else:
                    nc = seg.numerics.get(fld)
                    if nc is None or not nc.exists[d]:
                        sort_vals.append(None)
                    else:
                        v = nc.raw[d]
                        sort_vals.append(int(v) if nc.raw.dtype == np.int64
                                         else float(v))
            hit["sort"] = sort_vals
            if p["want_version"]:
                hit["_version"] = int(seg.versions[d])
            if p["source_filter"] is not False:
                src = filter_source(_load_source(seg.sources[d]),
                                    p["source_filter"])
                if src is not None:
                    hit["_source"] = src
            hits.append(hit)
        resp = {
            "took": int((time.monotonic() - started) * 1000),
            "timed_out": False,
            "_shards": {"total": 1, "successful": 1, "failed": 0},
            "hits": {"total": total, "max_score": None, "hits": hits},
        }
        if p["agg_specs"] or p["derived_specs"]:
            aux_body = {"query": p["raw_query"], "size": 0,
                        "aggs": body.get("aggs") or body.get("aggregations")}
            aux = self.msearch([aux_body], with_partials)[0]
            if with_partials:
                resp["_agg_partials"] = aux.get("_agg_partials", {})
            elif "aggregations" in aux:
                resp["aggregations"] = aux["aggregations"]
        return resp

    def _apply_rescore(self, resp: dict, p: dict) -> None:
        """Query rescorer over the top window (ref:
        search/rescore/QueryRescorer.java — combine original and rescore
        scores for the window docs, re-sort)."""
        spec = p["rescore"]
        window = max(spec["window_size"], p["from"] + p["size"])
        sub = self.msearch([{"query": spec["query"], "size": window,
                             "_source": False}])[0]
        re_scores = {h["_id"]: h["_score"] for h in sub["hits"]["hits"]}
        w1, w2, mode = (spec["query_weight"], spec["rescore_query_weight"],
                        spec["score_mode"])
        for h in resp["hits"]["hits"]:
            orig = h.get("_score") or 0.0
            rs = re_scores.get(h["_id"])
            if rs is None:
                h["_score"] = orig * w1
            elif mode == "multiply":
                h["_score"] = (orig * w1) * (rs * w2)
            elif mode == "avg":
                h["_score"] = (orig * w1 + rs * w2) / 2.0
            elif mode == "max":
                h["_score"] = max(orig * w1, rs * w2)
            elif mode == "min":
                h["_score"] = min(orig * w1, rs * w2)
            else:  # total
                h["_score"] = orig * w1 + rs * w2
        resp["hits"]["hits"].sort(key=lambda h: -(h["_score"] or 0.0))
        if resp["hits"]["hits"]:
            resp["hits"]["max_score"] = resp["hits"]["hits"][0]["_score"]

    def _apply_highlight(self, resp: dict, p: dict) -> None:
        for h in resp["hits"]["hits"]:
            source = h.get("_source")
            if source is None:
                seg, local = self._locate(h["_id"])
                if seg is None:
                    continue
                source = _load_source(seg.sources[local])
            hl = highlight_hit(source, p["query"], p["highlight"],
                               self.mappers)
            if hl:
                h["highlight"] = hl

    def term_stats(self, pairs: list[tuple[str, str]]
                   ) -> dict[str, tuple[int, int]]:
        """(field, term) -> (df, doc_count) summed over this shard's
        segments — the per-shard half of the DFS phase (ref:
        search/dfs/DfsPhase.java termStatistics)."""
        out: dict[str, tuple[int, int]] = {}
        for f, t in pairs:
            df = 0
            n = 0
            for seg in self.segments:
                pf = seg.text.get(f)
                if pf is not None:
                    tid = pf.lookup(str(t))
                    if tid >= 0:
                        df += int(pf.df[tid])
                    n += pf.doc_count
                    continue
                kc = seg.keywords.get(f)
                if kc is not None:
                    o = kc.lookup(str(t))
                    if o >= 0:
                        df += int(kc.df[o])
                    n += seg.num_docs
            out[f"{f}\x00{t}"] = (df, n)
        return out

    # -- parent/child joins (host-side two-pass resolution) ----------------
    # The reference resolves has_child/has_parent with per-shard parent-id
    # collectors (index/search/child/ChildrenQuery.java: collect matching
    # child docs' parent ids into a set, then filter parents). Same shape
    # here: an auxiliary device query collects one side, the ids become a
    # host-computed filter for the other side. Parent/child requires
    # children routed to the parent's shard (routing=parent), as in ES.

    JOIN_RESOLVE_WINDOW = 10_000

    def _collect_all_hits(self, query: dict) -> list[dict]:
        """All hits of an auxiliary join-resolution query. Two passes at
        most: the first learns the total, an optional second fetches
        everything in one top-k (no silent truncation, no quadratic
        re-paging)."""
        res = self.msearch([{"query": query,
                             "size": self.JOIN_RESOLVE_WINDOW,
                             "_source": False}])[0]
        total = res["hits"]["total"]
        if total <= self.JOIN_RESOLVE_WINDOW:
            return res["hits"]["hits"]
        res = self.msearch([{"query": query, "size": total,
                             "_source": False}])[0]
        return res["hits"]["hits"]

    def _join_field(self, ctx: str):
        fm = self.mappers.join_field()
        if fm is None:
            raise SearchParseError(
                f"[{ctx}] no join field is mapped on [{self.index_name}]")
        return fm

    # compound query shapes whose bodies contain QUERY nodes — join
    # resolution only recurses here, so field names like "parent_id"
    # inside term/match leaves are never misread as join queries
    _QUERY_LIST_KEYS = ("must", "should", "must_not", "filter", "queries",
                        "filters")
    _QUERY_CHILD_KEYS = ("query", "filter", "positive", "negative",
                         "no_match_query", "include", "exclude")
    _COMPOUND_NODES = ("bool", "constant_score", "filtered", "not", "and",
                       "or", "nested", "function_score", "boosting",
                       "dis_max", "indices", "_parents_match",
                       "span_multi")

    def _resolve_joins(self, q):
        """Replace has_child/has_parent/parent_id QUERY NODES (by position
        in the query tree, not by key name) with resolved id filters."""
        if not isinstance(q, dict):
            return q
        out = {}
        for name, body in q.items():
            if name == "has_child":
                out.update(self._resolve_has_child(body))
            elif name == "has_parent":
                out.update(self._resolve_has_parent(body))
            elif name == "parent_id":
                out.update(self._resolve_parent_id(body))
            elif name in self._COMPOUND_NODES and isinstance(body, dict):
                nb = dict(body)
                for k, v in body.items():
                    if k in self._QUERY_LIST_KEYS and isinstance(v, list):
                        nb[k] = [self._resolve_joins(x) for x in v]
                    elif k in self._QUERY_LIST_KEYS + self._QUERY_CHILD_KEYS \
                            and isinstance(v, dict):
                        nb[k] = self._resolve_joins(v)
                    elif k == "functions" and isinstance(v, list):
                        # function_score function entries carry a filter
                        # query each
                        nb[k] = [
                            ({**fn, "filter": self._resolve_joins(
                                fn["filter"])}
                             if isinstance(fn, dict) and
                             isinstance(fn.get("filter"), dict) else fn)
                            for fn in v]
                out[name] = nb
            elif name in ("and", "or", "dis_max") and isinstance(body, list):
                out[name] = [self._resolve_joins(x) for x in body]
            else:
                out[name] = body  # leaf query — never recurse into values
        return out

    def _join_parent_of_hit(self, doc_id: str, pcol: str) -> str | None:
        seg, local = self._locate(doc_id)
        if seg is None:
            return None
        kc = seg.keywords.get(pcol)
        if kc is None or kc.ords[local] < 0:
            return None
        return kc.terms[kc.ords[local]]

    def _resolve_has_child(self, spec: dict) -> dict:
        from collections import Counter
        fm = self._join_field("has_child")
        ctype = spec.get("type") or spec.get("child_type")
        inner = self._resolve_joins(spec.get("query") or {"match_all": {}})
        hits = self._collect_all_hits(
            {"bool": {"must": [inner],
                      "filter": [{"term": {fm.name: ctype}}]}})
        pcol = f"{fm.name}#parent"
        counts: Counter = Counter()
        for h in hits:
            pid = self._join_parent_of_hit(h["_id"], pcol)
            if pid is not None:
                counts[pid] += 1
        mn = int(spec.get("min_children", 1) or 1)
        mx = spec.get("max_children")
        ids = [p for p, c in counts.items()
               if c >= mn and (mx is None or c <= int(mx))]
        if not ids:
            return {"match_none": {}}
        return {"ids": {"values": sorted(ids)}}

    def _resolve_has_parent(self, spec: dict) -> dict:
        fm = self._join_field("has_parent")
        ptype = spec.get("parent_type") or spec.get("type")
        inner = self._resolve_joins(spec.get("query") or {"match_all": {}})
        hits = self._collect_all_hits(
            {"bool": {"must": [inner],
                      "filter": [{"term": {fm.name: ptype}}]}})
        pids = {h["_id"] for h in hits}
        if not pids:
            return {"match_none": {}}
        # children of the matched parents: vectorized membership test on
        # the parent-id ordinal column
        pcol = f"{fm.name}#parent"
        child_ids: list[str] = []
        for seg in self.segments:
            kc = seg.keywords.get(pcol)
            if kc is None:
                continue
            want = np.asarray([i for i, t in enumerate(kc.terms)
                               if t in pids], dtype=np.int32)
            if want.size == 0:
                continue
            n = seg.num_docs
            mask = (self.live[seg.seg_id][:n]
                    & np.isin(kc.ords[:n], want))
            child_ids.extend(seg.ids[d] for d in np.nonzero(mask)[0])
        if not child_ids:
            return {"match_none": {}}
        return {"ids": {"values": sorted(child_ids)}}

    def _resolve_parent_id(self, spec: dict) -> dict:
        fm = self._join_field("parent_id")
        ctype = spec.get("type")
        pid = spec.get("id")
        clauses = [{"term": {f"{fm.name}#parent": str(pid)}}]
        if ctype:
            clauses.append({"term": {fm.name: ctype}})
        return {"bool": {"filter": clauses}}

    def _locate(self, doc_id: str) -> tuple[Segment | None, int]:
        for seg in self.segments:
            d = seg.id_map.get(doc_id)
            if d is not None and self.live[seg.seg_id][d]:
                return seg, d
        return None, -1

    # -- internals ---------------------------------------------------------
    def _ords_for(self, specs: list[AggSpec]) -> dict:
        out = {}
        for s in specs:
            if s.kind in ("terms", "cardinality"):
                out[s.field] = self.global_ords(s.field)
        return out

    def _parse_request(self, body: dict) -> dict:
        body = body or {}

        def doc_lookup(doc_id: str):
            seg, local = self._locate(doc_id)
            return _load_source(seg.sources[local]) if seg is not None else None

        raw_query = body.get("query")
        if raw_query is not None and _has_join_nodes(raw_query):
            raw_query = self._resolve_joins(raw_query)
        query: Query = QueryParser(self.mappers, index_name=self.index_name,
                                   doc_lookup=doc_lookup).parse(raw_query)
        all_specs = parse_aggs(body.get("aggs") or body.get("aggregations"))
        from .aggregations import DERIVED_KINDS
        derived_specs = [s for s in all_specs if s.kind in DERIVED_KINDS]
        agg_specs = [s for s in all_specs if s.kind not in DERIVED_KINDS]
        for spec in agg_specs:
            if spec.kind in ("terms", "cardinality", "value_count"):
                spec.field = self._keyword_fallback(spec.field)
        size = int(body.get("size", 10))
        frm = int(body.get("from", 0))
        if size < 0 or frm < 0:
            raise SearchParseError("[from] and [size] must be >= 0")
        sort_spec = self._parse_sort(body.get("sort"))
        src = body.get("_source", True)
        stored_fields = body.get("fields")
        if isinstance(stored_fields, str):
            stored_fields = [stored_fields]
        if stored_fields is not None:
            # a fields list suppresses _source unless "_source" is listed
            # (ref: search/fetch/FieldsParseElement)
            if "_source" in stored_fields:
                stored_fields = [f for f in stored_fields if f != "_source"]
            elif "_source" not in body:
                src = False
        rescore = body.get("rescore")
        if rescore is not None:
            if isinstance(rescore, list):
                rescore = rescore[0] if rescore else None
        if rescore is not None:
            q = rescore.get("query") or {}
            rescore = {
                "window_size": int(rescore.get("window_size", 10)),
                "query": q.get("rescore_query"),
                "query_weight": float(q.get("query_weight", 1.0)),
                "rescore_query_weight": float(q.get("rescore_query_weight", 1.0)),
                "score_mode": str(q.get("score_mode", "total")),
            }
            if rescore["query"] is None:
                raise SearchParseError("[rescore] requires [rescore_query]")
        nested_scope = body.get("_nested_scope")
        static_sig = (
            tuple((s.name, s.kind, s.field, s.interval, s.size,
                   s.min_doc_count, s.order, s.precision,
                   tuple((m.name, m.kind, m.field) for m in s.sub_metrics))
                  for s in agg_specs),
            sort_spec, frm + size, bool(nested_scope),
        )
        return {"query": query, "agg_specs": agg_specs, "size": size,
                "from": frm, "sort_spec": sort_spec, "source_filter": src,
                "static_sig": static_sig,
                "want_version": bool(body.get("version", False)),
                "stored_fields": stored_fields,
                "rescore": rescore,
                "script_fields": self._parse_script_fields(
                    body.get("script_fields")),
                "derived_specs": derived_specs,
                "raw_query": raw_query,
                "nested_scope": nested_scope,
                "dfs_stats": body.get("_dfs_stats"),
                "reverse_ctx": body.get("_reverse_ctx"),
                "highlight": parse_highlight(body.get("highlight")),
                "suggest_specs": parse_suggest(body.get("suggest"))}

    def _parse_script_fields(self, spec) -> list:
        """script_fields (ref: search/fetch/script/ScriptFieldsParseElement)
        -> [(name, CompiledScript, params)], evaluated host-side per hit."""
        if not spec:
            return []
        from ..script import parse_script_spec, compile_script
        out = []
        for name, conf in spec.items():
            src, params = parse_script_spec(conf)
            out.append((name, compile_script(src), params))
        return out

    def _keyword_fallback(self, field: str) -> str:
        """Aggregating/sorting on a text field falls back to its .keyword
        multi-field twin when one exists (modern-ES UX; the ES 2.0
        equivalent was analyzed-string fielddata)."""
        fm = self.mappers.field(field)
        if fm is not None and fm.type == "text":
            twin = self.mappers.field(f"{field}.keyword")
            if twin is not None and twin.type == "keyword":
                return f"{field}.keyword"
        return field

    def _parse_sort(self, sort) -> tuple:
        """-> ("_score",) | ("field", name, descending, kindtag)
        | ("multi", ((name, descending, kindtag), ...)).

        Multi-key sorts take a dedicated host-lexsort path over the
        device match mask (ref: SortParseElement multi-field sort +
        Lucene FieldComparator chaining)."""
        if sort is None:
            return ("_score",)
        entries = sort if isinstance(sort, list) else [sort]
        if not entries:
            return ("_score",)
        if len(entries) > 1:
            keys = []
            for e in entries:
                if isinstance(e, str):
                    fld, order = e, "asc"
                else:
                    fld, spec = next(iter(e.items()))
                    order = (spec.get("order", "asc")
                             if isinstance(spec, dict) else str(spec))
                if fld in ("_geo_distance", "_geoDistance", "_script"):
                    raise SearchParseError(
                        f"[{fld}] is not supported in multi-key sort")
                if fld == "_score":
                    raise SearchParseError(
                        "[_score] in a multi-key sort is not supported "
                        "yet (field keys only)")
                fld = self._keyword_fallback(fld)
                kindtag = "num"
                for seg in self.segments:
                    k = seg.field_kind(fld)
                    if k == "keyword":
                        kindtag = "kw"
                    elif k == "text":
                        if seg.ensure_text_sort_column(fld):
                            self._global_ords.pop(fld, None)
                        kindtag = "kw"
                fm = self.mappers.field(fld)
                if fm is not None and fm.type == "keyword":
                    kindtag = "kw"
                keys.append((fld, str(order).lower() == "desc", kindtag))
            return ("multi", tuple(keys))
        entry = entries[0]
        if isinstance(entry, str):
            fld, order = entry, "asc"
            if fld == "_score":
                return ("_score",)
        else:
            fld, spec = next(iter(entry.items()))
            if fld == "_score":
                return ("_score",)
            if fld in ("_geo_distance", "_geoDistance"):
                # ref: search/sort/GeoDistanceSortParser.java
                from ..ops.geo import parse_geo_point, distance_unit_meters
                if not isinstance(spec, dict):
                    raise SearchParseError(
                        "[_geo_distance] sort requires an object")
                geo_field = None
                point = None
                for k, v in spec.items():
                    if k not in ("order", "unit", "mode", "distance_type",
                                 "ignore_unmapped", "nested_path"):
                        geo_field, point = k, v
                if geo_field is None:
                    raise SearchParseError(
                        "[_geo_distance] sort requires a geo_point field")
                lat, lon = parse_geo_point(point)
                unit_m = distance_unit_meters(spec.get("unit", "m"))
                order = str(spec.get("order", "asc")).lower()
                return ("field", geo_field, order == "desc", "geo",
                        lat, lon, unit_m)
            if fld == "_script":
                # script sort (ref: search/sort/ScriptSortParser.java) —
                # keys computed on-device from doc-value columns; params
                # baked into the static tag (part of the jit cache key)
                from ..script import parse_script_spec, compile_script
                from ..script.service import numeric_param
                src, sparams = parse_script_spec(spec)
                compile_script(src)
                ptag = ",".join(f"{k}={numeric_param(k, v)}"
                                for k, v in sorted(sparams.items()))
                order = str(spec.get("order", "asc")).lower() \
                    if isinstance(spec, dict) else "asc"
                return ("field", f"{src}\x00{ptag}", order == "desc",
                        "script")
            order = (spec.get("order", "asc") if isinstance(spec, dict)
                     else str(spec)).lower()
        fld = self._keyword_fallback(fld)
        kindtag = None
        for seg in self.segments:
            k = seg.field_kind(fld)
            if k == "keyword":
                kindtag = "kw"
            elif k == "numeric":
                kindtag = kindtag or "num"
            elif k == "text":
                # analyzed-string sort: min-term ordinal view (ES 2.0
                # string fielddata semantics)
                if seg.ensure_text_sort_column(fld):
                    self._global_ords.pop(fld, None)
                kindtag = "kw"
        if kindtag is None:
            fm = self.mappers.field(fld)
            if fm is None:
                # ref: SortParseElement "No mapping found for [f] in order to sort on"
                raise SearchParseError(
                    f"No mapping found for [{fld}] in order to sort on")
            kindtag = "kw" if fm.type == "keyword" else "num"
        return ("field", fld, order == "desc", kindtag)

    def _build_response(self, p: dict, seg_tops: list, b: int, aggs: dict,
                        started: float, sort_terms: list[str] | None = None) -> dict:
        is_score_sort = p["sort_spec"][0] == "_score"
        descending = True if is_score_sort else p["sort_spec"][2]
        cands = []
        total = 0
        for seg_ord, entry in enumerate(seg_tops):
            top_score, top_key, top_idx, tot, top_miss = entry[:5]
            total += int(tot[b])
            # pack-split entries (streaming delta path) carry a 6th
            # element: the per-row count of candidates that actually
            # landed in this segment's split of the merged top-k (its
            # total alone would over-read into the pad)
            n_valid = (int(entry[5][b]) if len(entry) > 5
                       else min(int(tot[b]), top_score.shape[1]))
            for j in range(n_valid):
                missing = bool(top_miss[b, j])
                cands.append((missing, float(top_key[b, j]), seg_ord,
                              int(top_idx[b, j]), float(top_score[b, j])))
        sign = -1.0 if descending else 1.0
        # missing-field docs sort last regardless of direction (ES _last)
        cands.sort(key=lambda c: (c[0], sign * c[1], c[2], c[3]))
        window = cands[p["from"]: p["from"] + p["size"]]

        hits = []
        max_score = None
        if is_score_sort and cands:
            max_score = cands[0][4] if cands[0][4] > -np.inf else None
        for missing, key, seg_ord, local_doc, score in window:
            seg = self.segments[seg_ord]
            hit = {
                "_index": self.index_name,
                "_type": "_doc",
                "_id": seg.ids[local_doc],
                "_score": score if is_score_sort else (score or None),
            }
            if not is_score_sort:
                if missing:
                    hit["sort"] = [None]
                elif sort_terms is not None:
                    hit["sort"] = [sort_terms[int(key)]]  # global ord -> term
                else:
                    hit["sort"] = [int(key) if float(key).is_integer() else key]
            if p["want_version"]:
                hit["_version"] = int(seg.versions[local_doc])
            src = p["source_filter"]
            if src is not False:
                source = _load_source(seg.sources[local_doc])
                filtered = filter_source(source, src)
                if filtered is not None:
                    hit["_source"] = filtered
            if p["stored_fields"]:
                # stored fields load from _source (all fields are
                # source-backed here; ref: FetchPhase fieldsVisitor)
                source = _load_source(seg.sources[local_doc])
                flds = {}
                for f in p["stored_fields"]:
                    v = source.get(f)
                    if v is None and "." in f:
                        # dotted path into nested objects
                        cur = source
                        for part in f.split("."):
                            cur = (cur.get(part)
                                   if isinstance(cur, dict) else None)
                            if cur is None:
                                break
                        v = cur
                    if v is not None:
                        flds[f] = v if isinstance(v, list) else [v]
                if flds:
                    hit["fields"] = flds
            if p["script_fields"]:
                from ..script import run_field_script
                sf = hit.setdefault("fields", {})
                for name, cs, sparams in p["script_fields"]:
                    val = run_field_script(cs, seg, local_doc, sparams,
                                           score=score)
                    sf[name] = [val]
            hits.append(hit)

        took = int((time.monotonic() - started) * 1000)
        resp = {
            "took": took,
            "timed_out": False,
            "_shards": {"total": 1, "successful": 1, "failed": 0},
            "hits": {"total": total, "max_score": max_score, "hits": hits},
        }
        if aggs:
            resp["aggregations"] = aggs
        return resp

    def _empty_response(self, p: dict, started: float,
                        with_partials: bool = False) -> dict:
        resp = {
            "took": int((time.monotonic() - started) * 1000),
            "timed_out": False,
            "_shards": {"total": 1, "successful": 1, "failed": 0},
            "hits": {"total": 0, "max_score": None, "hits": []},
        }
        if p["agg_specs"]:
            from .aggregations import finalize_partials
            if with_partials:
                resp["_agg_partials"] = {}
            else:
                resp["aggregations"] = finalize_partials(p["agg_specs"], {})
        return resp


def filter_source(source: dict, spec) -> dict | None:
    """_source filtering: True/False, "field", [fields], or
    {"includes": [...], "excludes": [...]} with * wildcards
    (ref: search/fetch/source/FetchSourceContext.java). The _ttl_expiry
    metadata column never surfaces (the reference keeps _ttl out of
    _source too)."""
    if isinstance(source, dict) and "_ttl_expiry" in source:
        source = {k: v for k, v in source.items() if k != "_ttl_expiry"}
    if spec is True:
        return source
    if spec is False:
        return None
    if isinstance(spec, (str, list)):
        includes = [spec] if isinstance(spec, str) else list(spec)
        excludes = []
    else:
        includes = spec.get("includes") or spec.get("include") or []
        excludes = spec.get("excludes") or spec.get("exclude") or []
        if isinstance(includes, str):
            includes = [includes]
        if isinstance(excludes, str):
            excludes = [excludes]

    import fnmatch

    def keep(path: str) -> bool:
        # an include pattern keeps the node itself, any ancestor (so the
        # walk can descend), and any descendant of a matched subtree
        if includes and not any(fnmatch.fnmatch(path, p)
                                or p.startswith(path + ".")
                                or path.startswith(p + ".")
                                for p in includes):
            return False
        if any(fnmatch.fnmatch(path, p)
               or path.startswith(p + ".") for p in excludes):
            return False
        return True

    def walk(obj: dict, prefix: str) -> dict:
        out = {}
        for k, v in obj.items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                sub = walk(v, f"{path}.")
                if sub or keep(path):
                    out[k] = sub
            elif keep(path):
                out[k] = v
        return out

    return walk(source, "")


_JOIN_NODE_KEYS = ("has_child", "has_parent", "parent_id")


def _has_join_nodes(q) -> bool:
    if isinstance(q, dict):
        return any(k in _JOIN_NODE_KEYS or _has_join_nodes(v)
                   for k, v in q.items())
    if isinstance(q, list):
        return any(_has_join_nodes(x) for x in q)
    return False


def _load_source(raw: bytes) -> dict:
    """Parse stored _source bytes; rows without source (legacy hidden
    child rows) read as an empty object."""
    if not raw:
        return {}
    return json.loads(raw)


def _default_live(seg: Segment) -> np.ndarray:
    live = np.zeros(seg.capacity, dtype=bool)
    live[: seg.num_docs] = True
    return live
